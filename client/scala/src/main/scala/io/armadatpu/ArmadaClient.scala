/*
 * Thin Scala client for the armada-tpu control plane.
 *
 * Mirrors the Python client's approach (armada_tpu/rpc/client.py): generic
 * gRPC method descriptors over the protoc-java message classes -- no
 * ScalaPB or grpc service codegen needed, only
 * `tools/genclients.sh OUT java` for the messages (armada_tpu.api.Rpc /
 * armada_tpu.events.Events), shared with client/java.
 *
 * Reference parity: client/scala/armada-scala-client
 * (io.armadaproject.armada.ArmadaClient -- submit/cancel/reprioritize/
 * queue CRUD/events over a plaintext-or-TLS channel with optional bearer
 * metadata); this client speaks the armada-tpu Submit/Event/Lookout/Reports services.
 */
package io.armadatpu

import armada_tpu.api.Rpc
import com.google.protobuf.Message
import io.grpc.{CallOptions, Channel, ClientInterceptors, ManagedChannel, ManagedChannelBuilder, Metadata, MethodDescriptor}
import io.grpc.protobuf.ProtoUtils
import io.grpc.stub.{ClientCalls, MetadataUtils}

import scala.jdk.CollectionConverters._

final class ArmadaClient private (channel: ManagedChannel, stubChannel: Channel)
    extends AutoCloseable {

  private def unary[Req <: Message, Res <: Message](
      fullName: String,
      defReq: Req,
      defRes: Res
  ): MethodDescriptor[Req, Res] =
    MethodDescriptor
      .newBuilder[Req, Res]()
      .setType(MethodDescriptor.MethodType.UNARY)
      .setFullMethodName(fullName)
      .setRequestMarshaller(ProtoUtils.marshaller(defReq))
      .setResponseMarshaller(ProtoUtils.marshaller(defRes))
      .build()

  private def call[Req <: Message, Res <: Message](
      fullName: String,
      req: Req,
      defRes: Res
  ): Res = {
    val md = unary(
      fullName,
      req.getDefaultInstanceForType.asInstanceOf[Req],
      defRes
    )
    ClientCalls.blockingUnaryCall(stubChannel, md, CallOptions.DEFAULT, req)
  }

  // --- submit surface (armada_tpu.api.Submit) ------------------------------

  def submitJobs(
      queue: String,
      jobset: String,
      items: Seq[Rpc.SubmitItem]
  ): Seq[String] =
    call(
      "armada_tpu.api.Submit/SubmitJobs",
      Rpc.SubmitJobsRequest
        .newBuilder()
        .setQueue(queue)
        .setJobset(jobset)
        .addAllItems(items.asJava)
        .build(),
      Rpc.SubmitJobsResponse.getDefaultInstance
    ).getJobIdsList.asScala.toSeq

  def cancelJobs(
      queue: String,
      jobset: String,
      jobIds: Seq[String],
      reason: String = ""
  ): Unit =
    call(
      "armada_tpu.api.Submit/CancelJobs",
      Rpc.CancelJobsRequest
        .newBuilder()
        .setQueue(queue)
        .setJobset(jobset)
        .addAllJobIds(jobIds.asJava)
        .setReason(reason)
        .build(),
      Rpc.Empty.getDefaultInstance
    )

  def cancelJobSet(queue: String, jobset: String): Unit =
    call(
      "armada_tpu.api.Submit/CancelJobSet",
      Rpc.CancelJobSetRequest
        .newBuilder()
        .setQueue(queue)
        .setJobset(jobset)
        .build(),
      Rpc.Empty.getDefaultInstance
    )

  def preemptJobs(
      queue: String,
      jobset: String,
      jobIds: Seq[String],
      reason: String = ""
  ): Unit =
    call(
      "armada_tpu.api.Submit/PreemptJobs",
      Rpc.PreemptJobsRequest
        .newBuilder()
        .setQueue(queue)
        .setJobset(jobset)
        .addAllJobIds(jobIds.asJava)
        .setReason(reason)
        .build(),
      Rpc.Empty.getDefaultInstance
    )

  def reprioritizeJobs(
      queue: String,
      jobset: String,
      priority: Long,
      jobIds: Seq[String]
  ): Unit =
    call(
      "armada_tpu.api.Submit/ReprioritizeJobs",
      Rpc.ReprioritizeJobsRequest
        .newBuilder()
        .setQueue(queue)
        .setJobset(jobset)
        .setPriority(priority)
        .addAllJobIds(jobIds.asJava)
        .build(),
      Rpc.Empty.getDefaultInstance
    )

  def createQueue(queue: Rpc.Queue): Unit =
    call(
      "armada_tpu.api.Submit/CreateQueue",
      queue,
      Rpc.Empty.getDefaultInstance
    )

  def listQueues(): Seq[Rpc.Queue] =
    call(
      "armada_tpu.api.Submit/ListQueues",
      Rpc.Empty.getDefaultInstance,
      Rpc.QueueListResponse.getDefaultInstance
    ).getQueuesList.asScala.toSeq

  // --- lookout surface (armada_tpu.api.Lookout: JSON-over-gRPC) ------------

  /** Filtered job page; `queryJson` is the lookout query document. */
  def getJobs(queryJson: String): String =
    call(
      "armada_tpu.api.Lookout/GetJobs",
      Rpc.LookoutQuery.newBuilder.setQueryJson(queryJson).build,
      Rpc.JsonResponse.getDefaultInstance
    ).getJson

  def groupJobs(queryJson: String): String =
    call(
      "armada_tpu.api.Lookout/GroupJobs",
      Rpc.LookoutQuery.newBuilder.setQueryJson(queryJson).build,
      Rpc.JsonResponse.getDefaultInstance
    ).getJson

  /** Full job details (spec fields, runs, errors, ingress addresses). */
  def getJobDetails(jobId: String): String =
    call(
      "armada_tpu.api.Lookout/GetJobDetails",
      Rpc.QueueGetRequest.newBuilder.setName(jobId).build,
      Rpc.JsonResponse.getDefaultInstance
    ).getJson

  // --- scheduling reports (armada_tpu.api.Reports; followers proxy to the
  // leader, UNAVAILABLE is retryable) ----------------------------------------

  def getJobReport(jobId: String): String =
    call(
      "armada_tpu.api.Reports/GetJobReport",
      Rpc.QueueGetRequest.newBuilder.setName(jobId).build,
      Rpc.JsonResponse.getDefaultInstance
    ).getJson

  def getQueueReport(queue: String): String =
    call(
      "armada_tpu.api.Reports/GetQueueReport",
      Rpc.QueueGetRequest.newBuilder.setName(queue).build,
      Rpc.JsonResponse.getDefaultInstance
    ).getJson

  /** Pool scheduling report; "" = every pool. */
  def getPoolReport(pool: String): String =
    call(
      "armada_tpu.api.Reports/GetPoolReport",
      Rpc.QueueGetRequest.newBuilder.setName(pool).build,
      Rpc.JsonResponse.getDefaultInstance
    ).getJson

  // --- event surface (armada_tpu.api.Event) --------------------------------

  /** Stream jobset events from `fromIdx`; `watch` keeps the stream open for
    * new events (`idleTimeoutS` without progress ends it).  Each message's
    * `idx` is the resume cursor to persist.
    */
  def watch(
      queue: String,
      jobset: String,
      fromIdx: Long = 0,
      watch: Boolean = false,
      idleTimeoutS: Double = 0.0
  ): Iterator[Rpc.JobSetEventMessage] = {
    val md = MethodDescriptor
      .newBuilder[Rpc.JobSetEventsRequest, Rpc.JobSetEventMessage]()
      .setType(MethodDescriptor.MethodType.SERVER_STREAMING)
      .setFullMethodName("armada_tpu.api.Event/GetJobSetEvents")
      .setRequestMarshaller(
        ProtoUtils.marshaller(Rpc.JobSetEventsRequest.getDefaultInstance)
      )
      .setResponseMarshaller(
        ProtoUtils.marshaller(Rpc.JobSetEventMessage.getDefaultInstance)
      )
      .build()
    val req = Rpc.JobSetEventsRequest
      .newBuilder()
      .setQueue(queue)
      .setJobset(jobset)
      .setFromIdx(fromIdx)
      .setWatch(watch)
      .setIdleTimeoutS(idleTimeoutS)
      .build()
    ClientCalls
      .blockingServerStreamingCall(stubChannel, md, CallOptions.DEFAULT, req)
      .asScala
  }

  override def close(): Unit = { channel.shutdown(); () }
}

object ArmadaClient {

  /** Channel with the x-armada-principal trusted header (dev auth chains);
    * use `withBearer` for OIDC/token-review chains.  `useTls` turns on
    * transport security (the reference client's grpcs:// / useSsl mode) --
    * required before sending credentials across untrusted networks.
    */
  def apply(
      target: String,
      principal: String = "anonymous",
      useTls: Boolean = false
  ): ArmadaClient = {
    val md = new Metadata()
    md.put(
      Metadata.Key.of("x-armada-principal", Metadata.ASCII_STRING_MARSHALLER),
      principal
    )
    build(target, md, useTls)
  }

  /** The same client with an Authorization: Bearer header (server authn).
    * Defaults to TLS: bearer tokens must not ride cleartext channels
    * (plaintext only for localhost development).
    */
  def withBearer(
      target: String,
      token: String,
      useTls: Boolean = true
  ): ArmadaClient = {
    val md = new Metadata()
    md.put(
      Metadata.Key.of("authorization", Metadata.ASCII_STRING_MARSHALLER),
      "Bearer " + token
    )
    build(target, md, useTls)
  }

  private def build(
      target: String,
      md: Metadata,
      useTls: Boolean
  ): ArmadaClient = {
    val builder = ManagedChannelBuilder.forTarget(target)
    val channel =
      (if (useTls) builder.useTransportSecurity() else builder.usePlaintext())
        .build()
    new ArmadaClient(
      channel,
      ClientInterceptors.intercept(
        channel,
        MetadataUtils.newAttachHeadersInterceptor(md)
      )
    )
  }
}
