"""Mesh construction + sharding specs for the scheduling round.

Sharding layout (SURVEY.md section 7 "Tensor reformulation" / section 2.8):

- axis ``nodes``: node-dimension tensors (node_total[N,R], node_type[N],
  node_ok[N], and the alloc[P1,N,R] carry) are sharded -- the 50k-node pool is
  split across devices, so per-node fit masks, member capacities and packing
  scores are computed locally and the best-fit argmin is a cross-device
  reduction that XLA lowers onto ICI.
- axis ``jobs``: gang- and run-dimension tensors (g_req[G,R], g_order[G], ...,
  run_req[RJ,R], ...) are sharded -- the 1M-gang backlog is split, and the
  per-queue segment-min candidate scan reduces across devices.
- queue/pool tensors ([Q], [Q,R], [R], scalars) are replicated: Q is small
  (thousands at most) and every device needs the full fairness state.

The round kernel (models/fair_scheduler.py schedule_round) is reused unchanged:
`sharded_schedule_round` jits it with these shardings; GSPMD partitions the
while-loop body.  This mirrors how the reference runs ONE logical round over a
whole executor fleet's nodes (scheduling_algo.go:126-186) -- the parallelism is
inside the round, not across rounds.
"""

from __future__ import annotations

import functools
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from armada_tpu.models.fair_scheduler import schedule_round
from armada_tpu.models.problem import SchedulingProblem

AXIS_NODES = "nodes"
AXIS_JOBS = "jobs"


def make_mesh(
    devices: Optional[Sequence] = None,
    *,
    node_shards: Optional[int] = None,
    job_shards: int = 1,
) -> Mesh:
    """A 2D (nodes x jobs) device mesh.

    Defaults to all visible devices on the ``nodes`` axis: node count (50k)
    dwarfs everything else in the fit/score inner product, so that is the axis
    whose sharding buys HBM locality.  ``job_shards`` > 1 splits the backlog
    scan as well (use for very deep queues).
    """
    if devices is None:
        devices = jax.devices()
    devices = np.asarray(devices)
    n = devices.size
    if node_shards is None:
        node_shards = n // job_shards
    if node_shards * job_shards != n:
        raise ValueError(
            f"mesh {node_shards}x{job_shards} != {n} devices"
        )
    return Mesh(devices.reshape(node_shards, job_shards), (AXIS_NODES, AXIS_JOBS))


def problem_shardings(mesh: Mesh) -> SchedulingProblem:
    """A SchedulingProblem pytree of NamedShardings matching its field layout."""

    def s(*spec):
        return NamedSharding(mesh, P(*spec))

    nodes = s(AXIS_NODES)
    nodes_r = s(AXIS_NODES, None)
    jobsax = s(AXIS_JOBS)
    jobs_r = s(AXIS_JOBS, None)
    repl = s()
    return SchedulingProblem(
        node_total=nodes_r,
        node_type=nodes,
        node_ok=nodes,
        run_req=jobs_r,
        run_node=jobsax,
        run_level=jobsax,
        run_queue=jobsax,
        run_pc=jobsax,
        run_preemptible=jobsax,
        run_gang=jobsax,
        run_valid=jobsax,
        g_req=jobs_r,
        g_card=jobsax,
        g_level=jobsax,
        g_queue=jobsax,
        g_key=jobsax,
        g_pc=jobsax,
        g_order=jobsax,
        g_run=jobsax,
        g_valid=jobsax,
        g_absent=jobsax,
        g_price=jobsax,
        g_spot_price=jobsax,
        # gq_gang is read-only index data gathered with [Q,W] indices every
        # iteration; replicated so the gather never crosses devices.
        gq_gang=repl,
        q_start=repl,
        q_len=repl,
        q_weight=repl,
        q_cds=repl,
        q_penalty=repl,
        compat=repl,
        total_pool=repl,
        drf_mult=repl,
        inv_scale=repl,
        round_cap=repl,
        pc_queue_cap=repl,
        protected_fraction=repl,
        global_burst=repl,
        perq_burst=repl,
        node_axes=repl,
        float_total=repl,
        market=repl,
        spot_cutoff=repl,
        # ban rows follow the node axis; the row-index vector follows gangs
        ban_mask=s(None, AXIS_NODES),
        g_ban_row=jobsax,
    )


def _check_divisible(problem: SchedulingProblem, mesh: Mesh) -> None:
    n_shards = mesh.shape[AXIS_NODES]
    j_shards = mesh.shape[AXIS_JOBS]
    N = problem.node_total.shape[0]
    G = problem.g_req.shape[0]
    RJ = problem.run_req.shape[0]
    for size, shards, name in ((N, n_shards, "nodes"), (G, j_shards, "gangs"), (RJ, j_shards, "runs")):
        if size % shards:
            raise ValueError(
                f"{name} axis {size} not divisible by its {shards} mesh shards; "
                f"raise SchedulingConfig.shape_bucket to a multiple of the mesh"
            )


def shard_problem(problem: SchedulingProblem, mesh: Mesh) -> SchedulingProblem:
    """Place a (host or device) problem onto the mesh with the round shardings."""
    _check_divisible(problem, mesh)
    shardings = problem_shardings(mesh)
    return SchedulingProblem(
        *(jax.device_put(a, sh) for a, sh in zip(problem, shardings))
    )


@functools.partial(
    jax.jit,
    static_argnames=("mesh", "num_levels", "max_slots", "slot_width", "max_iterations"),
)
def _sharded_round(problem, *, mesh, num_levels, max_slots, slot_width, max_iterations):
    # Inputs arrive pre-sharded (shard_problem); jit propagates their shardings
    # through the while-loop and GSPMD inserts the collectives.  Outputs are
    # pulled back replicated: everything the host decodes is small ([S,W] slots,
    # [G] states, [RJ] flags) except alloc, which callers feeding the next round
    # re-shard anyway.
    return schedule_round(
        problem,
        num_levels=num_levels,
        max_slots=max_slots,
        slot_width=slot_width,
        max_iterations=max_iterations,
    )


def sharded_schedule_round(
    problem: SchedulingProblem,
    mesh: Mesh,
    *,
    num_levels: int,
    max_slots: int,
    slot_width: int,
    max_iterations: int = 0,
):
    """Run one scheduling round SPMD over the mesh.

    Equivalent single-device call: models.schedule_round.  Results are
    numerically identical (the kernel is deterministic and sharding only
    distributes the reductions).
    """
    problem = shard_problem(problem, mesh)
    with mesh:
        return _sharded_round(
            problem,
            mesh=mesh,
            num_levels=num_levels,
            max_slots=max_slots,
            slot_width=slot_width,
            max_iterations=max_iterations,
        )
