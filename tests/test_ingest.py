"""Scheduler ingestion: dbops merge/reorder, converter, pipeline exactly-once.

Models the reference's scheduleringester tests (dbops merge + reorder
legality, instructions.go conversion, schedulerdb storage with serials).
"""

import pytest

from armada_tpu.eventlog import EventLog, Publisher
from armada_tpu.events import events_pb2 as pb
from armada_tpu.ingest import (
    SchedulerDb,
    convert_sequences,
    scheduler_ingestion_pipeline,
)
from armada_tpu.ingest import dbops as ops


def seq(queue="q", jobset="js", events=()):
    return pb.EventSequence(queue=queue, jobset=jobset, events=list(events))


def submit(job_id, priority=0):
    return pb.Event(
        created_ns=1,
        submit_job=pb.SubmitJob(job_id=job_id, spec=pb.JobSpec(priority=priority)),
    )


# --- dbops ------------------------------------------------------------------


def test_same_type_ops_merge():
    merged = ops.merge_ops(
        [
            ops.MarkJobsSucceeded(job_ids={"a"}),
            ops.MarkJobsSucceeded(job_ids={"b"}),
        ]
    )
    assert len(merged) == 1
    assert merged[0].job_ids == {"a", "b"}


def test_independent_ops_hoist_past_each_other():
    # succeeded(a), cancel(b), succeeded(c): the second succeeded op touches
    # only c, commutes with cancel(b), and merges into the first.
    merged = ops.merge_ops(
        [
            ops.MarkJobsSucceeded(job_ids={"a"}),
            ops.MarkJobsCancelRequested(job_ids={"b"}),
            ops.MarkJobsSucceeded(job_ids={"c"}),
        ]
    )
    assert len(merged) == 2
    assert merged[0].job_ids == {"a", "c"}


def test_conflicting_ops_do_not_reorder():
    # cancel(a) then succeeded(a) must stay ordered; a later succeeded(a)
    # cannot hoist past cancel(a).
    merged = ops.merge_ops(
        [
            ops.MarkJobsSucceeded(job_ids={"x"}),
            ops.MarkJobsCancelRequested(job_ids={"a"}),
            ops.MarkJobsSucceeded(job_ids={"a"}),
        ]
    )
    assert len(merged) == 3
    assert isinstance(merged[1], ops.MarkJobsCancelRequested)


def test_jobset_wildcard_blocks_reordering():
    merged = ops.merge_ops(
        [
            ops.MarkJobsSucceeded(job_ids={"a"}),
            ops.MarkJobSetCancelRequested(queue="q", jobset="js"),
            ops.MarkJobsSucceeded(job_ids={"b"}),
        ]
    )
    assert len(merged) == 3  # nothing crosses the jobset-wide op


def test_queued_state_merge_keeps_newest_version():
    op1 = ops.UpdateJobQueuedState(state_by_job={"j": (False, 3)})
    op1.merge(ops.UpdateJobQueuedState(state_by_job={"j": (True, 2)}))
    assert op1.state_by_job["j"] == (False, 3)  # stale version ignored
    op1.merge(ops.UpdateJobQueuedState(state_by_job={"j": (True, 4)}))
    assert op1.state_by_job["j"] == (True, 4)


# --- converter --------------------------------------------------------------


def test_convert_submit_and_lifecycle():
    events = [
        submit("j1", priority=3),
        pb.Event(job_validated=pb.JobValidated(job_id="j1", pools=["default"])),
        pb.Event(
            job_run_leased=pb.JobRunLeased(
                job_id="j1", run_id="r1", executor_id="e1", node_id="n1",
                pool="default", scheduled_at_priority=1000,
            )
        ),
        pb.Event(job_run_running=pb.JobRunRunning(job_id="j1", run_id="r1")),
        pb.Event(job_run_succeeded=pb.JobRunSucceeded(job_id="j1", run_id="r1")),
        pb.Event(job_succeeded=pb.JobSucceeded(job_id="j1")),
    ]
    out = convert_sequences([seq(events=events)])
    kinds = [type(o).__name__ for o in out]
    assert "InsertJobs" in kinds and "InsertRuns" in kinds
    assert "MarkRunsSucceeded" in kinds and "MarkJobsSucceeded" in kinds


def test_convert_terminal_run_error_also_fails_run():
    events = [
        pb.Event(
            job_run_errors=pb.JobRunErrors(
                job_id="j1", run_id="r1",
                errors=[pb.Error(reason="oom", message="killed", terminal=True)],
            )
        )
    ]
    out = convert_sequences([seq(events=events)])
    kinds = {type(o).__name__ for o in out}
    assert kinds == {"InsertJobRunErrors", "MarkRunsFailed"}


# --- schedulerdb + pipeline -------------------------------------------------


def test_store_and_fetch_updates():
    db = SchedulerDb()
    db.store(convert_sequences([seq(events=[submit("j1"), submit("j2")])]))
    jobs, runs = db.fetch_job_updates(0, 0)
    assert {r["job_id"] for r in jobs} == {"j1", "j2"}
    assert runs == []
    js, rs = db.max_serials()
    # Incremental: no new rows past the cursor.
    jobs2, _ = db.fetch_job_updates(js, rs)
    assert jobs2 == []
    # A lifecycle update bumps the serial past the cursor.
    db.store(
        convert_sequences(
            [seq(events=[pb.Event(job_succeeded=pb.JobSucceeded(job_id="j1"))])]
        )
    )
    jobs3, _ = db.fetch_job_updates(js, rs)
    assert [r["job_id"] for r in jobs3] == ["j1"]
    assert jobs3[0]["succeeded"] == 1 and jobs3[0]["queued"] == 0


def test_jobset_cancel_only_touches_jobset():
    db = SchedulerDb()
    db.store(
        convert_sequences(
            [
                seq(jobset="js-a", events=[submit("a1"), submit("a2")]),
                seq(jobset="js-b", events=[submit("b1")]),
            ]
        )
    )
    db.store(
        convert_sequences(
            [seq(jobset="js-a", events=[pb.Event(cancel_job_set=pb.CancelJobSet())])]
        )
    )
    jobs, _ = db.fetch_job_updates(0, 0)
    flags = {r["job_id"]: r["cancel_by_jobset_requested"] for r in jobs}
    assert flags == {"a1": 1, "a2": 1, "b1": 0}


def test_pipeline_end_to_end_and_restart_resume(tmp_path):
    log_dir = str(tmp_path / "log")
    db_path = str(tmp_path / "scheduler.db")
    with EventLog(log_dir, num_partitions=2) as log:
        publisher = Publisher(log)
        publisher.publish([seq(events=[submit("j1")])])
        db = SchedulerDb(db_path)
        pipeline = scheduler_ingestion_pipeline(log, db)
        assert pipeline.run_until_caught_up() == 1
        jobs, _ = db.fetch_job_updates(0, 0)
        assert [r["job_id"] for r in jobs] == ["j1"]
        # Re-running applies nothing new (positions persisted).
        assert pipeline.run_until_caught_up() == 0
        db.close()
        # Simulate restart: fresh pipeline from stored positions must not
        # re-apply j1 but must pick up a new event.
        publisher.publish([seq(events=[submit("j2")])])
        db2 = SchedulerDb(db_path)
        pipeline2 = scheduler_ingestion_pipeline(log, db2)
        assert pipeline2.run_until_caught_up() == 1
        jobs, _ = db2.fetch_job_updates(0, 0)
        assert {r["job_id"] for r in jobs} == {"j1", "j2"}
        db2.close()


def test_marker_roundtrip_through_pipeline(tmp_path):
    with EventLog(str(tmp_path / "log"), num_partitions=3) as log:
        publisher = Publisher(log)
        group = publisher.publish_markers()
        db = SchedulerDb()
        pipeline = scheduler_ingestion_pipeline(log, db)
        pipeline.run_until_caught_up()
        assert db.has_marker(group, num_partitions=3)
        assert not db.has_marker("other-group", num_partitions=3)


def test_duplicate_submit_is_idempotent():
    db = SchedulerDb()
    batch = convert_sequences([seq(events=[submit("j1")])])
    db.store(batch)
    db.store(batch)  # replay (at-least-once delivery)
    jobs, _ = db.fetch_job_updates(0, 0)
    assert len(jobs) == 1
