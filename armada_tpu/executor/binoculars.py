"""Binoculars-lite: pod log retrieval + node cordon, next to the cluster.

Equivalent of the reference's binoculars service (internal/binoculars:
logs.go:39-43 reads pod logs straight from kube-api, cordon.go patches node
schedulability) -- deployed per cluster beside the executor, NOT behind the
control plane, because logs/cordon are cluster-local concerns.  Here it wraps
the executor's ClusterContext; the gRPC surface lives in armada_tpu.rpc.
"""

from __future__ import annotations

from typing import Optional

from armada_tpu.executor.cluster import ClusterContext


class Binoculars:
    def __init__(
        self,
        cluster: ClusterContext,
        cordon_labels: Optional[dict] = None,
    ):
        """cordon_labels: audit labels applied with every cordon, with
        `<user>` in keys/values replaced by the caller's principal (the
        reference's CordonConfiguration.AdditionalLabels + templateLabels,
        cordon.go:23,63-71)."""
        self._cluster = cluster
        self._cordon_labels = dict(cordon_labels or {})

    def logs(self, job_id: str = "", run_id: str = "") -> str:
        """Log text of the job's (latest) pod; raises KeyError if unknown."""
        if run_id:
            return self._cluster.pod_logs(run_id)
        if not job_id:
            raise KeyError("job_id or run_id required")
        pods = [p for p in self._cluster.pod_states() if p.job_id == job_id]
        if not pods:
            raise KeyError(f"no pod for job {job_id}")
        return self._cluster.pod_logs(pods[-1].run_id)

    def cordon(
        self, node_id: str, cordoned: bool = True, user: str = ""
    ) -> None:
        labels = {
            k.replace("<user>", user): v.replace("<user>", user)
            for k, v in self._cordon_labels.items()
        }
        if labels and cordoned:
            self._cluster.cordon_node(node_id, cordoned, labels=labels)
        else:
            self._cluster.cordon_node(node_id, cordoned)
