"""ArmadaOperator (third_party/airflow equivalent) against a live control
plane over gRPC, without Airflow installed (the gated-import path)."""

import threading

import pytest

from armada_tpu.cli.serve import run_fake_executor, start_control_plane
from armada_tpu.core.config import SchedulingConfig
from armada_tpu.integrations.airflow import AirflowException, ArmadaOperator
from armada_tpu.rpc.client import ArmadaClient
from armada_tpu.server.queues import QueueRecord


@pytest.fixture
def plane(tmp_path):
    p = start_control_plane(
        str(tmp_path / "data"),
        config=SchedulingConfig(shape_bucket=32),
        cycle_interval_s=0.05,
        schedule_interval_s=0.1,
    )
    client = ArmadaClient(f"127.0.0.1:{p.port}")
    client.create_queue(QueueRecord("af"))
    client.close()
    yield p
    p.stop()


def agent(plane, runtime_s=0.2):
    stop = threading.Event()
    t = threading.Thread(
        target=run_fake_executor,
        args=(f"127.0.0.1:{plane.port}",),
        kwargs={
            "interval_s": 0.05,
            "stop": stop,
            "default_runtime_s": runtime_s,
            "config": SchedulingConfig(shape_bucket=32),
        },
        daemon=True,
    )
    t.start()
    return stop, t


def test_operator_runs_job_to_success(plane):
    stop, t = agent(plane)
    try:
        op = ArmadaOperator(
            task_id="sim",
            armada_url=f"127.0.0.1:{plane.port}",
            queue="af",
            job={"resources": {"cpu": "2", "memory": "1"}},
            poll_interval_s=0.2,
            timeout_s=30,
        )
        job_id = op.execute()
        assert job_id and op.jobset == "sim"
    finally:
        stop.set()
        t.join(timeout=5)


def test_operator_raises_on_unschedulable_failure(plane):
    stop, t = agent(plane)
    try:
        op = ArmadaOperator(
            task_id="toolarge",
            armada_url=f"127.0.0.1:{plane.port}",
            queue="af",
            # larger than any fake node: the submit check fails it terminally
            job={"resources": {"cpu": "9999", "memory": "1"}},
            poll_interval_s=0.2,
            timeout_s=30,
        )
        with pytest.raises(AirflowException, match="failed"):
            op.execute()
    finally:
        stop.set()
        t.join(timeout=5)


def test_on_kill_cancels_the_job(plane):
    # No executor: the job stays queued; on_kill cancels it.
    op = ArmadaOperator(
        task_id="killme",
        armada_url=f"127.0.0.1:{plane.port}",
        queue="af",
        job={"resources": {"cpu": "1", "memory": "1"}, "priorityClass": ""},
        poll_interval_s=0.1,
        timeout_s=2,
    )
    with pytest.raises(AirflowException, match="timed out"):
        op.execute()
    assert op.job_id is not None
    op.on_kill()
    # the cancellation lands as a cancelled_job event
    client = ArmadaClient(f"127.0.0.1:{plane.port}")
    try:
        import time

        deadline = time.time() + 10
        cancelled = False
        while time.time() < deadline and not cancelled:
            for _, seq in client.get_jobset_events("af", "killme"):
                for ev in seq.events:
                    if ev.WhichOneof("event") == "cancelled_job":
                        cancelled = True
        assert cancelled
    finally:
        client.close()


def test_camel_case_job_keys_accepted():
    op = ArmadaOperator(
        task_id="x",
        armada_url="localhost:1",
        queue="q",
        job={
            "resources": {"cpu": "1"},
            "priorityClassName": "armada-default",
            "nodeSelector": {"zone": "a"},
            "gangCardinality": 2,
        },
    )
    from armada_tpu.integrations.airflow import _snake_item

    item = _snake_item(op.job)
    assert item["priority_class"] == "armada-default"
    assert item["node_selector"] == {"zone": "a"}
    assert item["gang_cardinality"] == 2


def run_deferred(op, context=None):
    """Drive the deferrable flow the way Airflow's triggerer would: catch
    TaskDeferred, round-trip the trigger through serialize() (Airflow
    persists deferred triggers that way), run it to its one TriggerEvent,
    then resume the operator with it."""
    import asyncio
    import importlib

    from armada_tpu.integrations.airflow import TaskDeferred

    try:
        op.execute(context)
    except TaskDeferred as d:
        classpath, kwargs = d.trigger.serialize()
        mod, cls = classpath.rsplit(".", 1)
        trigger = getattr(importlib.import_module(mod), cls)(**kwargs)

        async def first_event():
            async for ev in trigger.run():
                return ev

        event = asyncio.run(first_event())
        return getattr(op, d.method_name)(context, event)
    raise AssertionError("deferrable execute() must raise TaskDeferred")


def test_deferrable_operator_success(plane):
    stop, t = agent(plane)
    try:
        op = ArmadaOperator(
            task_id="defer-ok",
            armada_url=f"127.0.0.1:{plane.port}",
            queue="af",
            job={"resources": {"cpu": "2", "memory": "1"}},
            poll_interval_s=0.2,
            timeout_s=30,
            deferrable=True,
        )
        job_id = run_deferred(op)
        assert job_id == op.job_id and job_id
    finally:
        stop.set()
        t.join(timeout=5)


def test_deferrable_operator_failure_raises_on_resume(plane):
    stop, t = agent(plane)
    try:
        op = ArmadaOperator(
            task_id="defer-fail",
            armada_url=f"127.0.0.1:{plane.port}",
            queue="af",
            job={"resources": {"cpu": "9999", "memory": "1"}},
            poll_interval_s=0.2,
            timeout_s=30,
            deferrable=True,
        )
        with pytest.raises(AirflowException, match="failed"):
            run_deferred(op)
    finally:
        stop.set()
        t.join(timeout=5)


def test_deferrable_timeout_cancels_and_raises(plane):
    # No executor: the job never runs; the trigger times out, resume()
    # cancels the job (parity with the blocking path's deadline) and raises.
    op = ArmadaOperator(
        task_id="defer-timeout",
        armada_url=f"127.0.0.1:{plane.port}",
        queue="af",
        job={"resources": {"cpu": "1", "memory": "1"}},
        poll_interval_s=0.1,
        timeout_s=1,
        deferrable=True,
    )
    with pytest.raises(AirflowException, match="timed out"):
        run_deferred(op)
    client = ArmadaClient(f"127.0.0.1:{plane.port}")
    try:
        import time

        deadline = time.time() + 10
        cancelled = False
        while time.time() < deadline and not cancelled:
            for _, seq in client.get_jobset_events("af", "defer-timeout"):
                for ev in seq.events:
                    if ev.WhichOneof("event") == "cancelled_job":
                        cancelled = True
        assert cancelled
    finally:
        client.close()


def test_deferred_trigger_cancellation_cancels_the_job(plane):
    """Killing a DEFERRED task cancels the trigger's asyncio task -- the
    only teardown signal a deferred operator gets.  The trigger must cancel
    the armada job on its way out (blocking mode's on_kill contract), or
    the job runs on-cluster forever."""
    import asyncio

    from armada_tpu.integrations.airflow import (
        ArmadaPollJobTrigger,
        TaskDeferred,
    )

    op = ArmadaOperator(
        task_id="defer-killed",
        armada_url=f"127.0.0.1:{plane.port}",
        queue="af",
        job={"resources": {"cpu": "1", "memory": "1"}},
        poll_interval_s=0.1,
        deferrable=True,
    )
    with pytest.raises(TaskDeferred) as deferred:
        op.execute()
    trigger = deferred.value.trigger
    assert isinstance(trigger, ArmadaPollJobTrigger)

    async def run_then_kill():
        gen = trigger.run()
        task = asyncio.ensure_future(gen.__anext__())
        await asyncio.sleep(0.3)  # let it start polling
        task.cancel()
        with pytest.raises(asyncio.CancelledError):
            await task

    asyncio.run(run_then_kill())
    client = ArmadaClient(f"127.0.0.1:{plane.port}")
    try:
        import time

        deadline = time.time() + 10
        cancelled = False
        while time.time() < deadline and not cancelled:
            for _, seq in client.get_jobset_events("af", "defer-killed"):
                for ev in seq.events:
                    if ev.WhichOneof("event") == "cancelled_job":
                        cancelled = True
        assert cancelled
    finally:
        client.close()
