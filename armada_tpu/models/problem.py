"""Building the dense scheduling problem and decoding round results.

This is the host<->device boundary of the scheduling round: the equivalent of the
reference's per-pool context construction (scheduling_algo.go
newFairSchedulingAlgoContext:201, constructNodeDb:467, constructSchedulingContext:486)
-- except the output is a pytree of padded tensors instead of a NodeDb + context tree.

Layout conventions (see SURVEY.md section 7 "Tensor reformulation"):
- R: fixed resource axis (resolution units, integral float32).
- P levels: priority ladder index; level 0 is reserved for the *evicted* marker
  priority (the reference's internaltypes.EvictedPriority = -1): resources of evicted
  jobs stay counted at level 0 so clean fit ("schedule without preemption",
  nodedb.go:506-514) sees them, while fit at a real priority does not.  Level 1 is
  reserved for *away* placements (jobs borrowed onto another pool's nodes,
  scheduling_algo.go:216-283): below every real priority, so any home job can
  urgency-preempt them; real PC priorities occupy levels 2 and up.
- Gangs are the unit of scheduling; a plain job is a gang of cardinality 1.  Every
  *preemptible running job* also gets a gang slot (its "evictee" re-scheduling
  candidate, pinned to its node), activated in-kernel only if the job is actually
  evicted -- mirroring how evicted jobs re-enter the queue scheduler ahead of queued
  jobs (preempting_queue_scheduler.go evict -> InMemoryJobRepository; jobs pinned
  via node-id selector).
- All axes are padded to `config.shape_bucket` multiples so jit recompiles only when
  a bucket boundary is crossed.
"""

from __future__ import annotations

import dataclasses
import os
from typing import NamedTuple, Optional, Sequence

import numpy as np

from armada_tpu.core.config import SchedulingConfig
from armada_tpu.core.ordering import scheduling_order_key
from armada_tpu.core.keys import (
    NodeTypeIndex,
    SchedulingKeyIndex,
    class_signature,
    labels_referenced_by_selectors,
    static_fit_matrix,
    type_score_tables,
)
from armada_tpu.core.types import JobSpec, NodeSpec, Queue, RunningJob

_INF = np.float32(3.0e38)


class SchedulingProblem(NamedTuple):
    """Dense per-round problem; every field is a device-ready array."""

    # nodes
    node_total: np.ndarray  # f32[N, R] allocatable units
    node_type: np.ndarray  # i32[N]
    node_ok: np.ndarray  # bool[N] real & schedulable
    # running jobs
    run_req: np.ndarray  # f32[RJ, R]
    run_node: np.ndarray  # i32[RJ]
    run_level: np.ndarray  # i32[RJ] ladder level (>= 1)
    run_queue: np.ndarray  # i32[RJ]
    run_pc: np.ndarray  # i32[RJ] priority-class index
    run_preemptible: np.ndarray  # bool[RJ]
    run_gang: np.ndarray  # i32[RJ] evictee gang slot (-1 if not preemptible)
    run_valid: np.ndarray  # bool[RJ]
    # gangs (queued jobs + evictee slots)
    g_req: np.ndarray  # f32[G, R] per-member request
    g_card: np.ndarray  # i32[G]
    g_level: np.ndarray  # i32[G] ladder level (>= 1)
    g_queue: np.ndarray  # i32[G]
    g_key: np.ndarray  # i32[G] scheduling key (-1 for evictee slots)
    g_pc: np.ndarray  # i32[G]
    g_order: np.ndarray  # i32[G] rank within its queue (evictees first)
    g_run: np.ndarray  # i32[G] backing run for evictee slots, else -1
    g_valid: np.ndarray  # bool[G]
    # Slot not part of THIS cycle's problem (slab free-list holes, jobs beyond
    # the queue lookback, slack regions): the kernel marks these state 3
    # (absent) instead of 2 (failed) so decode never reports them.  All-False
    # under the legacy dense builders, whose padding is sliced off by
    # num_real_gangs instead.
    g_absent: np.ndarray  # bool[G]
    g_price: np.ndarray  # f32[G] bid price (market pools; 0 otherwise)
    # Minimum member bid: the spot price a crossing gang publishes
    # (queue_scheduler.go:138-144 takes the lowest member bid).
    g_spot_price: np.ndarray  # f32[G]
    # queue-ordered gang index: gangs sorted by (queue, order); per-queue
    # contiguous slices.  The kernel's candidate scan is O(Q) gathers into this
    # instead of O(G) segment reductions (the analog of the reference keeping
    # per-queue sorted job iterators, queue_scheduler.go QueuedGangIterator:273).
    gq_gang: np.ndarray  # i32[G] gang ids, (queue, order)-sorted
    q_start: np.ndarray  # i32[Q] slice offset into gq_gang
    q_len: np.ndarray  # i32[Q] slice length
    # queues
    q_weight: np.ndarray  # f32[Q] (0 = padding)
    q_cds: np.ndarray  # f32[Q] constrained demand share
    # Short-job penalty (short_job_penalty.go): resources of recently-exited
    # short jobs, charged to the queue-ordering cost only
    # (queue_scheduler.go:514-515 GetAllocationInclShortJobPenalty).
    q_penalty: np.ndarray  # f32[Q, R]
    # static fit
    compat: np.ndarray  # bool[K, T]
    # pool-level scalars/vectors
    total_pool: np.ndarray  # f32[R]
    drf_mult: np.ndarray  # f32[R]
    inv_scale: np.ndarray  # f32[R] packing-score weights
    round_cap: np.ndarray  # f32[R] max schedulable this round (absolute units)
    pc_queue_cap: np.ndarray  # f32[C, R] per-queue cap by priority class (absolute)
    protected_fraction: np.ndarray  # f32 scalar
    global_burst: np.ndarray  # i32 scalar
    perq_burst: np.ndarray  # i32[Q] per-queue burst (rate-limited)
    # Floating resources (floatingresources/): 1.0 on node-bound axes, 0.0 on
    # floating axes; per-pool floating capacity (0 on node axes).
    node_axes: np.ndarray  # f32[R]
    float_total: np.ndarray  # f32[R]
    # Market-driven pools order candidates by bid price instead of DRF cost
    # (scheduling/market_iterator.go MarketCandidateGangIterator:245).
    market: np.ndarray  # bool scalar
    # Spot-price threshold (queue_scheduler.go:135-150): once the round's
    # newly-scheduled share crosses this, the crossing gang's bid becomes the
    # pool spot price.  _INF disables (non-market pools).
    spot_cutoff: np.ndarray  # f32 scalar
    # Retry anti-affinity (scheduler.go:522-568): nodes a gang must avoid --
    # nodes where a previous attempt died.  Precomputed outside the round loop
    # as a row table so the kernel does one invariant-table gather per
    # iteration (row 0 = no bans); an in-loop scatter keyed on the gathered
    # candidate would defeat XLA's invariant hoisting (see CLAUDE.md).
    ban_mask: np.ndarray  # bool[BR, N]
    g_ban_row: np.ndarray  # i32[G]
    # Heterogeneity (per-node-type throughput scoring, Gavel arXiv:2008.09213):
    # `type_bias[key_type_row[key], t]` is the packing-score adjustment of a
    # candidate with scheduling key `key` on a node of static type t -- the
    # exact ban_mask discipline: dense tables precomputed OUTSIDE the round
    # loop, one invariant-table gather in-loop.  Row 0 of type_bias is the
    # all-zero insensitive row; TR == 1 (no type-sensitive key anywhere) is
    # the structural switch that compiles the exact pre-hetero kernel body.
    # `compat_pre_type` is the static fit WITHOUT the hardware-type gate --
    # the explain pass partitions type-mismatch vs shape-infeasible with it.
    type_bias: np.ndarray  # f32[TR, T]
    key_type_row: np.ndarray  # i32[K]
    compat_pre_type: np.ndarray  # bool[K, T]


@dataclasses.dataclass
class HostContext:
    """Everything needed to decode a RoundResult back to ids."""

    config: SchedulingConfig
    pool: str
    queue_names: list  # index -> queue name
    node_ids: list  # index -> node id
    gang_members: list  # gang index -> list of member job ids ([] for evictee slots)
    # gang index -> shared tag for sub-gangs split from one declared gang
    # ("" otherwise); drives the cross-class atomicity unwind in decode_result.
    gang_group: list
    run_job_ids: list  # run index -> job id
    num_real_nodes: int
    num_real_queues: int
    num_real_gangs: int
    num_real_runs: int
    ladder: tuple  # priority ladder (ladder[level-1] = priority of level)
    pc_names: list  # priority-class index -> name
    max_slots: int
    slot_width: int
    # Host-only extras for metrics: raw (uncapped) per-queue demand shares and
    # the pool's fairness total in resource atoms (node + floating capacity --
    # the denominator every published share is a fraction of).
    q_demand_raw: list = dataclasses.field(default_factory=list)
    pool_total_atoms: dict = dataclasses.field(default_factory=dict)
    # Vectorized decode support (models/incremental.py): per-gang single job
    # id as bytes (b"" for evictee slots and multi-member units), overrides
    # for multi-member units, and per-run job ids as bytes.  When set,
    # gang_members / run_job_ids may be None and decode_result takes the
    # numpy path -- a 1M-gang Python loop in decode would cost the time the
    # incremental builder saves.
    gang_ids_vec: Optional[np.ndarray] = None
    gang_members_over: dict = dataclasses.field(default_factory=dict)
    run_ids_vec: Optional[np.ndarray] = None
    # Running-gang fate-sharing (preempting_queue_scheduler.go:345-399 +
    # setEvictedGangCardinality): tag -> run indices of the gang's
    # PREEMPTIBLE members present in this problem.  run_round_on_device uses
    # it to cascade partial preemptions -- a running gang either keeps all
    # members or loses all (the reference evicts the remains of partially
    # evicted gangs and re-schedules them as one all-or-nothing unit).
    running_gangs: dict = dataclasses.field(default_factory=dict)
    # Static node-type id -> hardware type name ("" = the untyped default)
    # for the REAL types of this round's NodeTypeIndex; explain's per-type
    # fragmentation merges the device's per-static-type rows onto hardware
    # types through it (several static types share one hw_type whenever
    # taints/labels differ within the hardware class).
    type_names: list = dataclasses.field(default_factory=list)
    # The compact decode buffer EXACTLY as this round's fetch received it
    # (stashed by _fetch_compact, overwritten per round; None on the
    # full-pull fallback).  Round verification (models/verify.py)
    # re-derives the fingerprint from these bytes -- the device-computed
    # fold rides a separate buffer, so transfer truncation/bit-flips in
    # either transfer surface as a mismatch instead of a committed round.
    last_compact_np: Optional[np.ndarray] = None

    def members_of(self, gi: int) -> list:
        """Member job ids of gang `gi` under either representation."""
        if self.gang_members is not None:
            return self.gang_members[gi]
        over = self.gang_members_over.get(gi)
        if over is not None:
            return over
        jid = self.gang_ids_vec[gi]
        return [jid.decode()] if jid else []

    def run_job_id(self, ri: int) -> str:
        if self.run_job_ids is not None:
            return self.run_job_ids[ri]
        return self.run_ids_vec[ri].decode()


@dataclasses.dataclass
class RoundOutcome:
    """Host-side decoded result of a scheduling round (the reference's
    SchedulerResult: scheduled jobs with nodes, preempted jobs)."""

    scheduled: dict  # job id -> node id
    preempted: list  # job ids preempted (evicted and not rescheduled)
    failed: list  # job ids attempted and unschedulable this round
    num_iterations: int
    termination: str
    # Physical while-loop trips (RoundResult.kernel_iters): num_iterations /
    # kernel_iters = average certified commits per iteration under the
    # multi-commit kernel (ARMADA_COMMIT_K); equal when K=1.  0 = unknown
    # (synthetic outcomes).
    kernel_iters: int = 0
    # queue name -> {weight, fair_share, adjusted_fair_share, actual_share,
    # demand_share} (feeds cycle metrics + reports; the reference's
    # QueueSchedulingContext numbers, cycle_metrics.go:71-170).
    queue_stats: dict = dataclasses.field(default_factory=dict)
    # Market pools: bid price of the gang that crossed the spot cutoff this
    # round (queue_scheduler.go:135-150); None when unset/not market.
    spot_price: Optional[float] = None
    # Pool fairness total (resource name -> atoms, node + floating): the
    # denominator of every share above (feeds metric events).
    pool_totals: dict = dataclasses.field(default_factory=dict)
    # job ids evicted this round and re-placed (they keep running; counted by
    # the realised-value metric like the reference's RescheduledJobSchedulingContexts)
    rescheduled: list = dataclasses.field(default_factory=list)
    # {base priority: share a new queue at that priority would get}
    # (CalculateTheoreticalShare; indicative_share metric).
    indicative_shares: dict = dataclasses.field(default_factory=dict)
    # Declared-gang group tags whose placed siblings were unwound at decode
    # because another sub-gang failed (runtime contention).  Non-empty means
    # evictions those placements caused are still in the result; the caller
    # re-runs without the doomed gangs to roll them back (the reference's
    # gang-txn rollback, nodedb.go:347).
    unwound_groups: frozenset = frozenset()
    # Unschedulable-reason attribution (models/explain.py ExplainOutcome):
    # populated on explain-cadence rounds (ARMADA_EXPLAIN_INTERVAL), None
    # otherwise.  Feeds reports, metrics, /healthz and bench.
    explain: Optional[object] = None


def pc_queue_caps(config, pc_names, factory, total_pool) -> np.ndarray:
    """f32[C, R] per-priority-class queue allocation caps: frac x f32
    total_pool (maximumResourceFractionPerQueue, constraints.go), INF where
    unconfigured.  The ONE implementation shared by build_problem, the
    incremental builder and the columnar idealised sweep, so the f32
    rounding of the cap threshold can never drift between the kernel and
    its host-side mirrors."""
    C = len(pc_names)
    R = factory.num_resources
    caps = np.full((C, R), _INF, np.float32)
    tp = np.asarray(total_pool, np.float32)
    for ci, pc_name in enumerate(pc_names):
        fr = config.priority_classes[pc_name].maximum_resource_fraction_per_queue
        for name, frac in fr.items():
            if name in factory.names:
                ri = factory.index_of(name)
                caps[ci, ri] = frac * tp[ri]
    return caps


def _pad(n: int, bucket: int) -> int:
    return max(bucket, ((n + bucket - 1) // bucket) * bucket)


class LazyJobIds:
    """List-like over a numpy byte-id array, decoded on demand.

    A round can retire whole unfeasible key classes, putting ~the entire
    backlog into `failed`; materialising a million Python strings cost ~2s
    per cycle at 1M jobs.  Consumers that only count (simulator, pool
    reports) pay O(1); only consumers that actually iterate pay the decode.
    """

    __slots__ = ("_raw", "_extra")

    def __init__(self, raw=None, extra=None):
        self._raw = raw if raw is not None and raw.size else None
        self._extra = list(extra) if extra else []

    def __len__(self):
        return (self._raw.size if self._raw is not None else 0) + len(self._extra)

    def __iter__(self):
        if self._raw is not None:
            # decode in chunks: a bounded consumer (itertools.islice) must
            # not pay a whole-array unicode conversion up front
            width = self._raw.dtype.itemsize
            for start in range(0, self._raw.size, 4096):
                for s in self._raw[start : start + 4096].astype(f"U{width}"):
                    yield str(s)
        yield from self._extra

    def __bool__(self):
        return len(self) > 0

    def __contains__(self, jid):
        if jid in self._extra:
            return True
        return self._raw is not None and self._raw.dtype.type(jid) in self._raw

    def __getitem__(self, i):
        if isinstance(i, slice):
            return list(self)[i]
        n_raw = self._raw.size if self._raw is not None else 0
        if i < 0:
            i += len(self)
        if i < n_raw:
            return self._raw[i].decode()
        return self._extra[i - n_raw]

    def append(self, jid):
        self._extra.append(jid)

    def extend(self, jids):
        self._extra.extend(jids)

    def __eq__(self, other):
        return list(self) == list(other)

    def __repr__(self):
        return f"LazyJobIds(n={len(self)})"


class ChainedJobIds:
    """Concatenation of id sequences that NEVER materialises its parts on
    extend -- `SchedulerResult.failed` collects one (possibly lazy) sequence
    per pool round; a plain list.extend would decode every id."""

    __slots__ = ("_parts",)

    def __init__(self):
        self._parts: list = []

    def extend(self, part) -> None:
        self._parts.append(part)

    def append(self, jid) -> None:
        self._parts.append([jid])

    def __len__(self):
        return sum(len(p) for p in self._parts)

    def __iter__(self):
        for p in self._parts:
            yield from p

    def __bool__(self):
        return any(len(p) for p in self._parts)

    def __eq__(self, other):
        return list(self) == list(other)

    def __repr__(self):
        return f"ChainedJobIds(n={len(self)})"


def queue_ordered_gang_index(
    g_queue: np.ndarray, g_order: np.ndarray, num_real: int, G: int, Q: int
) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """(gq_gang[G], q_start[Q], q_len[Q]): gang ids sorted by (queue, order)
    into per-queue contiguous slices -- the kernel's O(Q) candidate index
    (SchedulingProblem.gq_gang)."""
    gq_gang = np.zeros((G,), np.int32)
    q_start = np.zeros((Q,), np.int32)
    q_len = np.zeros((Q,), np.int32)
    if num_real:
        order = np.lexsort((g_order[:num_real], g_queue[:num_real]))
        gq_gang[:num_real] = order.astype(np.int32)
        counts = np.bincount(g_queue[:num_real], minlength=Q)
        q_len[:] = counts
        q_start[1:] = np.cumsum(counts)[:-1]
    return gq_gang, q_start, q_len


class _GangFitContext:
    """Per-round vectorized helpers for host-side gang feasibility: per-node
    member capacity (one numpy op over the [N,R] totals), static-fit masks
    memoized by (selector, tolerations) signature, and per-label domain
    index arrays built once however many gangs share the label."""

    def __init__(self, pool_nodes, node_total, node_index, factory, node_axes):
        self.pool_nodes = pool_nodes
        self.node_index = node_index
        self.num_real = len(pool_nodes)
        self.totals = node_total[: self.num_real].astype(np.float64)  # [n, R]
        self.ok = np.array(
            [not n.unschedulable for n in pool_nodes], bool
        ) if pool_nodes else np.zeros((0,), bool)
        self.factory = factory
        # 1.0 on node-bound axes, 0.0 on floating axes: per-node fit must
        # never see floating requests (floating_resource_types.go; the pool
        # totals gate handles them).
        self.node_axes = np.asarray(node_axes, np.float64)
        # Free capacity (totals minus running usage) once set_running_usage is
        # called; falls back to totals until then.
        self.free = self.totals
        self._static: dict = {}
        self._domains: dict = {}

    def set_running_usage(self, run_req, run_node, run_valid) -> None:
        """Subtract running jobs' usage so occupancy-aware choices (the
        uniformity domain pick) see actual headroom, not raw node sizes."""
        if not self.num_real:
            return
        used = np.zeros_like(self.totals)
        valid = np.asarray(run_valid, bool)
        if valid.any():
            np.add.at(
                used,
                np.asarray(run_node)[valid],
                np.asarray(run_req, np.float64)[valid],
            )
        self.free = np.maximum(self.totals - used, 0.0)

    def capacity(
        self, req_units: np.ndarray, cardinality: int, occupancy: bool = False
    ) -> np.ndarray:
        """i64[n]: members of `req_units` each node holds, capped at card.
        occupancy=True measures against FREE capacity (for preferences like
        the domain pick); False against totals (static feasibility -- a full
        node is not statically infeasible, preemption can clear it)."""
        if not self.num_real:
            return np.zeros((0,), np.int64)
        base = self.free if occupancy else self.totals
        req = np.asarray(req_units, np.float64) * self.node_axes
        with np.errstate(divide="ignore", invalid="ignore"):
            per = np.floor(
                np.where(
                    req[None, :] > 0,
                    base / np.maximum(req[None, :], 1e-9),
                    np.inf,
                )
            ).min(axis=1)
        return np.minimum(np.where(np.isfinite(per), per, cardinality), cardinality).astype(np.int64)

    def frac_capacity(self, req_units: np.ndarray) -> np.ndarray:
        """f64[n]: FRACTIONAL members of `req_units` each node's total holds
        (no floor, no cardinality cap).  An upper bound on any integral
        packing, which is what the joint hopeless-gang check needs: the LP
        relaxation of "how many mixed-class members fit on this node" attains
        its optimum on a single class, so max-over-classes of this bound is
        sound for class subsets."""
        if not self.num_real:
            return np.zeros((0,), np.float64)
        req = np.asarray(req_units, np.float64) * self.node_axes
        with np.errstate(divide="ignore", invalid="ignore"):
            per = np.where(
                req[None, :] > 0,
                self.totals / np.maximum(req[None, :], 1e-9),
                np.inf,
            ).min(axis=1)
        return np.where(np.isfinite(per), per, np.inf)

    def static_fit(self, job: JobSpec, node_id_label: str) -> np.ndarray:
        """bool[n]: taints tolerated and selector satisfied, memoized by the
        job's static signature (nodematching.go StaticJobRequirementsMet)."""
        from armada_tpu.core.types import selector_matches, taints_tolerated

        sel = tuple(
            sorted((k, v) for k, v in job.node_selector.items() if k != node_id_label)
        )
        sig = (sel, tuple(job.tolerations))
        cached = self._static.get(sig)
        if cached is None:
            seld = dict(sel)
            cached = np.array(
                [
                    taints_tolerated(n.taints, job.tolerations)
                    and selector_matches(seld, n.labels)
                    for n in self.pool_nodes
                ],
                bool,
            ) if self.pool_nodes else np.zeros((0,), bool)
            self._static[sig] = cached
        return cached

    def domains(self, label: str) -> dict:
        """{value: i64 node-index array} for nodes carrying `label`."""
        cached = self._domains.get(label)
        if cached is None:
            by_value: dict[str, list] = {}
            for i, n in enumerate(self.pool_nodes):
                v = n.labels.get(label)
                if v is not None:
                    by_value.setdefault(v, []).append(i)
            cached = {
                v: np.asarray(idx, np.int64) for v, idx in sorted(by_value.items())
            }
            self._domains[label] = cached
        return cached


def _joint_capacity_ok(class_info) -> bool:
    """Hall-condition bound over class subsets of a split gang.

    class_info: [(usable bool[n], frac_cap f64[n], member count)] per key
    class.  For a subset S of classes, no packing can place more than
    sum_n max_{c in S, usable} frac_cap_n(c) members of S (fractional-LP
    upper bound per node), so if that is < the members S needs, the declared
    gang is jointly infeasible even though each class fits alone -- the case
    the reference discovers by attempting the placement
    (gang_scheduler.go:152-227) and we must pre-kill to keep the kernel's
    sibling-unwind path cold.  Sound: only definitely-infeasible gangs fail.
    Subset enumeration is capped at 2^10; larger splits check the full set
    and pairs only (still sound, just less sharp)."""
    k = len(class_info)
    if k < 2:
        return True
    total_members = sum(count for _, _, count in class_info)
    # per-class capacity capped at what the subset could ever need: keeps
    # inf (zero-request classes) from masking a genuine shortfall elsewhere.
    caps = np.stack(
        [
            np.where(usable, np.minimum(frac, float(total_members)), 0.0)
            for usable, frac, _ in class_info
        ]
    )  # [k, n]
    counts = np.array([count for _, _, count in class_info], np.int64)
    if k <= 10:
        subsets = range(1, 1 << k)
    else:
        subsets = [(1 << k) - 1] + [
            (1 << i) | (1 << j) for i in range(k) for j in range(i + 1, k)
        ]
    for s in subsets:
        members = np.array([(s >> i) & 1 for i in range(k)], bool)
        if members.sum() < 2:
            continue  # singletons already checked with the tighter bound
        ub = caps[members].max(axis=0).sum()
        if ub < counts[members].sum():
            return False
    return True


def _uniform_domain_ban(
    fit: _GangFitContext,
    label: str,
    classes,
    banned_node_ids,
    node_id_label: str,
) -> tuple[set, str]:
    """(banned node indices, chosen value) restricting a uniformity gang to
    its best label-value domain (gang_scheduler.go tries domains; here the
    best domain is chosen per round).  `classes` is [(lead job, member
    count)] -- ONE per key class of the gang, so a heterogeneous gang's
    domain must work for every class, not just the lead's.  Scoring counts
    only schedulable, statically-fitting, non-retry-banned nodes; a domain
    whose FREE capacity satisfies every class beats one satisfying on
    totals only, which beats neither, ties broken by free capacity -- so an
    occupied domain never shadows an empty viable one, and the choice
    self-corrects round over round as occupancy shifts.  Nodes lacking the
    label are always excluded."""
    per_class = []
    for lead, count in classes:
        req = (
            fit.factory.ceil_units(lead.resources.atoms).astype(np.float64)
            if lead.resources is not None
            else np.zeros((fit.factory.num_resources,), np.float64)
        )
        cap_total = fit.capacity(req, count)
        cap_free = fit.capacity(req, count, occupancy=True)
        usable = fit.ok & fit.static_fit(lead, node_id_label)  # fresh array
        if banned_node_ids:
            for nid in banned_node_ids:
                ni = fit.node_index.get(nid)
                if ni is not None and ni < usable.shape[0]:
                    usable[ni] = False
        per_class.append((cap_total, cap_free, usable, count))
    best_value, best = "", (False, False, -1)
    for v, idx in fit.domains(label).items():
        free_total, fits_free, fits_static = 0, True, True
        for cap_total, cap_free, usable, count in per_class:
            u = usable[idx]
            cf = int(cap_free[idx][u].sum())
            free_total += cf
            if cf < count:
                fits_free = False
            if int(cap_total[idx][u].sum()) < count:
                fits_static = False
        if (fits_free, fits_static, free_total) > best:
            best_value, best = v, (fits_free, fits_static, free_total)
    allowed = set(
        int(i) for i in fit.domains(label).get(best_value, np.zeros(0, np.int64))
    )
    banned = set(range(fit.num_real)) - allowed
    return banned, best_value


def _job_sort_key(pc_priority: int, job: JobSpec):
    """Queue-internal scheduling order; single source of truth in
    core.ordering (shared with the JobDb queued index)."""
    return scheduling_order_key(pc_priority, job.priority, job.submit_time, job.id)


def build_problem(
    config: SchedulingConfig,
    *,
    pool: str,
    nodes: Sequence[NodeSpec],
    queues: Sequence[Queue],
    queued_jobs: Sequence[JobSpec],
    running: Sequence[RunningJob] = (),
    bid_price_of=None,
    away_mode: bool = False,
    global_tokens=None,
    queue_tokens=None,
    banned_nodes=None,
    queue_penalty=None,
) -> tuple[SchedulingProblem, HostContext]:
    """`bid_price_of(job) -> float` supplies bid prices; required for pools
    configured market_driven (pricer/gang_pricer.go:29-40).

    away_mode=True places queued gangs at the LOWEST real priority level (an
    away round: jobs borrowing another pool's nodes, scheduling_algo.go:216-283);
    they then never preempt anything, and home jobs evict them later via
    urgency preemption since away runs hold resources at level 1.

    global_tokens / queue_tokens clamp the burst caps to the scheduler's rate
    limiters (maximumSchedulingRate token buckets, queue_scheduler.go).

    banned_nodes: {job_id: iterable of node ids} a retried job must avoid
    (retry anti-affinity, scheduler.go:522-568).

    queue_penalty: {queue: resource atoms} short-job penalty charged to the
    queue-ordering cost (short_job_penalty.go; scheduling_algo.go:342-360)."""
    factory = config.resource_list_factory()
    R = factory.num_resources
    bucket = config.shape_bucket

    pool_nodes = [n for n in nodes if n.pool == pool]
    pool_cfg = next((pc for pc in config.pools if pc.name == pool), None)
    market = bool(pool_cfg is not None and getattr(pool_cfg, "market_driven", False))
    if market and bid_price_of is None:
        raise ValueError(f"pool {pool} is market driven but no bid_price_of given")
    # Prices are f32-canonical everywhere they order candidates: the kernel
    # orders queues by the f32 g_price tensor, and the incremental builder's
    # (queue, band) table is f32 -- rounding HERE too keeps the within-queue
    # order consistent across all three, even for f64-distinct prices that
    # collide in f32 (CLAUDE.md parity: f32 score arithmetic is the canon).
    _raw_price_of = bid_price_of or (lambda job: 0.0)
    price_of = lambda job: float(np.float32(_raw_price_of(job)))  # noqa: E731
    queue_by_name = {q.name: i for i, q in enumerate(sorted(queues, key=lambda q: q.name))}
    sorted_queues = sorted(queues, key=lambda q: q.name)

    # --- priority ladder: level 0 = evicted marker, 1..P = distinct PC priorities.
    # Levels: 0 = evicted markers, 1 = away placements, 2.. = the PC ladder.
    ladder = config.priority_ladder()
    level_of_priority = {p: i + 2 for i, p in enumerate(ladder)}
    pc_names = sorted(config.priority_classes)
    pc_index = {name: i for i, name in enumerate(pc_names)}

    def job_level(job: JobSpec) -> int:
        return level_of_priority[config.priority_class(job.priority_class).priority]

    # --- node tensors -----------------------------------------------------------
    all_jobs = list(queued_jobs) + [r.job for r in running]
    indexed = set(config.indexed_node_labels) | labels_referenced_by_selectors(
        all_jobs, config.node_id_label
    )
    ntidx = NodeTypeIndex(indexed)
    N = _pad(len(pool_nodes), bucket)
    node_total = np.zeros((N, R), np.float32)
    node_type = np.zeros((N,), np.int32)
    node_ok = np.zeros((N,), bool)
    node_index = {}
    atoms_rows = []
    atoms_idx = []
    for i, node in enumerate(pool_nodes):
        node_index[node.id] = i
        if node.total_resources is not None:
            atoms_rows.append(node.total_resources.atoms)
            atoms_idx.append(i)
        node_type[i] = ntidx.type_of(node)
        node_ok[i] = not node.unschedulable
    if atoms_rows:
        # one vectorized floor instead of a per-node numpy call
        node_total[atoms_idx] = factory.floor_units(np.stack(atoms_rows))

    # --- scheduling keys for queued jobs ---------------------------------------
    kidx = SchedulingKeyIndex()
    bans_of = banned_nodes or {}

    def _key_of(j: JobSpec, gang_bans=None, uniformity=("", "")) -> int:
        # Bans join the key (podutils.go folds affinity into SchedulingKey), so a
        # retried job's placement failure never retires the clean jobs' key class.
        # Gang members share their gang's UNION ban set: per-member bans would
        # give members distinct keys and shatter the gang into singleton
        # sub-gangs, losing all-or-nothing atomicity.  A uniformity gang's
        # chosen domain joins the key the same way.
        bans = gang_bans if gang_bans is not None else bans_of.get(j.id, ())
        return kidx.key_of(
            j, config.node_id_label, banned_nodes=bans, uniformity=uniformity
        )

    # --- running jobs + evictee gang slots --------------------------------------
    run_list = [r for r in running if r.node_id in node_index]
    RJ = _pad(len(run_list), bucket)
    run_req = np.zeros((RJ, R), np.float32)
    run_node = np.zeros((RJ,), np.int32)
    run_level = np.ones((RJ,), np.int32)
    run_queue = np.zeros((RJ,), np.int32)
    run_pc = np.zeros((RJ,), np.int32)
    run_preemptible = np.zeros((RJ,), bool)
    run_valid = np.zeros((RJ,), bool)
    run_job_ids = []

    # --- gangs: group queued jobs ----------------------------------------------
    class _Gang:
        __slots__ = (
            "jobs", "queue", "key", "level", "pc", "req", "req_atoms", "card",
            "order", "run", "price", "spot_price", "group", "uban", "dead",
        )

    floating_names = set(config.floating_resource_names())
    node_axes = np.array(
        [0.0 if name in floating_names else 1.0 for name in factory.names],
        np.float32,
    )
    fitctx = _GangFitContext(pool_nodes, node_total, node_index, factory, node_axes)

    gangs: list[_Gang] = []
    per_queue_jobs: dict[int, list] = {qi: [] for qi in range(len(sorted_queues))}
    for job in queued_jobs:
        qi = queue_by_name.get(job.queue)
        if qi is None:
            continue
        if job.pools and pool not in job.pools:
            continue
        per_queue_jobs[qi].append(job)

    gang_members_out: list[list] = []

    def _new_gang() -> _Gang:
        g = _Gang()
        gangs.append(g)
        return g

    # evictee slots first (order ranks below queued gangs per queue)
    evictee_by_queue: dict[int, list] = {qi: [] for qi in range(len(sorted_queues))}
    running_gang_groups: dict[str, list] = {}
    for ri, r in enumerate(run_list):
        run_job_ids.append(r.job.id)
        run_req[ri] = factory.ceil_units(r.job.resources.atoms) if r.job.resources else 0
        run_node[ri] = node_index[r.node_id]
        pc = config.priority_class(r.job.priority_class)
        if r.away:
            # Away runs hold resources at the lowest real level and are
            # always evictable by home jobs (scheduling_algo.go:216-283).
            run_level[ri] = 1
            preemptible = True
        else:
            run_level[ri] = level_of_priority[pc.priority]
            preemptible = pc.preemptible
        qi = queue_by_name.get(r.job.queue, -1)
        if qi < 0:
            continue  # unknown queue: cannot be evicted (pqs.go:129-131)
        run_queue[ri] = qi
        run_pc[ri] = pc_index[pc.name]
        run_preemptible[ri] = preemptible
        run_valid[ri] = True
        if preemptible:
            evictee_by_queue[qi].append(ri)
            if r.job.gang_id:
                # fate-sharing group for the partial-preemption cascade
                running_gang_groups.setdefault(
                    f"{r.job.queue}/{r.job.gang_id}", []
                ).append(ri)

    run_gang = np.full((RJ,), -1, np.int32)
    for qi, ris in evictee_by_queue.items():
        # evictees ordered among themselves by the same comparator
        if market:
            ris.sort(
                key=lambda ri: (
                    -price_of(run_list[ri].job),
                    run_list[ri].job.submit_time,
                    run_list[ri].job.id,
                )
            )
        else:
            ris.sort(
                key=lambda ri: _job_sort_key(
                    ladder[max(run_level[ri] - 2, 0)], run_list[ri].job
                )
            )
        for order, ri in enumerate(ris):
            g = _new_gang()
            g.jobs = []
            g.queue = qi
            g.key = -1
            g.level = int(run_level[ri])
            g.pc = int(run_pc[ri])
            g.req = run_req[ri].copy()
            g.req_atoms = None
            g.card = 1
            g.order = order
            g.run = ri
            g.price = float(price_of(run_list[ri].job))
            g.spot_price = g.price
            g.group = ""
            g.uban = None
            g.dead = False
            run_gang[ri] = len(gangs) - 1
            gang_members_out.append([])

    # Occupancy for the uniformity-domain pick (run tensors are now filled),
    # and where each partially-running gang's siblings already live: re-queued
    # members must rejoin the SAME domain, not the statically-best one.
    fitctx.set_running_usage(run_req, run_node, run_valid)
    running_gang_nodes: dict[tuple, list[int]] = {}
    for r in run_list:
        if r.job.gang_id:
            rqi = queue_by_name.get(r.job.queue)
            if rqi is not None:
                running_gang_nodes.setdefault((rqi, r.job.gang_id), []).append(
                    node_index[r.node_id]
                )

    # queued gangs, per queue, lookback-capped
    for qi in range(len(sorted_queues)):
        jobs = per_queue_jobs[qi]
        # group by gang id; singletons stay singletons
        by_gang: dict[str, list] = {}
        singles = []
        for job in jobs:
            if job.gang_id:
                by_gang.setdefault(job.gang_id, []).append(job)
            else:
                singles.append(job)
        def unit_key(lead_pc_priority, job):
            if market:
                return (-price_of(job), job.submit_time, job.id)
            return _job_sort_key(lead_pc_priority, job)

        units: list[tuple[tuple, list, int, str, object, bool]] = []
        for job in singles:
            pc = config.priority_class(job.priority_class)
            units.append(
                (unit_key(pc.priority, job), [job], _key_of(job), "", None, False)
            )
        for gang_id, members in by_gang.items():
            gang_bans = sorted(
                set().union(*(bans_of.get(m.id, ()) for m in members))
            ) if bans_of else ()
            # Node-uniformity (gang_scheduler.go NodeUniformity): restrict the
            # whole gang to the single best label-value domain, chosen by
            # usable static capacity; encoded as extra ban rows, so the
            # kernel needs no new machinery.  Re-chosen every round.  The
            # choice sees every key CLASS of the gang (grouped provisionally,
            # without interning junk keys), so a heterogeneous gang's domain
            # must work for all of its classes.
            label = members[0].gang_node_uniformity_label
            uniformity = ("", "")
            uban: Optional[set] = None
            if label:
                def _sig(m: JobSpec):
                    return class_signature(m, config.node_id_label)

                prov: dict = {}
                for m in members:
                    prov.setdefault(_sig(m), []).append(m)
                classes = [(grp[0], len(grp)) for grp in prov.values()]
                if len(classes) == 1:
                    classes = [
                        (
                            members[0],
                            max(len(members), members[0].gang_cardinality or 1),
                        )
                    ]
                # Partially-running gang: siblings already occupy a domain;
                # re-queued members MUST rejoin it or the gang straddles.
                pinned_values = {
                    pool_nodes[ni].labels.get(label)
                    for ni in running_gang_nodes.get((qi, gang_id), ())
                } - {None}
                if len(pinned_values) == 1:
                    chosen = next(iter(pinned_values))
                    allowed = {
                        int(i)
                        for i in fitctx.domains(label).get(
                            chosen, np.zeros(0, np.int64)
                        )
                    }
                    uban = set(range(fitctx.num_real)) - allowed
                else:
                    uban, chosen = _uniform_domain_ban(
                        fitctx, label, classes, gang_bans, config.node_id_label
                    )
                uniformity = (label, chosen)
            keys = {_key_of(m, gang_bans, uniformity) for m in members}
            if len(keys) > 1:
                # Heterogeneous gangs split per key class; the hopeless check
                # below + the decode unwind keep them atomic across classes.
                by_key: dict[int, list] = {}
                for m in members:
                    by_key.setdefault(_key_of(m, gang_bans, uniformity), []).append(m)
                groups = list(by_key.items())
            else:
                groups = [(next(iter(keys)), members)]
            group_tag = f"{qi}:{gang_id}" if len(groups) > 1 else ""
            # If the declared gang is statically hopeless, kill every sub-gang
            # up front so no sibling placement has to be unwound after the
            # fact (and no eviction is spent on it).  Two tiers, both sound
            # (never kill a feasible gang):
            #   1. per class: integer member capacity across usable nodes
            #      < member count;
            #   2. jointly: classes are individually feasible but COMPETE for
            #      the same nodes (gang_scheduler.go:152-227 discovers this by
            #      actually placing; here a Hall-condition bound over class
            #      subsets with a fractional-LP per-node capacity).
            dead = False
            if len(groups) > 1:
                class_info = []  # (usable[n], frac_cap[n], count)
                for _, grp in groups:
                    glead = grp[0]
                    usable = fitctx.ok & fitctx.static_fit(
                        glead, config.node_id_label
                    )
                    if uban:
                        usable = usable.copy()
                        usable[np.asarray(sorted(uban), np.int64)] = False
                    req_units = (
                        fitctx.factory.ceil_units(glead.resources.atoms).astype(np.float64)
                        if glead.resources is not None
                        else np.zeros((R,), np.float64)
                    )
                    cap = fitctx.capacity(req_units, len(grp))
                    if int(cap[usable].sum()) < len(grp):
                        dead = True
                        break
                    class_info.append(
                        (usable, fitctx.frac_capacity(req_units), len(grp))
                    )
                if not dead:
                    dead = not _joint_capacity_ok(class_info)
            for grp_key, grp in groups:
                lead = min(
                    grp,
                    key=lambda m: _job_sort_key(
                        config.priority_class(m.priority_class).priority, m
                    ),
                )
                pc = config.priority_class(lead.priority_class)
                units.append(
                    (unit_key(pc.priority, lead), grp, grp_key, group_tag, uban, dead)
                )
        units.sort(key=lambda u: u[0])
        kept = units[: config.max_queue_lookback]
        if len(units) > len(kept):
            # The lookback cap must keep or drop a split gang's sub-gangs
            # ATOMICALLY: a sibling truncated out of the problem would be
            # invisible to the decode unwind and a half-gang could lease.
            kept_tags = {u[3] for u in kept if u[3]}
            cut_tags = {u[3] for u in units[len(kept):] if u[3]}
            partial = kept_tags & cut_tags
            if partial:
                kept = [u for u in kept if u[3] not in partial]
        base = len(evictee_by_queue[qi])
        for order, (_, members, key, group_tag, uban, dead) in enumerate(kept):
            lead = members[0]
            pc = config.priority_class(lead.priority_class)
            g = _new_gang()
            g.jobs = [m.id for m in members]
            g.queue = qi
            g.key = key
            g.level = 1 if away_mode else job_level(lead)
            g.pc = pc_index[pc.name]
            # raw atoms; unit-ceiled in ONE vectorized pass at assembly
            g.req = None
            g.req_atoms = lead.resources.atoms if lead.resources else None
            g.card = len(members)
            g.order = base + order
            g.run = -1
            g.price = float(price_of(lead))
            g.spot_price = (
                g.price
                if len(members) == 1
                else min(float(price_of(m)) for m in members)
            )
            g.group = group_tag
            g.uban = uban
            g.dead = dead
            gang_members_out.append(g.jobs)

    G = _pad(len(gangs), bucket)
    g_req = np.zeros((G, R), np.float32)
    g_card = np.zeros((G,), np.int32)
    g_level = np.ones((G,), np.int32)
    g_queue = np.zeros((G,), np.int32)
    g_key = np.full((G,), -1, np.int32)
    g_pc = np.zeros((G,), np.int32)
    g_order = np.zeros((G,), np.int32)
    g_run = np.full((G,), -1, np.int32)
    g_valid = np.zeros((G,), bool)
    g_price = np.zeros((G,), np.float32)
    g_spot_price = np.zeros((G,), np.float32)
    for i, g in enumerate(gangs):
        if g.req is not None:
            g_req[i] = g.req
        g_card[i] = g.card
        g_level[i] = g.level
        g_queue[i] = g.queue
        g_key[i] = g.key
        g_pc[i] = g.pc
        g_order[i] = g.order
        g_run[i] = g.run
        g_valid[i] = not g.dead
        g_price[i] = g.price
        g_spot_price[i] = g.spot_price
    # Unit-ceil every queued gang's request in one vectorized pass (a per-gang
    # ceil_units call costs ~3us of numpy overhead; at 1M gangs that is
    # seconds of host time per round).
    atom_rows = [i for i, g in enumerate(gangs) if g.req is None]
    if atom_rows:
        mat = np.stack(
            [
                gangs[i].req_atoms
                if gangs[i].req_atoms is not None
                else np.zeros((R,), np.int64)
                for i in atom_rows
            ]
        )
        g_req[atom_rows] = factory.ceil_units(mat).astype(np.float32)

    # --- pinned node for evictee slots is derived in-kernel from run_node -------

    # --- static fit matrix ------------------------------------------------------
    K = max(1, len(kidx))
    T = max(1, len(ntidx))
    compat = np.zeros((K, T), bool)
    compat_pre_type = np.zeros((K, T), bool)
    if len(kidx) and len(ntidx):
        compat[: len(kidx), : len(ntidx)] = static_fit_matrix(kidx.keys, ntidx.types)
        compat_pre_type[: len(kidx), : len(ntidx)] = static_fit_matrix(
            kidx.keys, ntidx.types, pre_type=True
        )
    key_type_row, type_bias = type_score_tables(kidx.keys, ntidx.types, K, T)

    # --- pool totals, DRF, caps -------------------------------------------------
    float_total = np.zeros((R,), np.float32)
    if floating_names:
        fl = factory.from_mapping(config.floating_totals_for_pool(pool))
        # Same resolution-unit scale as node_total/g_req (floor like capacity).
        float_total = (
            factory.floor_units(fl.atoms).astype(np.float64) * (1 - node_axes)
        ).astype(np.float32)
    # Keep an exact f64 copy: the f32 device tensor is fine for shares, but
    # metric events publish the totals as exact quantities (a 50k-node pool's
    # byte count exceeds f32's 2^24 integer range).
    total_pool64 = node_total[: len(pool_nodes)].sum(axis=0, dtype=np.float64)
    # Floating capacity joins the pool totals for fairness + caps
    # (scheduling_algo.go:289,585 adds GetTotalAvailableForPool).
    total_pool64 = total_pool64 + float_total.astype(np.float64)
    total_pool = total_pool64.astype(np.float32)
    drf_mult = factory.multipliers_for(config.drf_multipliers()).astype(np.float32)
    scale = node_total.max(axis=0) if len(pool_nodes) else np.zeros(R, np.float32)
    inv_scale = np.where(scale > 0, 1.0 / np.maximum(scale, 1e-9), 0.0).astype(np.float32)

    round_cap = np.full((R,), _INF, np.float32)
    for name, frac in config.maximum_resource_fraction_to_schedule.items():
        if name in factory.names:
            round_cap[factory.index_of(name)] = frac * total_pool[factory.index_of(name)]

    C = len(pc_names)
    pc_queue_cap = pc_queue_caps(config, pc_names, factory, total_pool)

    # --- ban rows: retry anti-affinity + uniformity-domain restrictions --------
    # Row 0 is the all-clear; each gang with bans gets its own row.  Shapes are
    # padded to small buckets so jit recompiles only when the banned-gang count
    # crosses a bucket boundary.
    g_ban_row = np.zeros((G,), np.int32)
    ban_rows: list[np.ndarray] = []
    rows_by_gang: dict[int, np.ndarray] = {}

    def _gang_row(gi: int) -> np.ndarray:
        row = rows_by_gang.get(gi)
        if row is None:
            row = np.zeros((N,), bool)
            rows_by_gang[gi] = row
        return row

    if bans_of:
        gang_of_job = {}
        for gi, members in enumerate(gang_members_out):
            for jid in members:
                gang_of_job[jid] = gi
        for jid, node_ids in bans_of.items():
            gi = gang_of_job.get(jid)
            if gi is None:
                continue
            row = _gang_row(gi)
            for nid in node_ids:
                ni = node_index.get(nid)
                if ni is not None:
                    row[ni] = True
    for gi, g in enumerate(gangs):
        if g.uban:
            row = _gang_row(gi)
            for ni in g.uban:
                row[ni] = True
    for gi, row in rows_by_gang.items():
        if row.any():
            ban_rows.append(row)
            g_ban_row[gi] = len(ban_rows)
    BR = _pad(len(ban_rows) + 1, 8) if ban_rows else 1
    ban_mask = np.zeros((BR, N), bool)
    for i, row in enumerate(ban_rows):
        ban_mask[i + 1] = row

    # --- queue-ordered gang index ----------------------------------------------
    Q = _pad(len(sorted_queues), bucket)
    gq_gang, q_start, q_len = queue_ordered_gang_index(
        g_queue, g_order, len(gangs), G, Q
    )

    # --- queues: weights + constrained demand share ----------------------------
    q_weight = np.zeros((Q,), np.float32)
    q_cds = np.zeros((Q,), np.float32)
    q_penalty = np.zeros((Q, R), np.float32)
    if queue_penalty:
        for qname, atoms in queue_penalty.items():
            qi = queue_by_name.get(qname)
            if qi is not None:
                q_penalty[qi] = factory.ceil_units(atoms).astype(np.float32)
    demand_by_pc = np.zeros((len(sorted_queues), C, R), np.float64)
    nreal = len(gangs)
    if nreal:
        queued_mask = g_run[:nreal] < 0
        contrib = g_req[:nreal].astype(np.float64) * g_card[:nreal, None]
        np.add.at(
            demand_by_pc,
            (g_queue[:nreal][queued_mask], g_pc[:nreal][queued_mask]),
            contrib[queued_mask],
        )
    nr = len(run_list)
    if nr:
        rv = run_valid[:nr]
        np.add.at(
            demand_by_pc,
            (run_queue[:nr][rv], run_pc[:nr][rv]),
            run_req[:nr][rv].astype(np.float64),
        )
    q_demand_raw = [0.0] * len(sorted_queues)
    for qi, q in enumerate(sorted_queues):
        q_weight[qi] = q.weight
        raw = demand_by_pc[qi].sum(axis=0)
        capped = np.minimum(demand_by_pc[qi], pc_queue_cap).sum(axis=0)
        capped = np.minimum(capped, total_pool.astype(np.float64))
        with np.errstate(divide="ignore", invalid="ignore"):
            frac = np.where(total_pool > 0, capped / np.maximum(total_pool, 1e-9), 0.0)
            rawfrac = np.where(
                total_pool > 0, raw / np.maximum(total_pool, 1e-9), 0.0
            )
        q_cds[qi] = max(0.0, float((frac * drf_mult).max())) if R else 0.0
        # RAW demand share (may exceed 1) for metric events: the reference's
        # metricevents distinguishes demand from constrained_demand.
        q_demand_raw[qi] = max(0.0, float((rawfrac * drf_mult).max())) if R else 0.0

    # --- burst caps, clamped by the rate limiters' available tokens -----------
    burst_cfg = config.maximum_scheduling_burst or 2**31 - 1
    if global_tokens is not None:
        burst_cfg = max(0, min(burst_cfg, int(global_tokens)))
    perq_cfg = config.maximum_per_queue_scheduling_burst or 2**31 - 1
    perq_burst = np.full((Q,), 2**31 - 1, np.int32)
    for qi, q in enumerate(sorted_queues):
        cap = perq_cfg
        if queue_tokens is not None and q.name in queue_tokens:
            cap = max(0, min(cap, int(queue_tokens[q.name])))
        perq_burst[qi] = min(cap, 2**31 - 1)

    max_card = int(g_card.max()) if len(gangs) else 1
    if max_card > 10_000:
        raise ValueError(f"gang cardinality {max_card} exceeds the supported 10k")
    W = max(1, min(max_card, N))
    S = max(1, min(len(gangs), burst_cfg))

    problem = SchedulingProblem(
        node_total=node_total,
        node_type=node_type,
        node_ok=node_ok,
        run_req=run_req,
        run_node=run_node,
        run_level=run_level,
        run_queue=run_queue,
        run_pc=run_pc,
        run_preemptible=run_preemptible,
        run_gang=run_gang,
        run_valid=run_valid,
        g_req=g_req,
        g_card=g_card,
        g_level=g_level,
        g_queue=g_queue,
        g_key=g_key,
        g_pc=g_pc,
        g_order=g_order,
        g_run=g_run,
        g_valid=g_valid,
        g_absent=np.zeros_like(g_valid),
        g_price=g_price,
        g_spot_price=g_spot_price,
        gq_gang=gq_gang,
        q_start=q_start,
        q_len=q_len,
        q_weight=q_weight,
        q_cds=q_cds,
        q_penalty=q_penalty,
        compat=compat,
        total_pool=total_pool,
        drf_mult=drf_mult,
        inv_scale=inv_scale,
        round_cap=round_cap,
        pc_queue_cap=pc_queue_cap.astype(np.float32),
        # Away rounds never evict: guests take genuinely free capacity only
        # (the host's home rounds handle eviction; an away guest must not be
        # able to displace a home job).
        protected_fraction=np.float32(
            _INF if away_mode else config.protected_fraction_of_fair_share
        ),
        global_burst=np.int32(min(burst_cfg, 2**31 - 1)),
        perq_burst=perq_burst,
        node_axes=node_axes,
        float_total=float_total,
        market=np.bool_(market),
        spot_cutoff=np.float32(
            pool_cfg.spot_price_cutoff
            if market and pool_cfg is not None and pool_cfg.spot_price_cutoff > 0
            else _INF
        ),
        ban_mask=ban_mask,
        g_ban_row=g_ban_row,
        type_bias=type_bias,
        key_type_row=key_type_row,
        compat_pre_type=compat_pre_type,
    )
    ctx = HostContext(
        config=config,
        pool=pool,
        queue_names=[q.name for q in sorted_queues],
        node_ids=[n.id for n in pool_nodes],
        gang_members=gang_members_out,
        gang_group=[g.group for g in gangs],
        run_job_ids=run_job_ids,
        num_real_nodes=len(pool_nodes),
        num_real_queues=len(sorted_queues),
        num_real_gangs=len(gangs),
        num_real_runs=len(run_list),
        ladder=ladder,
        pc_names=pc_names,
        max_slots=S,
        slot_width=W,
        q_demand_raw=q_demand_raw,
        pool_total_atoms={
            name: int(round(float(total_pool64[i]) * factory.resolutions[i]))
            for i, name in enumerate(factory.names)
            if total_pool64[i]
        },
        running_gangs={
            tag: tuple(ris)
            for tag, ris in running_gang_groups.items()
            if len(ris) > 1
        },
        type_names=[nt.hw_type for nt in ntidx.types],
    )
    return problem, ctx


_TERMINATIONS = ["exhausted", "global_burst", "round_resource_cap", "max_iterations"]


def queue_stats_from_result(result, problem: SchedulingProblem, ctx: HostContext) -> dict:
    """Per-queue share numbers from the final round state (fair shares are
    recomputed host-side from the same inputs the kernel used)."""
    from armada_tpu.ops.fairness import fair_shares, unweighted_drf_cost

    Q = int(problem.q_weight.shape[0])
    shares = fair_shares(np.asarray(problem.q_weight), np.asarray(problem.q_cds))
    actual = unweighted_drf_cost(
        np.asarray(result.q_alloc),
        np.asarray(problem.total_pool),
        np.asarray(problem.drf_mult),
    )
    fs = np.asarray(shares.fair_share)
    afs = np.asarray(shares.demand_capped_adjusted_fair_share)
    actual = np.asarray(actual)
    penalty = unweighted_drf_cost(
        np.asarray(problem.q_penalty),
        np.asarray(problem.total_pool),
        np.asarray(problem.drf_mult),
    )
    penalty = np.asarray(penalty)
    out = {}
    for qi in range(ctx.num_real_queues):
        out[ctx.queue_names[qi]] = {
            "weight": float(problem.q_weight[qi]),
            "fair_share": float(fs[qi]),
            "adjusted_fair_share": float(afs[qi]),
            "actual_share": float(actual[qi]),
            "demand_share": float(problem.q_cds[qi]),
            # RAW demand (may exceed 1; metricevents distinguishes it from
            # the constrained demand_share above).
            "demand_share_raw": (
                float(ctx.q_demand_raw[qi]) if qi < len(ctx.q_demand_raw) else 0.0
            ),
            # cycle_metrics.go:443: unweighted cost of the penalty RL.
            "short_job_penalty": float(penalty[qi]),
        }
    return out


# Caps for the packed single-transfer decode (decode_result fast path); a
# round whose failed/evicted counts exceed them falls back to the full pull.
# Module-level so tests can shrink them to force the fallback.
_COMPACT_FCAP = 8192
_COMPACT_ECAP = 8192


def _dispatch_compact(result, ctx: HostContext):
    """Enqueue the jitted result compaction on the device WITHOUT reading it
    back; returns (device buffer, fcap, ecap) or None when the result is not
    a device RoundResult.  Splitting dispatch from the host read lets
    begin_decode start the device->host copy behind the round kernel."""
    import jax

    from armada_tpu.models.fair_scheduler import compact_result

    if not isinstance(result.g_state, jax.Array):
        return None
    sharding = getattr(result.g_state, "sharding", None)
    mesh_shape = getattr(getattr(sharding, "mesh", None), "shape", None)
    if mesh_shape is not None and sum(1 for v in mesh_shape.values() if v > 1) >= 2:
        # XLA:CPU GSPMD (jax 0.4.37) miscompiles cross-jit reductions over
        # arrays partitioned on one mesh axis and REPLICATED on another:
        # the per-device partial sums all-reduce over BOTH axes, so every
        # compact-header scalar comes back x(replicated-axis size) --
        # caught by test_parallel_sharding's 2D (nodes x jobs) mesh, where
        # n_slots/n_failed arrived x node_shards.  Per-shard values are
        # correct (direct np.asarray reads are fine), so fall back to the
        # full pull.  The serving mesh is nodes x 1 (one >1 axis) and
        # keeps the compact path.
        return None
    G = int(result.g_state.shape[0])
    RJ = int(result.run_evicted.shape[0])
    fcap = min(G, _COMPACT_FCAP)
    ecap = min(RJ, _COMPACT_ECAP) if RJ else 0
    buf = compact_result(
        result,
        np.int32(ctx.num_real_gangs),
        np.int32(ctx.num_real_runs),
        fcap=fcap,
        ecap=ecap,
    )
    return buf, fcap, ecap


def _fetch_compact(result, ctx: HostContext, dispatched=None):
    """Pull the O(decisions) decode inputs in ONE device->host transfer.

    Returns (n_slots, slot_gang, slot_nodes, slot_counts, g2, pre_idx,
    res_idx, state_of, iterations, termination, spot) or None when a cap
    overflowed (fall back to the full-array pull) or the result is not a
    device RoundResult.
    """
    d = dispatched if dispatched is not None else _dispatch_compact(result, ctx)
    ctx.last_compact_np = None
    if d is None:
        return None
    buf_dev, fcap, ecap = d
    buf = np.asarray(buf_dev)
    from armada_tpu.models.xfer import TRANSFER_STATS

    TRANSFER_STATS.count_down(buf.nbytes)
    if os.environ.get("ARMADA_FAULT"):
        # round_corrupt `bytes` drill (core/faults): flip a bit in the
        # buffer AS RECEIVED -- decode and the verification fingerprint
        # must both see the corrupted copy, exactly like real transfer
        # corruption.  Slot 3 (sched_count) is decode-inert, so only the
        # fingerprint cross-check can catch it.
        from armada_tpu.core import faults as _faults

        if _faults.active("round_corrupt", modes=("bytes",)):
            buf = buf.copy()
            buf[min(3, buf.size - 1)] ^= np.int32(1 << 20)
    return _parse_compact(buf, ctx, fcap, ecap)


def _parse_compact(buf: np.ndarray, ctx: HostContext, fcap: int, ecap: int):
    """Decode-input tuple from an already-fetched compact buffer (one pool's
    row).  Shared by the solo fetch above and the stacked fetch
    (begin_decode_stacked), which pulls ALL pools' rows in one transfer and
    parses each at its pool's decode turn.  Stashes the exact bytes on the
    ctx (HostContext.last_compact_np) for the verification fingerprint
    cross-check (models/verify.py)."""
    from armada_tpu.models.fair_scheduler import _COMPACT_HEADER

    ctx.last_compact_np = buf
    (
        n_slots, iterations, termination, _sched_count, spot_bits, n_failed,
        n_pre, n_res, kernel_iters,
    ) = (int(v) for v in buf[:_COMPACT_HEADER])
    if n_failed > fcap or n_pre > ecap or n_res > ecap:
        return None
    spot = float(np.int32(spot_bits).view(np.float32))
    S, W = ctx.max_slots, ctx.slot_width
    off = _COMPACT_HEADER
    slot_gang = buf[off : off + S]
    off += S
    slot_nodes = buf[off : off + S * W].reshape(S, W)
    off += S * W
    slot_counts = buf[off : off + S * W].reshape(S, W)
    off += S * W
    g2 = buf[off : off + n_failed]
    off += fcap
    pre_idx = buf[off : off + n_pre]
    off += ecap
    res_idx = buf[off : off + n_res]

    sched_set = set(int(g) for g in slot_gang[:n_slots])
    failed_set = set(int(g) for g in g2)

    def state_of(gi: int) -> int:
        if gi in sched_set:
            return 1
        return 2 if gi in failed_set else 0

    return (
        n_slots, slot_gang, slot_nodes, slot_counts, g2, pre_idx, res_idx,
        state_of, iterations, termination, spot, kernel_iters,
    )


def begin_decode(result, ctx: HostContext):
    """Start the decode WITHOUT blocking: enqueue the result compaction
    behind the round kernel and kick off its device->host copy, so the
    transfer streams as soon as the kernel finishes instead of waiting for a
    host sync + a fresh fetch round trip (each costs ~0.1s on the axon
    tunnel).  Returns a zero-arg callable producing the RoundOutcome; any
    decision-independent host work run between the two overlaps the kernel
    and the transfer.

    The returned callable carries two attributes for round verification
    (models/verify.py): ``finish.dispatched`` is the compact dispatch
    handle (the verification kernel fingerprints the SAME device buffer
    the decode transfer carries), and ``finish.fetch()`` performs JUST the
    blocking compact fetch (idempotent, one transfer however often it is
    called) -- the verification verdict runs between that fetch and the
    decode, so a corrupted round never reaches the host decode loops."""
    dispatched = _dispatch_compact(result, ctx)
    if dispatched is not None:
        try:
            dispatched[0].copy_to_host_async()
        except (AttributeError, RuntimeError):
            pass  # backend without async copies: finish() fetches normally

    box: dict = {}

    def fetch():
        if "v" not in box:
            box["v"] = _fetch_compact(result, ctx, dispatched=dispatched)
        return box["v"]

    def finish() -> RoundOutcome:
        return decode_result(result, ctx, _dispatched=dispatched, _fetched=fetch())

    finish.dispatched = dispatched
    finish.fetch = fetch
    return finish


_COMPACT_STACKED = None


def _compact_stacked():
    """jit(vmap(compact_result)) on first use -- the module stays importable
    without a jax backend (the begin_decode discipline)."""
    global _COMPACT_STACKED
    if _COMPACT_STACKED is None:
        import functools

        import jax

        from armada_tpu.models.fair_scheduler import compact_result

        @functools.partial(jax.jit, static_argnames=("fcap", "ecap"))
        def _stacked(result, gangs, runs, *, fcap, ecap):
            return jax.vmap(
                lambda r, g, n: compact_result(r, g, n, fcap=fcap, ecap=ecap)
            )(result, gangs, runs)

        _COMPACT_STACKED = _stacked
    return _COMPACT_STACKED


def begin_decode_stacked(result, ctxs: list):
    """begin_decode for a STACKED round (pool-parallel serving, round 17):
    `result` is a RoundResult whose every field carries a leading pool axis
    (fair_scheduler.schedule_round_stacked); `ctxs[i]` is pool i's
    HostContext.  ONE vmapped compaction and ONE [P, L] device->host
    transfer replace P separate compact fetches -- on the axon tunnel each
    transfer pays ~0.1s fixed latency, so the stack amortizes the decode
    leg the way the stacked launch amortizes the kernel leg.

    Returns a list of per-pool finish callables with begin_decode's API
    (``finish()`` -> RoundOutcome, ``finish.fetch()`` = the blocking fetch
    of THIS pool's row -- first caller pays the one shared transfer --
    ``finish.dispatched`` = the shared (buffer, fcap, ecap) handle), or
    None when the result is not a device RoundResult (the caller falls
    back to per-pool begin_decode on sliced lanes).  The stacked path
    never runs under a serving mesh (pool-parallel stacking is
    single-device; parallel/serving.py), so the GSPMD reduction gate in
    _dispatch_compact does not arise here.
    """
    import jax

    if not isinstance(result.g_state, jax.Array):
        return None
    G = int(result.g_state.shape[1])
    RJ = int(result.run_evicted.shape[1])
    fcap = min(G, _COMPACT_FCAP)
    ecap = min(RJ, _COMPACT_ECAP) if RJ else 0
    gangs = np.asarray([c.num_real_gangs for c in ctxs], np.int32)
    runs = np.asarray([c.num_real_runs for c in ctxs], np.int32)
    buf = _compact_stacked()(result, gangs, runs, fcap=fcap, ecap=ecap)
    try:
        buf.copy_to_host_async()
    except (AttributeError, RuntimeError):
        pass  # backend without async copies: the fetch blocks normally

    box: dict = {}

    def fetch_all() -> np.ndarray:
        if "all" not in box:
            arr = np.asarray(buf)
            from armada_tpu.models.xfer import TRANSFER_STATS

            TRANSFER_STATS.count_down(arr.nbytes)
            box["all"] = arr
        return box["all"]

    finishes = []
    for i, ctx in enumerate(ctxs):

        def fetch(i=i, ctx=ctx):
            if i not in box:
                box[i] = _parse_compact(fetch_all()[i], ctx, fcap, ecap)
            return box[i]

        def finish(i=i, ctx=ctx, fetch=fetch) -> RoundOutcome:
            fetched = fetch()
            # The compact tuple carries everything decode needs; the lane
            # slice of the stacked result materializes ONLY on the cap-
            # overflow fallback (eager per-field slices cost ~0.6ms of XLA
            # dispatch each on CPU -- 17 fields x P lanes of them erased
            # the stacking win before this was lazy).
            lane = None if fetched is not None else lane_slice(result, i)
            return decode_result(lane, ctx, _fetched=fetched)

        finish.dispatched = (buf, fcap, ecap)
        finish.fetch = fetch
        finish.stacked_index = i
        finishes.append(finish)
    return finishes


_LANE_SLICE = None


def lane_slice(tree, i: int):
    """Slice lane `i` out of a stacked pytree (RoundResult /
    SchedulingProblem) as ONE jitted program instead of one eager XLA
    dispatch per field -- the per-field form cost ~0.6ms x fields x lanes
    on the CPU backend."""
    global _LANE_SLICE
    if _LANE_SLICE is None:
        import functools

        import jax

        @functools.partial(jax.jit, static_argnames=("i",))
        def _slice(t, *, i):
            return jax.tree_util.tree_map(lambda a: a[i], t)

        _LANE_SLICE = _slice
    return _LANE_SLICE(tree, i=i)


_UNFETCHED = object()  # decode_result sentinel: None is a real fetch result


def decode_result(
    result, ctx: HostContext, _dispatched=None, _fetched=_UNFETCHED
) -> RoundOutcome:
    """Map device tensors back to job/node ids (the reference's SchedulerResult).

    Decode stays O(decisions) on the wire too: when the result lives on
    device, a jitted compaction packs failed/evicted indices + placement
    slots into one small buffer (fair_scheduler.compact_result) so the
    tunnel transfer is ~100KB instead of the [G] g_state pull.
    `_fetched` lets begin_decode hand over an already-fetched compact
    tuple (the verification flow fetches first, checks the verdict, then
    decodes) without paying or counting a second transfer."""
    compact = (
        _fetched
        if _fetched is not _UNFETCHED
        else _fetch_compact(result, ctx, dispatched=_dispatched)
    )
    if compact is not None:
        (
            n_slots, slot_gang, slot_nodes, slot_counts, g2, pre_idx, res_idx,
            state_of, iterations, termination, spot, kernel_iters,
        ) = compact
    else:
        g_state = np.asarray(result.g_state)
        slot_gang = np.asarray(result.slot_gang)
        slot_nodes = np.asarray(result.slot_nodes)
        slot_counts = np.asarray(result.slot_counts)
        n_slots = int(result.n_slots)
        run_resched = np.asarray(result.run_rescheduled)
        run_evicted = np.asarray(result.run_evicted)
        # Flag vectors first, Python only over the flagged indices: decode must
        # stay O(decisions), not O(backlog) -- a 1M-gang Python loop here would
        # cost the time the incremental builder saves.
        nr = ctx.num_real_runs
        ev = np.asarray(run_evicted[:nr], bool)
        rs = np.asarray(run_resched[:nr], bool)
        pre_idx = np.flatnonzero(ev & ~rs)
        res_idx = np.flatnonzero(ev & rs)
        g2 = np.flatnonzero(np.asarray(g_state[: ctx.num_real_gangs]) == 2)
        state_of = lambda gi: int(g_state[gi])  # noqa: E731
        iterations = int(result.iterations)
        kernel_iters = int(result.kernel_iters)
        termination = int(result.termination)
        spot = float(result.spot_price)

    scheduled: dict = {}
    for s in range(n_slots):
        gi = int(slot_gang[s])
        members = ctx.members_of(gi)
        mi = 0
        for w in range(ctx.slot_width):
            node = int(slot_nodes[s, w])
            for _ in range(int(slot_counts[s, w])):
                if mi < len(members):
                    scheduled[members[mi]] = ctx.node_ids[node]
                    mi += 1

    preempted = [ctx.run_job_id(int(ri)) for ri in pre_idx]
    rescheduled = [ctx.run_job_id(int(ri)) for ri in res_idx]

    if ctx.gang_members is None:
        # Vectorized path: a round can retire WHOLE unfeasible key classes
        # (g_state=2 en masse); per-id Python here cost seconds at 1M gangs,
        # so decode stays lazy until someone iterates.
        ids = ctx.gang_ids_vec[g2]
        extra = [
            m
            for gi, members in ctx.gang_members_over.items()
            if state_of(gi) == 2
            for m in members
        ]
        failed = LazyJobIds(ids[ids != b""], extra)
    else:
        failed = []
        for gi in g2:
            failed.extend(ctx.members_of(int(gi)))

    # Cross-class gang atomicity (gang_scheduler.go all-or-nothing): a
    # heterogeneous gang is split into per-key sub-gangs for the kernel; if
    # any sub-gang of a declared gang failed to place while a sibling placed,
    # unwind the placed siblings -- no half-gang may lease.  The statically-
    # hopeless case is killed before the round (build_problem `dead` + the
    # joint Hall check), so this backstop fires only on runtime capacity
    # contention.  The affected group tags are reported so the caller can
    # re-run the round WITHOUT the doomed gangs (run_scheduling_round):
    # evictions a now-unwound sibling triggered must not stand either -- the
    # reference rolls the whole gang txn back (nodedb.go:347).
    unwound = set()
    groups: dict = {}
    # Split-gang tags live only on multi-member units under the vectorized
    # representation; the list path may tag any gang.
    tagged = (
        ctx.gang_members_over.keys()
        if ctx.gang_members is None
        else range(ctx.num_real_gangs)
    )
    for gi in tagged:
        tag = ctx.gang_group[gi]
        if tag:
            groups.setdefault(tag, []).append(gi)
    for tag, gis in groups.items():
        states = {state_of(gi) for gi in gis}
        if 1 in states and states != {1}:
            unwound.add(tag)
            for gi in gis:
                if state_of(gi) == 1:
                    for jid in ctx.members_of(gi):
                        scheduled.pop(jid, None)
                        failed.append(jid)

    return RoundOutcome(
        scheduled=scheduled,
        preempted=preempted,
        rescheduled=rescheduled,
        failed=failed,
        num_iterations=iterations,
        kernel_iters=kernel_iters,
        termination=_TERMINATIONS[termination],
        spot_price=spot if spot >= 0 else None,
        unwound_groups=frozenset(unwound),
    )
