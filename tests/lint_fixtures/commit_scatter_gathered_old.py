# Fixture for rule `commit-scatter-gathered-old` (linted under
# armada_tpu/models/).  The twin line is syntactically IDENTICAL to the
# true positive after normalization; its where-fallback gathers a
# loop-INVARIANT row table (the sanctioned pass-rows idiom), not the
# scattered carry buffer itself -- only dataflow provenance (and base
# identity) separates them.
import jax
import jax.numpy as jnp


def run(cand_tab, rows, carry0):
    def body(c):
        i, state, other, done = c
        idx = cand_tab[i]
        state = state.at[idx].set(jnp.where(done, 1, state[idx]))  # TP
        other = other.at[idx].set(jnp.where(done, 1, rows[idx]))  # twin
        return (i + 1, state, other, done | (idx < 0))

    return jax.lax.while_loop(lambda c: ~c[3], body, carry0)
