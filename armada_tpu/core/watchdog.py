"""Device-loss watchdog: round deadlines, CPU failover state, re-probe.

The axon TPU tunnel's observed failure mode is a HANG, not an error: the
backend blocks on its chip claim indefinitely (it wedged for ALL of round
2), so a scheduler that calls the device inline wedges mid-round while
holding leadership -- a zombie leader.  bench.py already defends itself
(subprocess probe + labelled CPU fallback); this module extends the same
discipline to the production serve/sidecar paths:

* ``run_with_deadline`` runs the device round in a worker thread under a
  deadline; a timeout ABANDONS the wedged thread (no in-process recovery
  exists once the backend lock is held -- bench round-1 lesson) and raises
  ``RoundTimeout`` to the caller, which re-runs the round on the CPU
  backend from host tables (models.run_round_on_device).
* ``DeviceSupervisor`` is the process-wide degradation state: which backend
  rounds target ("device" = the default jax backend, "cpu" = the explicit
  XLA:CPU failover), consecutive failures, the last fallback reason.  A
  failure fires the registered reset hooks (device-resident caches must
  drop state that now lives on an unreachable or reset device) and starts
  a background re-probe -- a SUBPROCESS health check like bench's, because
  an in-process probe of a hung tunnel just hangs too -- which re-promotes
  to the device after N consecutive healthy checks, riding one full slab
  re-upload (the reset hooks fire again on promotion).

The state surfaces in /healthz (core/health.py), scheduler metrics, and
the bench JSON.  Knobs: ``ARMADA_WATCHDOG_S`` (round deadline; 0 =
disabled -- the default outside `serve`, which arms 120s),
``ARMADA_REPROBE_INTERVAL_S`` (default 30; 0 disables auto re-promotion),
``ARMADA_REPROBE_HEALTHY`` (consecutive healthy probes to promote, default
2), ``ARMADA_REPROBE_TIMEOUT_S`` (per-probe subprocess budget, default 60).
"""

from __future__ import annotations

import os
import sys
import threading
import time
import weakref
from typing import Callable, Optional

from armada_tpu.analysis.tsan import make_lock
from armada_tpu.core.logging import get_logger

_log = get_logger(__name__)


class RoundTimeout(RuntimeError):
    """The device round exceeded the watchdog deadline (tunnel wedge)."""


def probe_device(timeout_s: float = 60.0) -> tuple[bool, str]:
    """Subprocess health check of the default accelerator backend (the same
    shape as bench.py's probe: a hang is just a timeout out-of-process).
    Returns (healthy, detail)."""
    import subprocess

    code = (
        "import jax, jax.numpy as jnp;"
        "x = jnp.ones((128, 128), jnp.bfloat16);"
        "(x @ x).block_until_ready();"
        "print('PLATFORM=' + jax.devices()[0].platform)"
    )
    try:
        out = subprocess.run(
            [sys.executable, "-c", code],
            timeout=timeout_s,
            capture_output=True,
            text=True,
        )
    except subprocess.TimeoutExpired:
        return False, f"probe timed out after {timeout_s:.0f}s (tunnel hang)"
    if out.returncode == 0 and "PLATFORM=" in out.stdout:
        return True, out.stdout.split("PLATFORM=")[-1].strip()
    tail = (out.stderr or out.stdout).strip().splitlines()
    return False, (tail[-1] if tail else f"rc={out.returncode}")[:300]


def run_with_deadline(fn: Callable, deadline_s: float, what: str = "device round"):
    """Run fn() in a daemon worker; return its result, re-raise its
    exception, or abandon it and raise RoundTimeout after `deadline_s`.

    An abandoned worker is NOT cancelled (Python threads cannot be): it
    stays wedged on the dead backend and is forgotten.  Callers must only
    pass work whose host-side mutations are safe to abandon mid-flight
    (see models.run_round_on_device for the exact discipline)."""
    box: dict = {}
    # Cycle-trace adoption (ops/trace.py): the worker's spans (kernel
    # dispatch, fetch, shadow thunks) nest under the CALLER's open span,
    # exactly like the inline path -- without this they'd flatten onto the
    # cycle root and double-count as stages while the caller's round span
    # covers the same wall time.  The handle carries the owning trace so
    # an ABANDONED worker that unwedges after its cycle finalized records
    # nothing (the recorder's zombie guard).
    from armada_tpu.ops.trace import recorder as _trace_recorder

    _rec = _trace_recorder()
    _trace_handle = _rec.capture() if _rec.enabled else None

    def _worker():
        _rec.adopt(_trace_handle)
        try:
            box["result"] = fn()
        except BaseException as e:  # noqa: BLE001 - transported to caller
            box["error"] = e

    t = threading.Thread(target=_worker, daemon=True, name=f"watchdog:{what}")
    t.start()
    t.join(deadline_s)
    if t.is_alive():
        raise RoundTimeout(f"{what} exceeded {deadline_s:.1f}s deadline")
    if "error" in box:
        raise box["error"]
    return box["result"]


# Reset hooks live at MODULE level (not on the supervisor instance) so
# reset_supervisor() -- a test/embedding convenience -- cannot silently
# detach long-lived feeds from failover notifications.  Weak references:
# a closed control plane's feed must not be kept alive by the registry.
_reset_hooks: list = []
_hooks_lock = make_lock("watchdog.reset_hooks")


def add_reset_hook(fn: Callable[[], None]) -> None:
    """Register a callback fired on EVERY backend transition (device->cpu
    fallback and cpu->device promotion).  Bound methods are held weakly."""
    with _hooks_lock:
        try:
            ref = weakref.WeakMethod(fn)
        except TypeError:
            ref = weakref.ref(fn)
        _reset_hooks.append(ref)


def fire_reset_hooks() -> None:
    """Public form of the reset-hook broadcast, for OTHER backend-shaped
    transitions than the supervisor's own device<->cpu flips: the mesh
    serving ladder (parallel/serving.py) fires it on every degrade/restore
    rung, so device caches drop state sharded over a mesh that no longer
    exists exactly as they drop state on an unreachable device."""
    _fire_reset_hooks()


# Promotion gate: an optional () -> Optional[str] veto consulted before ANY
# re-promotion toward the accelerator (DeviceSupervisor.promote and the mesh
# restore, parallel/serving.MeshServing.restore).  Registered by the device
# quarantine (scheduler/quarantine.device_quarantine): a device whose rounds
# keep failing output verification must not be re-promoted by a healthy
# matmul probe -- a probe cannot see silent corruption.  Module-level like
# the reset hooks, so reset_supervisor() cannot silently detach it.
_promotion_gate: Optional[Callable[[], Optional[str]]] = None


def set_promotion_gate(fn: Optional[Callable[[], Optional[str]]]) -> None:
    global _promotion_gate
    _promotion_gate = fn


def promotion_blocked() -> Optional[str]:
    """The gate's veto reason, or None (no gate / not blocked).  A raising
    gate never blocks -- quarantine must not be able to wedge recovery --
    but the fail-open is LOGGED loudly: silently re-promoting a device the
    gate was holding down would invert the gate's purpose."""
    gate = _promotion_gate
    if gate is None:
        return None
    try:
        return gate()
    except Exception:
        _log.error(
            "promotion gate raised; failing OPEN (promotion allowed)",
            exc_info=True,
        )
        return None


def _fire_reset_hooks() -> None:
    with _hooks_lock:
        hooks = list(_reset_hooks)
    for ref in hooks:
        fn = ref()
        if fn is None:
            continue
        try:
            fn()
        except Exception:
            _log.warning("device reset hook failed", exc_info=True)
    with _hooks_lock:
        _reset_hooks[:] = [r for r in _reset_hooks if r() is not None]


class DeviceSupervisor:
    """Process-wide device-backend health state."""

    def __init__(self):
        self._lock = make_lock("watchdog.supervisor")
        self.backend = "device"  # "device" = default jax backend
        self.consecutive_failures = 0
        self.fallbacks = 0
        self.promotions = 0
        self.last_failure: Optional[str] = None
        self.last_fallback_ts: Optional[float] = None
        self._deadline_s: Optional[float] = None
        self._armings: dict[int, float] = {}
        self._arm_seq = 0
        self._reprobe_interval_s: Optional[float] = None
        self._healthy_checks: Optional[int] = None
        self._probe = probe_device  # patchable in tests
        self._reprobe_thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------ config ----

    def configure(
        self,
        deadline_s: Optional[float] = None,
        reprobe_interval_s: Optional[float] = None,
        healthy_checks: Optional[int] = None,
    ) -> None:
        """Explicit settings beat the env defaults (serve calls this)."""
        if deadline_s is not None:
            self._deadline_s = float(deadline_s)
        if reprobe_interval_s is not None:
            self._reprobe_interval_s = float(reprobe_interval_s)
        if healthy_checks is not None:
            self._healthy_checks = int(healthy_checks)

    def arm(self, deadline_s: float) -> int:
        """Scoped arming for a control plane living inside a larger
        process: returns a token for disarm().  Reference-counted, NOT
        save/restore -- planes overlap and stop in any order (HA tests run
        leader+follower and kill the leader first), so each registers its
        own deadline and deadline_s() takes the max over live registrations;
        when the last plane disarms, the env default is back in force."""
        with self._lock:
            self._arm_seq += 1
            token = self._arm_seq
            self._armings[token] = float(deadline_s)
        return token

    def disarm(self, token: int) -> None:
        with self._lock:
            self._armings.pop(token, None)

    def deadline_s(self) -> float:
        """The armed round deadline; <= 0 means the watchdog is disabled
        (the default outside serve: tests/bench keep the inline path)."""
        with self._lock:
            if self._armings:
                return max(self._armings.values())
        if self._deadline_s is not None:
            return self._deadline_s
        try:
            return float(os.environ.get("ARMADA_WATCHDOG_S", "0"))
        except ValueError:
            return 0.0

    def reprobe_interval_s(self) -> float:
        if self._reprobe_interval_s is not None:
            return self._reprobe_interval_s
        try:
            return float(os.environ.get("ARMADA_REPROBE_INTERVAL_S", "30"))
        except ValueError:
            return 30.0

    def healthy_checks(self) -> int:
        if self._healthy_checks is not None:
            return self._healthy_checks
        try:
            return int(os.environ.get("ARMADA_REPROBE_HEALTHY", "2"))
        except ValueError:
            return 2

    @property
    def degraded(self) -> bool:
        return self.backend == "cpu"

    # ------------------------------------------------------- transitions ----

    def record_failure(self, reason: str) -> None:
        """A device round failed (timeout/XLA error): degrade to the CPU
        backend, drop device-resident cache state, start the re-probe."""
        with self._lock:
            self.consecutive_failures += 1
            self.fallbacks += 1
            self.last_failure = str(reason)[:500]
            self.last_fallback_ts = time.time()
            was_degraded = self.backend == "cpu"
            self.backend = "cpu"
        _log.error(
            "device round failed (%s); scheduling degraded to the CPU "
            "backend (failure %d)",
            reason,
            self.consecutive_failures,
        )
        # Hooks fire on the TRANSITION and on repeat failures alike: a
        # CPU-mode failure still means the caches' device state is suspect.
        _fire_reset_hooks()
        if not was_degraded or self._reprobe_thread is None:
            self._start_reprobe()

    def record_success(self) -> None:
        with self._lock:
            self.consecutive_failures = 0

    def promote(self) -> bool:
        """Re-promote rounds to the device backend; device caches were
        reset, so the next cycle rides one full slab re-upload.  Returns
        False (and stays degraded) while the promotion gate vetoes --
        a quarantined device is only re-admitted by operator clear
        (scheduler/quarantine.py); the re-probe loop keeps polling so the
        clear takes effect on the next healthy probe."""
        blocked = promotion_blocked()
        if blocked:
            _log.warning(
                "device backend probes healthy but promotion is blocked: %s",
                blocked,
            )
            return False
        with self._lock:
            if self.backend == "device":
                return True
            self.backend = "device"
            self.consecutive_failures = 0
            self.promotions += 1
        _log.warning(
            "device backend healthy again: re-promoting (next cycle pays "
            "one full slab re-upload)"
        )
        _fire_reset_hooks()
        return True

    # ----------------------------------------------------------- reprobe ----

    def _start_reprobe(self) -> None:
        interval = self.reprobe_interval_s()
        if interval <= 0:
            return  # operator/tests promote manually
        with self._lock:
            if self._reprobe_thread is not None and self._reprobe_thread.is_alive():
                return
            t = threading.Thread(
                target=self._reprobe_loop, daemon=True, name="device-reprobe"
            )
            self._reprobe_thread = t
        t.start()

    def _reprobe_loop(self) -> None:
        timeout = float(os.environ.get("ARMADA_REPROBE_TIMEOUT_S", "60"))
        healthy = 0
        need = self.healthy_checks()
        while self.degraded:
            time.sleep(self.reprobe_interval_s())
            if not self.degraded:
                break
            ok, detail = self._probe(timeout)
            if ok:
                healthy += 1
                _log.info(
                    "device re-probe healthy (%s): %d/%d", detail, healthy, need
                )
                if healthy >= need and self.promote():
                    break
                # gate-blocked (quarantine): keep polling at the probe
                # cadence so an operator clear promotes on the next pass
            else:
                healthy = 0
                _log.info("device re-probe still failing: %s", detail)
        with self._lock:
            self._reprobe_thread = None

    # ------------------------------------------------------------ export ----

    def snapshot(self) -> dict:
        # deadline_s() takes the lock itself (the armings map): resolve it
        # BEFORE entering, the lock is not reentrant.
        deadline = self.deadline_s()
        with self._lock:
            return {
                "backend": self.backend,
                "consecutive_failures": self.consecutive_failures,
                "fallbacks": self.fallbacks,
                "promotions": self.promotions,
                "last_fallback_reason": self.last_failure,
                "last_fallback_ts": self.last_fallback_ts,
                "watchdog_deadline_s": deadline,
            }


_SUPERVISOR = DeviceSupervisor()


def supervisor() -> DeviceSupervisor:
    return _SUPERVISOR


def reset_supervisor() -> DeviceSupervisor:
    """Fresh supervisor state (tests).  Reset hooks are module-level and
    survive; in-flight reprobe threads of the old instance die with its
    `degraded` flag flipping false-y only on their next poll, so tests
    should keep reprobe_interval_s small or 0."""
    global _SUPERVISOR
    _SUPERVISOR = DeviceSupervisor()
    return _SUPERVISOR


def data_device():
    """Where device-resident problem data should live right now: None =
    the default jax backend; an explicit jax CPU device while degraded
    (models/slab.py routes every upload through this, so the delta cache
    keeps its O(delta) scatter path during CPU-failover operation)."""
    if not _SUPERVISOR.degraded:
        return None
    import jax

    return jax.devices("cpu")[0]
