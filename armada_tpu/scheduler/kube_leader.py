"""Kubernetes Lease leader election for replicated scheduler deployments.

Equivalent of the reference's KubernetesLeaderController
(internal/scheduler/leader/leader.go:112-186), which runs client-go's
leaderelection over a coordination.k8s.io/v1 Lease.  The same protocol,
hand-rolled over the kube REST API:

  * acquire: create the Lease if absent; take it over when the holder's
    renewTime is older than leaseDurationSeconds; otherwise follow.
  * renew: update renewTime while holding.
  * fencing: every acquisition bumps `leaseTransitions`, which doubles as the
    token generation -- a cycle begun under generation g must not publish
    once any replica has acquired generation > g (scheduler.go:263,355).
  * races: all writes send `metadata.resourceVersion` as an optimistic
    precondition; the apiserver answers 409 to the loser, exactly the fence
    client-go relies on.

Satisfies the same LeaderController protocol as Standalone/FileLease
(scheduler/leader.py); wire with
`armadactl serve --leader-id <holder> --kube-lease-url <apiserver>`
(in-cluster service-account credentials are picked up automatically).
"""

from __future__ import annotations

import json
import ssl
import time
import urllib.error
import urllib.request
from typing import Callable, Optional

from armada_tpu.scheduler.leader import LeaderToken

_RFC3339 = "%Y-%m-%dT%H:%M:%S.%fZ"
_ADDRESS_ANNOTATION = "armada-tpu.io/advertised-address"


class KubeApiError(Exception):
    def __init__(self, status: int, message: str):
        super().__init__(f"kube api {status}: {message}")
        self.status = status


class KubernetesLeaseLeaderController:
    def __init__(
        self,
        base_url: str,
        holder_id: str,
        *,
        namespace: str = "default",
        lease_name: str = "armada-tpu-scheduler",
        lease_duration_s: float = 15.0,
        token: Optional[str] = None,
        token_file: Optional[str] = None,
        ca_file: Optional[str] = None,
        insecure: bool = False,
        timeout_s: float = 10.0,
        clock: Callable[[], float] = time.time,
        advertised_address: str = "",
    ):
        self._base = base_url.rstrip("/")
        self._holder = holder_id
        # Rides a Lease annotation so followers can proxy leader-local
        # queries (reports) -- the analog of the reference deriving the
        # leader pod's DNS from holderIdentity (leader_client.go).
        self._address = advertised_address
        self._path = (
            f"/apis/coordination.k8s.io/v1/namespaces/{namespace}/leases/{lease_name}"
        )
        self._create_path = (
            f"/apis/coordination.k8s.io/v1/namespaces/{namespace}/leases"
        )
        self._name = lease_name
        self._duration = lease_duration_s
        self._token = token
        # Bound service-account tokens expire (~1h) and the kubelet rotates
        # the mounted file; read it per request like client-go does -- a
        # token captured once at startup breaks election an hour in.
        self._token_file = token_file
        self._timeout = timeout_s
        self._clock = clock
        if base_url.startswith("https"):
            ctx = ssl.create_default_context(cafile=ca_file)
            if insecure:
                ctx.check_hostname = False
                ctx.verify_mode = ssl.CERT_NONE
            self._ssl = ctx
        else:
            self._ssl = None
        # Expiry is judged by how long since WE first observed the holder's
        # current (holder, renewTime, transitions) record -- client-go's
        # observedTime -- never by comparing the remote renewTime timestamp
        # against the local clock, which flaps leadership under clock skew.
        self._observed: Optional[tuple] = None
        self._observed_at: float = 0.0
        # Leader address as of the last lease read/write: leader_address()
        # serves from this cache (query paths must not block on the
        # apiserver, and an apiserver blip must not fail the LEADER's own
        # local queries).  Refreshed every get_token (once per cycle).
        self._last_seen_address: str = ""

    # ------------------------------------------------------------- http ----

    def _request(self, method: str, path: str, body=None):
        req = urllib.request.Request(
            self._base + path,
            data=json.dumps(body).encode() if body is not None else None,
            method=method,
        )
        if body is not None:
            req.add_header("Content-Type", "application/json")
        token = self._token
        if self._token_file:
            try:
                with open(self._token_file) as f:
                    token = f.read().strip()
            except OSError:
                pass
        if token:
            req.add_header("Authorization", f"Bearer {token}")
        try:
            with urllib.request.urlopen(
                req, timeout=self._timeout, context=self._ssl
            ) as resp:
                return json.loads(resp.read() or b"{}")
        except urllib.error.HTTPError as e:
            raise KubeApiError(e.code, e.read().decode(errors="replace")) from e
        except urllib.error.URLError as e:
            raise KubeApiError(0, str(e.reason)) from e

    # ------------------------------------------------------------ lease ----

    def _now_str(self) -> str:
        now = self._clock()  # single read: two reads straddling a second
        # boundary would encode a renewTime up to ~1s stale
        return time.strftime(
            "%Y-%m-%dT%H:%M:%S", time.gmtime(now)
        ) + ".%06dZ" % int((now % 1) * 1e6)

    def _observe(
        self, holder: str, renew: str, transitions: int, duration: float
    ) -> bool:
        """True when the holder's record has gone unrenewed for a full lease
        duration ON OUR CLOCK since we first saw it (client-go measures time
        since observedRecord last changed, not renewTime vs local now)."""
        record = (holder, renew, transitions)
        if record != self._observed:
            self._observed = record
            self._observed_at = self._clock()
            return False
        return self._clock() >= self._observed_at + duration

    def set_advertised_address(self, address: str) -> None:
        self._address = address  # picked up by the next acquire/renew write

    def leader_address(self) -> Optional[str]:
        """Read-only peek from the election state the cycle loop already
        maintains (NO apiserver round trip: report queries would otherwise
        each pay a blocking GET, and an apiserver blip would fail even the
        leader's own local queries).  None = we hold the lease, address =
        another holder advertises one, "" = unknown/no address (see
        leader.py LeaderController protocol).  Staleness is bounded by the
        cycle interval (get_token refreshes every cycle)."""
        if self._observed is None:
            return ""  # no election state observed yet
        if self._observed[0] == self._holder:
            return None
        return self._last_seen_address or ""

    def current_generation(self) -> int:
        """Read-only epoch peek from the same observed election state
        leader_address() uses (no apiserver round trip on the publish path;
        staleness is bounded by the cycle interval, and validate_token's
        apiserver re-check still backstops the fence)."""
        if self._observed is None:
            return 0
        return int(self._observed[2])

    def _spec(self, transitions: int) -> dict:
        return {
            "holderIdentity": self._holder,
            "leaseDurationSeconds": int(self._duration),
            "renewTime": self._now_str(),
            "leaseTransitions": transitions,
        }

    def get_token(self) -> LeaderToken:
        try:
            lease = self._request("GET", self._path)
        except KubeApiError as e:
            if e.status != 404:
                # apiserver unreachable: fail SAFE as follower (the reference
                # drops leadership when it cannot renew, leader.go:171-178)
                return LeaderToken(leader=False, generation=0)
            try:
                created = self._request(
                    "POST",
                    self._create_path,
                    {
                        "apiVersion": "coordination.k8s.io/v1",
                        "kind": "Lease",
                        "metadata": {
                            "name": self._name,
                            "annotations": {
                                _ADDRESS_ANNOTATION: self._address
                            },
                        },
                        "spec": self._spec(transitions=1),
                    },
                )
                self._note_acquired(created["spec"])
                return LeaderToken(
                    leader=True,
                    generation=created["spec"].get("leaseTransitions", 1),
                )
            except KubeApiError:
                # 409 = lost the creation race; anything else = follow and
                # retry next cycle
                return LeaderToken(leader=False, generation=0)

        spec = lease.get("spec", {})
        holder = spec.get("holderIdentity", "")
        transitions = int(spec.get("leaseTransitions", 0))
        renew = spec.get("renewTime")
        duration = float(spec.get("leaseDurationSeconds", self._duration))
        self._last_seen_address = (
            lease.get("metadata", {})
            .get("annotations", {})
            .get(_ADDRESS_ANNOTATION, "")
        )
        expired = renew is None or self._observe(holder, renew, transitions, duration)
        if holder == self._holder or expired:
            new_transitions = transitions if holder == self._holder else transitions + 1
            lease["spec"] = self._spec(new_transitions)
            lease.setdefault("metadata", {}).setdefault("annotations", {})[
                _ADDRESS_ANNOTATION
            ] = self._address
            try:
                updated = self._request("PUT", self._path, lease)
            except KubeApiError as e:
                if e.status == 409:  # another replica won the takeover race
                    return LeaderToken(leader=False, generation=transitions)
                return LeaderToken(leader=False, generation=transitions)
            self._note_acquired(updated["spec"])
            return LeaderToken(
                leader=True,
                generation=int(updated["spec"].get("leaseTransitions", new_transitions)),
            )
        return LeaderToken(leader=False, generation=transitions)

    def _note_acquired(self, spec: dict) -> None:
        """After a successful acquire/renew WE are the observed holder:
        leader_address() must answer None (serve locally) immediately, not
        report the pre-takeover holder's address until the next cycle."""
        self._observed = (
            self._holder,
            spec.get("renewTime"),
            int(spec.get("leaseTransitions", 0)),
        )
        self._observed_at = self._clock()
        self._last_seen_address = self._address

    def validate_token(self, token: LeaderToken) -> bool:
        if not token.leader:
            return False
        try:
            lease = self._request("GET", self._path)
        except KubeApiError:
            return False
        spec = lease.get("spec", {})
        holder = spec.get("holderIdentity", "")
        transitions = int(spec.get("leaseTransitions", 0))
        renew = spec.get("renewTime")
        duration = float(spec.get("leaseDurationSeconds", self._duration))
        return (
            holder == self._holder
            and transitions == token.generation
            and renew is not None
            and not self._observe(holder, renew, transitions, duration)
        )
