# Fixture for rule `f64-score` (linted as armada_tpu/models/fair_scheduler.py).
import jax.numpy as jnp


def score_rows(score, req):
    widened = score.astype(jnp.float64)  # TP
    # near-miss: f32 is the kernel's score dtype
    ok = score.astype(jnp.float32)
    # near-miss: int64 capacity math is exact and allowed
    units = req.astype(jnp.int64)
    return widened + ok.sum() + units.sum()
