import numpy as np
import pytest

from armada_tpu.ops.fairness import (
    fair_shares,
    unweighted_drf_cost,
    weighted_drf_cost,
)


def reference_water_fill(weights, cds, max_iterations=10):
    """Direct transcription of the reference loop semantics
    (context/scheduling.go:220-300) in plain Python, used as the oracle."""
    q = len(weights)
    achieved = [False] * q
    spare = [0.0] * q
    dcafs = [0.0] * q
    ucafs = [0.0] * q
    unallocated = 1.0
    for _ in range(max_iterations):
        if unallocated <= 0.01:
            break
        total_weight = sum(w for w, a in zip(weights, achieved) if not a)
        for i in range(q):
            denom = total_weight + (weights[i] if achieved[i] else 0.0)
            if denom > 0:
                ucafs[i] += (weights[i] / denom) * (unallocated - spare[i])
        if total_weight <= 0:
            break
        for i in range(q):
            if not achieved[i]:
                dcafs[i] += (weights[i] / total_weight) * unallocated
        unallocated = 0.0
        for i in range(q):
            s = dcafs[i] - cds[i]
            if s > 0:
                dcafs[i] = cds[i]
                achieved[i] = True
                spare[i] = s
                unallocated += s
            else:
                spare[i] = 0.0
    return dcafs, ucafs


def test_drf_cost_basics():
    total = np.array([100.0, 10.0, 0.0], np.float32)
    mult = np.array([1.0, 1.0, 1.0], np.float32)
    alloc = np.array([50.0, 1.0, 5.0], np.float32)
    # dominant resource: 50/100 = 0.5; zero-total resource contributes 0.
    assert float(unweighted_drf_cost(alloc, total, mult)) == pytest.approx(0.5)
    assert float(weighted_drf_cost(alloc, total, mult, 2.0)) == pytest.approx(0.25)
    # multiplier scales a resource's contribution
    mult2 = np.array([0.0, 1.0, 1.0], np.float32)
    assert float(unweighted_drf_cost(alloc, total, mult2)) == pytest.approx(0.1)
    # negative allocations clamp to zero cost
    assert float(unweighted_drf_cost(-alloc, total, mult)) == 0.0


@pytest.mark.parametrize(
    "weights,cds",
    [
        ([1.0, 1.0], [1.0, 1.0]),  # both saturated: 50/50
        ([1.0, 1.0], [0.1, 1.0]),  # q0 undemanding: spare reshared to q1
        ([3.0, 1.0], [1.0, 1.0]),  # weighted split
        ([1.0, 2.0, 1.0], [0.05, 0.3, 1.0]),  # cascade of reshares
        ([1.0, 1.0, 0.0], [1.0, 1.0, 0.0]),  # padding queue with zero weight
        ([2.0], [0.5]),  # single queue, capped by demand
        ([1.0, 1.0], [0.0, 0.0]),  # nobody demands anything
    ],
)
def test_water_filling_matches_reference_semantics(weights, cds):
    got = fair_shares(np.array(weights, np.float32), np.array(cds, np.float32))
    want_dcafs, want_ucafs = reference_water_fill(weights, cds)
    np.testing.assert_allclose(
        np.asarray(got.demand_capped_adjusted_fair_share), want_dcafs, atol=1e-5
    )
    np.testing.assert_allclose(
        np.asarray(got.uncapped_adjusted_fair_share), want_ucafs, atol=1e-5
    )
    wsum = sum(weights)
    want_fs = [w / wsum if wsum else 0.0 for w in weights]
    np.testing.assert_allclose(np.asarray(got.fair_share), want_fs, atol=1e-6)


def test_water_filling_reshare_direction():
    # An undemanding queue's unused share flows to the demanding one.
    got = fair_shares(
        np.array([1.0, 1.0], np.float32), np.array([0.1, 1.0], np.float32)
    )
    dcafs = np.asarray(got.demand_capped_adjusted_fair_share)
    assert dcafs[0] == pytest.approx(0.1, abs=1e-5)
    assert dcafs[1] == pytest.approx(0.9, abs=1e-5)
    # Uncapped share is not punished for low demand.
    ucafs = np.asarray(got.uncapped_adjusted_fair_share)
    assert ucafs[0] >= 0.5 - 1e-5
