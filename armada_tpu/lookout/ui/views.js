// Server-side saved views (lookout DB saved_view table -- the reference
// UI's server-backed job-table views).
import { $, esc } from "./util.js";
import { j } from "./api.js";

let serverViews = {};

export async function loadViews() {
  try {
    const d = await j("/api/views");
    serverViews = Object.fromEntries(
      d.views.map((v) => [v.name, JSON.parse(v.payload)]));
  } catch (e) { serverViews = {}; }
  renderViews();
}

function renderViews() {
  const sel = $("views").value;
  $("views").innerHTML = '<option value="">saved views…</option>' +
    Object.keys(serverViews).sort().map((n) =>
      `<option value="${esc(n)}">${esc(n)}</option>`).join("");
  if (serverViews[sel] !== undefined) $("views").value = sel;
}

export function wireViews(state, refresh) {
  $("save-view").onclick = async () => {
    const name = prompt("view name:");
    if (!name) return;
    const payload = Object.fromEntries(
      ["f-queue", "f-jobset", "f-state", "f-ann", "f-group", "f-groupkey"]
        .map((id) => [id, $(id).value]));
    await fetch("/api/views", {
      method: "POST", headers: {"Content-Type": "application/json"},
      body: JSON.stringify({name, payload}),
    });
    await loadViews();
    $("views").value = name;
  };
  $("del-view").onclick = async () => {
    const name = $("views").value;
    if (!name || !confirm(`delete view "${name}"?`)) return;
    await fetch("/api/views/" + encodeURIComponent(name), {method: "DELETE"});
    $("views").value = "";
    await loadViews();
  };
  $("views").addEventListener("change", () => {
    const v = serverViews[$("views").value];
    if (!v) return;
    for (const [id, val] of Object.entries(v)) { if ($(id)) $(id).value = val; }
    $("f-groupkey").style.display =
      $("f-group").value === "annotation" ? "" : "none";
    state.drill = [];
    state.skip = 0;
    refresh();
  });
}
