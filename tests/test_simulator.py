"""Simulator macro-behavior tests, modeled on the reference's
internal/scheduler/simulator/simulator_test.go: YAML-specified clusters +
workloads, assertions about completion, fair shares and preemption counts."""

import yaml
import pytest

from armada_tpu.core.config import PriorityClass, SchedulingConfig
from armada_tpu.simulator import (
    Simulator,
    cluster_spec_from_dict,
    parse_duration,
    workload_spec_from_dict,
)


def sim_config(**overrides) -> SchedulingConfig:
    base = dict(
        supported_resource_types=(("memory", "1Mi"), ("cpu", "1m"), ("nvidia.com/gpu", "1")),
        priority_classes={
            "armada-default": PriorityClass("armada-default", priority=1000, preemptible=False),
            "armada-preemptible": PriorityClass("armada-preemptible", priority=900, preemptible=True),
        },
        default_priority_class="armada-default",
        dominant_resource_fairness_resources=("cpu", "memory", "nvidia.com/gpu"),
        shape_bucket=8,
        maximum_scheduling_burst=10_000,
        maximum_per_queue_scheduling_burst=10_000,
        maximum_resource_fraction_to_schedule={},
    )
    base.update(overrides)
    return SchedulingConfig(**base)


def cluster(yaml_text: str):
    return cluster_spec_from_dict(yaml.safe_load(yaml_text))


def workload(yaml_text: str):
    return workload_spec_from_dict(yaml.safe_load(yaml_text))


TINY_CLUSTER = """
name: tiny
clusters:
  - name: c0
    pool: cpu
    nodeTemplates:
      - number: 2
        totalResources:
          resources: {cpu: "16", memory: "64Gi"}
"""

BASIC_WORKLOAD = """
name: basic
randomSeed: 42
queues:
  - name: A
    weight: 1
    jobTemplates:
      - id: tA
        number: 10
        priorityClassName: armada-default
        requirements:
          resourceRequirements:
            requests: {cpu: 1, memory: 2Gi}
        runtimeDistribution: {minimum: "5m"}
"""


def test_parse_duration():
    assert parse_duration("5m") == 300.0
    assert parse_duration("300ms") == 0.3
    assert parse_duration("1h30m") == 5400.0
    assert parse_duration(42) == 42.0
    assert parse_duration(None) == 0.0


def test_basic_workload_all_succeed():
    sim = Simulator(cluster(TINY_CLUSTER), workload(BASIC_WORKLOAD), sim_config())
    result = sim.run()
    assert result.total_succeeded == 10
    assert result.never_scheduled == []
    assert result.total_failed == 0
    # 32 cpus, 10 1-cpu jobs: all fit at once; makespan ~ one runtime
    assert result.makespan == pytest.approx(300.0, abs=1.0)


def test_capacity_contention_serializes():
    """40 jobs x 8 cpu on 32 cpus: 4 waves of ~10 -> makespan ~ 4 runtimes."""
    wl = workload(
        """
name: waves
randomSeed: 1
queues:
  - name: A
    weight: 1
    jobTemplates:
      - id: tA
        number: 16
        requirements:
          resourceRequirements:
            requests: {cpu: 8, memory: 1Gi}
        runtimeDistribution: {minimum: "10m"}
"""
    )
    sim = Simulator(cluster(TINY_CLUSTER), wl, sim_config())
    result = sim.run()
    assert result.total_succeeded == 16
    # 4 jobs fit at a time (32/8) -> 4 waves x 600s
    assert result.makespan == pytest.approx(4 * 600.0, rel=0.1)


def test_two_queue_fair_share_over_time():
    wl = workload(
        """
name: contention
randomSeed: 7
queues:
  - name: A
    weight: 1
    jobTemplates:
      - id: tA
        number: 40
        requirements:
          resourceRequirements:
            requests: {cpu: 4, memory: 1Gi}
        runtimeDistribution: {minimum: "10m"}
  - name: B
    weight: 1
    jobTemplates:
      - id: tB
        number: 40
        requirements:
          resourceRequirements:
            requests: {cpu: 4, memory: 1Gi}
        runtimeDistribution: {minimum: "10m"}
"""
    )
    sim = Simulator(cluster(TINY_CLUSTER), wl, sim_config())
    result = sim.run()
    assert result.total_succeeded == 80
    # while both queues are backlogged, each should hold ~half the cpus
    mid = [c for c in result.cycles if c.queued_after > 8]
    assert mid, "expected contended cycles"
    for c in mid:
        a = c.share_by_queue.get("A", 0.0)
        b = c.share_by_queue.get("B", 0.0)
        if a + b > 0.9:  # cluster saturated
            assert abs(a - b) < 0.15


def test_preemption_rebalances_late_queue():
    cfg = sim_config(protected_fraction_of_fair_share=0.5)
    wl = workload(
        """
name: preempt
randomSeed: 3
queues:
  - name: hog
    weight: 1
    jobTemplates:
      - id: th
        number: 8
        priorityClassName: armada-preemptible
        requirements:
          resourceRequirements:
            requests: {cpu: 4, memory: 1Gi}
        runtimeDistribution: {minimum: "2h"}
  - name: late
    weight: 1
    jobTemplates:
      - id: tl
        number: 8
        priorityClassName: armada-preemptible
        earliestSubmitTime: "15m"
        requirements:
          resourceRequirements:
            requests: {cpu: 4, memory: 1Gi}
        runtimeDistribution: {minimum: "2h"}
"""
    )
    sim = Simulator(cluster(TINY_CLUSTER), wl, cfg)
    result = sim.run()
    # hog fills the cluster; when late arrives, fair-share eviction frees half
    assert result.total_preempted >= 2
    late_start = min(
        t for t, kind, jid in result.events if kind == "leased" and jid.startswith("tl")
    )
    assert late_start < parse_duration("30m") + 1
    assert result.total_succeeded == 16  # preempted jobs retry and finish


def test_gang_workload_schedules_atomically():
    wl = workload(
        """
name: gangs
randomSeed: 5
queues:
  - name: G
    weight: 1
    jobTemplates:
      - id: tg
        number: 8
        gangCardinality: 4
        requirements:
          resourceRequirements:
            requests: {cpu: 8, memory: 1Gi}
        runtimeDistribution: {minimum: "5m"}
"""
    )
    sim = Simulator(cluster(TINY_CLUSTER), wl, sim_config())
    result = sim.run()
    assert result.total_succeeded == 8
    # each gang of 4x8cpu = 32 cpus = whole cluster: gangs run one at a time,
    # and each gang's 4 members lease at the same instant
    gang_starts = {}
    for t, kind, jid in result.events:
        if kind == "leased":
            idx = int(jid.rsplit("-", 1)[1])
            gang_starts.setdefault(idx // 4, set()).add(t)
    assert all(len(starts) == 1 for starts in gang_starts.values())


def test_dependencies_run_in_order():
    wl = workload(
        """
name: dag
randomSeed: 9
queues:
  - name: D
    weight: 1
    jobTemplates:
      - id: stage1
        number: 4
        requirements:
          resourceRequirements:
            requests: {cpu: 1, memory: 1Gi}
        runtimeDistribution: {minimum: "5m"}
      - id: stage2
        number: 4
        dependencies: [stage1]
        earliestSubmitTimeFromDependencyCompletion: "1m"
        requirements:
          resourceRequirements:
            requests: {cpu: 1, memory: 1Gi}
        runtimeDistribution: {minimum: "5m"}
"""
    )
    sim = Simulator(cluster(TINY_CLUSTER), wl, sim_config())
    result = sim.run()
    assert result.total_succeeded == 8
    s1_done = max(t for t, k, j in result.events if k == "succeeded" and j.startswith("stage1"))
    s2_start = min(t for t, k, j in result.events if k == "submitted" and j.startswith("stage2"))
    assert s2_start == pytest.approx(s1_done + 60.0, abs=1.0)


def test_repeat_template_resubmits():
    wl = workload(
        """
name: repeat
randomSeed: 11
queues:
  - name: R
    weight: 1
    jobTemplates:
      - id: tr
        number: 2
        repeat: {numTimes: 3, period: "30m"}
        requirements:
          resourceRequirements:
            requests: {cpu: 1, memory: 1Gi}
        runtimeDistribution: {minimum: "1m"}
"""
    )
    sim = Simulator(cluster(TINY_CLUSTER), wl, sim_config())
    result = sim.run()
    assert result.total_succeeded == 6  # 2 jobs x 3 submissions
    submits = sorted(t for t, k, j in result.events if k == "submitted")
    assert submits[0] == 0.0 and submits[-1] == pytest.approx(3600.0, abs=1.0)


def test_determinism_same_seed():
    a = Simulator(cluster(TINY_CLUSTER), workload(BASIC_WORKLOAD), sim_config()).run()
    b = Simulator(cluster(TINY_CLUSTER), workload(BASIC_WORKLOAD), sim_config()).run()
    assert a.makespan == b.makespan
    assert a.events == b.events
