"""gRPC transport for the control plane.

`rpc_pb2` is regenerated from rpc.proto with protoc when the .proto is newer
than the generated module (same lazy-codegen pattern as armada_tpu.events).
grpc_tools is not in this toolchain, so service stubs are hand-wired with
grpc generic handlers (server.py) and channel.unary_unary (client.py) --
functionally identical to generated code.
"""

import os
import shutil
import subprocess
import tempfile

_HERE = os.path.dirname(os.path.abspath(__file__))
_EVENTS_DIR = os.path.join(_HERE, os.pardir, "events")
_PROTO = os.path.join(_HERE, "rpc.proto")
_GEN = os.path.join(_HERE, "rpc_pb2.py")

# Ensure events_pb2 exists first (rpc.proto imports events.proto).
import armada_tpu.events  # noqa: F401,E402

if not os.path.exists(_GEN) or os.path.getmtime(_PROTO) > os.path.getmtime(_GEN):
    with tempfile.TemporaryDirectory() as _tmp:
        src_path = os.path.join(_tmp, "rpc_pb2.py")
        if shutil.which("protoc"):
            subprocess.run(
                [
                    "protoc",
                    "-I",
                    _HERE,
                    "-I",
                    _EVENTS_DIR,
                    f"--python_out={_tmp}",
                    _PROTO,
                ],
                check=True,
            )
            with open(src_path) as f:
                src = f.read()
            # protoc emits a sibling absolute import; our generated modules
            # live in different packages, so point it at the real location.
            src = src.replace(
                "import events_pb2 as events__pb2",
                "from armada_tpu.events import events_pb2 as events__pb2",
            )
            with open(src_path, "w") as f:
                f.write(src)
        else:
            from armada_tpu.events import _minigen

            with open(src_path, "w") as f:
                f.write(
                    _minigen.generate_pb2_source(
                        _PROTO,
                        "rpc.proto",
                        "rpc_pb2",
                        import_lines=(
                            "from armada_tpu.events import "
                            "events_pb2 as events__pb2\n"
                        ),
                    )
                )
        # lint: allow(atomic-state-file) -- generated CODE module, not durable
        # state: it must stay plainly importable (no checksum envelope), and
        # a lost regen just re-runs on the next import.
        os.replace(src_path, _GEN)

from armada_tpu.rpc import rpc_pb2  # noqa: E402

__all__ = ["rpc_pb2"]
