"""In-memory job store with single-writer transactions and invariant checks.

Equivalent of the reference's internal/scheduler/jobdb (SURVEY.md section 2.2).
"""

from armada_tpu.jobdb.job import Job, JobRun
from armada_tpu.jobdb.jobdb import JobDb, ReadTxn, WriteTxn, gang_key, market_order_key

__all__ = [
    "Job",
    "JobRun",
    "JobDb",
    "ReadTxn",
    "WriteTxn",
    "gang_key",
    "market_order_key",
]
