"""Cycle tracing: a correlated span timeline for the serving plane.

Every observability surface so far is an AGGREGATE -- bench prints
whole-cycle seconds, the SLO layer prints percentiles, the transfer
counters print totals.  None of them answers "where did *this* cycle's
0.51s go?" across the three-stage shadow pipeline, the axon tunnel's
serialized transfers, a watchdog failover re-run, and the sidecar gRPC
boundary.  This module is the missing correlated view: a process-global,
always-cheap span recorder the steady cycle is instrumented with, exported
as Chrome trace-event JSON (Perfetto-loadable), a ``trace`` block in
/healthz, and per-stage latency histograms.

Design constraints (all load-bearing):

* **One clock.**  Every timestamp is :func:`ops.metrics.mono_now` -- the
  single sanctioned monotonic source; armada-lint's ``slo-wallclock`` rule
  covers this module, so a wall-clock read here is a CI failure.  Chrome
  export emits offsets from each trace's root, so the arbitrary monotonic
  epoch never leaks.
* **Zero allocation when off.**  ``span()`` returns a shared no-op context
  manager unless a cycle is active AND tracing is enabled
  (``ARMADA_TRACE=0`` disables); the hot path of a disabled recorder is
  two attribute reads.  Armed, a span costs one small object and two
  clock reads (~1us) -- cheap enough that the pipeline/faults equality
  suites run with tracing armed (tests/test_trace.py pins bit-equality).
* **Bounded memory.**  Finished cycle trees land in a ring of the last N
  cycles (``ARMADA_TRACE_RING``, default 16); per-cycle span counts are
  capped (``_SPAN_CAP``) so a pathological loop cannot grow a tree without
  bound -- overflow is counted on the root, never silent.
* **Bit-neutral.**  The recorder only reads clocks and appends to lists;
  it never touches problem state, so tracing armed vs disarmed yields
  identical decisions (pinned by the tracing-armed pipeline equality run).
* **Cross-thread spans attach to the cycle.**  The watchdog worker and
  shadow thunks run on other threads; a span opened on a thread with no
  local open span parents to the active cycle's root (each span records
  its thread id, so Perfetto renders real thread tracks).
* **Cross-process stitching.**  A trace id propagates over the sidecar
  gRPC boundary via call metadata (rpc/client.py <-> rpc/server.py); the
  server's round spans ride the response and :meth:`TraceRecorder.graft`
  re-bases them under the caller's RPC span, yielding ONE stitched tree
  for a ``ScheduleRound`` driven by an external control plane.

Readers: ``armadactl trace`` / tools/trace_dump.py (:func:`chrome_trace`),
/healthz's ``trace`` block (:meth:`TraceRecorder.healthz_block`), the
prometheus gauges ``armada_cycle_stage_seconds{stage,quantile}``
(scheduler/metrics.py, fed from :meth:`TraceRecorder.stage_snapshot`),
and bench.py's ``stage_*_s`` keys.  docs/observability.md is the workflow.
"""

from __future__ import annotations

import os
import threading
from collections import deque
from typing import Optional

from armada_tpu.analysis.tsan import make_lock
from armada_tpu.ops.metrics import MetricsRegistry, mono_now

# Hard per-cycle span cap: a runaway instrumentation loop must degrade to a
# counted overflow, never an unbounded tree.
_SPAN_CAP = 200_000


class Span:
    """One timed region.  ``t0``/``t1`` are mono_now() seconds; ``args``
    is a small JSON-able dict (bytes counts, row counts, reasons)."""

    __slots__ = ("name", "t0", "t1", "tid", "args", "children")

    def __init__(self, name: str, t0: float, tid: int, args: Optional[dict]):
        self.name = name
        self.t0 = t0
        self.t1 = t0
        self.tid = tid
        self.args = args
        self.children: list = []

    @property
    def dur_s(self) -> float:
        return max(0.0, self.t1 - self.t0)

    def to_dict(self, base: float) -> dict:
        """Offset-based serialization (relative to ``base``): monotonic
        epochs differ across processes, so the wire form carries only
        offsets + durations -- graft() re-bases them in the receiver's
        timeline."""
        out = {
            "name": self.name,
            "off_s": round(self.t0 - base, 9),
            "dur_s": round(self.dur_s, 9),
        }
        if self.tid:
            out["tid"] = self.tid
        if self.args:
            out["args"] = self.args
        if self.children:
            out["children"] = [c.to_dict(base) for c in self.children]
        return out


class CycleTrace:
    """One finished (or active) cycle's span tree."""

    __slots__ = (
        "trace_id", "kind", "pid", "root", "span_count", "overflow",
        "finished",
    )

    def __init__(self, trace_id: str, kind: str, root: Span):
        self.trace_id = trace_id
        self.kind = kind
        self.pid = os.getpid()
        self.root = root
        self.span_count = 1
        self.overflow = 0
        # Zombie-writer guard (the devcache GenerationGuard's idea, in
        # miniature): a watchdog-abandoned worker that unwedges after its
        # cycle finalized must not keep growing the ring entry or charge
        # span counts to whatever cycle is primary by then -- span()/note()
        # drop work whose owning trace is finished.
        self.finished = False

    def to_dict(self) -> dict:
        d = {
            "trace_id": self.trace_id,
            "kind": self.kind,
            "pid": self.pid,
            "duration_s": round(self.root.dur_s, 9),
            "root": self.root.to_dict(self.root.t0),
        }
        if self.overflow:
            d["span_overflow"] = self.overflow
        return d


class _NoopSpan:
    """Shared do-nothing context manager: the disabled/idle fast path
    allocates NOTHING per span."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NOOP = _NoopSpan()


class _SpanCtx:
    """Context manager for one armed span: pushes onto the thread's open
    stack on enter, stamps t1 and pops on exit."""

    __slots__ = ("_rec", "_span", "_jax")

    def __init__(self, rec: "TraceRecorder", span: Span):
        self._rec = rec
        self._span = span
        self._jax = None

    def __enter__(self):
        stack = self._rec._stack()
        stack.append(self._span)
        if self._rec._jax_bridge:
            self._jax = self._rec._enter_jax(self._span.name)
        return self._span

    def __exit__(self, *exc):
        if self._jax is not None:
            try:
                self._jax.__exit__(*exc)
            except Exception:  # noqa: BLE001 - profiler teardown is best-effort
                pass
        self._span.t1 = mono_now()
        stack = self._rec._stack()
        if stack and stack[-1] is self._span:
            stack.pop()
        else:  # tolerate exotic unwind orders (watchdog-abandoned threads)
            try:
                stack.remove(self._span)
            except ValueError:
                pass
        return False


class _CycleCtx:
    """Context manager for a cycle root; finalizes into the ring."""

    __slots__ = ("_rec", "_trace", "_span_ctx")

    def __init__(self, rec: "TraceRecorder", trace: CycleTrace):
        self._rec = rec
        self._trace = trace
        self._span_ctx = _SpanCtx(rec, trace.root)

    def __enter__(self):
        self._rec._tls.trace = self._trace
        self._span_ctx.__enter__()
        return self._trace

    def __exit__(self, *exc):
        self._span_ctx.__exit__(*exc)
        self._rec._finish_cycle(self._trace)
        return False


def _gen_trace_id() -> str:
    # uuid4 without the uuid import cost on every cycle: 16 random hex
    # bytes from os.urandom (no clock involved -- lint scope).
    return os.urandom(16).hex()


class TraceRecorder:
    """Process-global span recorder (singleton via :func:`recorder`)."""

    def __init__(self, ring: Optional[int] = None):
        if ring is None:
            try:
                ring = int(os.environ.get("ARMADA_TRACE_RING", "16"))
            except ValueError:
                ring = 16
        self.ring: deque = deque(maxlen=max(1, ring))
        self.registry = MetricsRegistry("trace")
        # Active cycles are PER-THREAD (a sidecar session's round on a gRPC
        # worker must not nest into an unrelated cycle that happens to be
        # open on another thread -- in-process client+server is a real test
        # topology); `_primary` is the fallback for spans opened on threads
        # with no cycle of their own (the watchdog worker, shadow thunks).
        self._active_by_thread: dict[int, CycleTrace] = {}
        self._primary: Optional[CycleTrace] = None
        self._tls = threading.local()
        self._lock = make_lock("trace.recorder")
        self._jax_bridge = os.environ.get("ARMADA_TRACE_JAX") == "1"
        self.nested_cycles = 0  # cycle() while this thread already had one

    # ------------------------------------------------------------- state ----

    @property
    def enabled(self) -> bool:
        return os.environ.get("ARMADA_TRACE", "1") != "0"

    def _stack(self) -> list:
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = self._tls.stack = []
        return stack

    def active(self) -> Optional[CycleTrace]:
        """This thread's open cycle, else the process's primary one."""
        t = self._active_by_thread.get(threading.get_ident())
        return t if t is not None else self._primary

    def capture(self) -> Optional[tuple]:
        """(owning trace, span) new work on this thread would attach to --
        the handle a worker thread passes to :meth:`adopt`."""
        owner, parent = self._resolve()
        return None if owner is None else (owner, parent)

    def adopt(self, handle: Optional[tuple]) -> None:
        """Seed THIS thread's span stack with a (trace, span) handle
        captured on another thread (core/watchdog's round worker): spans
        opened here nest under the caller's open span -- e.g.
        kernel_dispatch under the round span -- instead of flattening onto
        the cycle root, so the stage histograms (direct children of the
        root) never double-count worker time that also elapses inside the
        caller's span.  For ONE-SHOT threads: the seeded frame is never
        popped.  The owning trace rides along so the zombie guard can
        refuse spans once that cycle finalizes."""
        if handle is None:
            return
        owner, parent = handle
        if parent is None:
            return
        self._tls.trace = owner
        self._stack().append(parent)

    def _resolve(self) -> tuple:
        """(owning trace, parent span) for new work on this thread: the
        innermost open span here (owned by the thread's recorded trace),
        else the active cycle's root.  (None, None) when no LIVE cycle is
        reachable -- including the zombie case where this thread's trace
        already finalized."""
        stack = self._stack()
        if stack:
            owner = getattr(self._tls, "trace", None)
            if owner is None:
                owner = self.active()
            if owner is None or owner.finished:
                return None, None
            return owner, stack[-1]
        owner = self.active()
        if owner is None or owner.finished:
            return None, None
        # record the owner so nested spans opened from this root-attached
        # one charge the SAME trace even if the primary moves meanwhile
        self._tls.trace = owner
        return owner, owner.root

    # ----------------------------------------------------------- writers ----

    def cycle(self, name: str, trace_id: str = "", kind: str = "", **args):
        """Begin a cycle trace: the root every span until exit attaches to.
        ``trace_id`` stitches across processes (the sidecar boundary passes
        the caller's).  Re-entrant use (a cycle inside a cycle) degrades to
        a plain span of the outer cycle, so nesting can never corrupt the
        ring."""
        if not self.enabled:
            return _NOOP
        tid = threading.get_ident()
        if tid in self._active_by_thread:
            self.nested_cycles += 1
            return self.span(name, **args)
        root = Span(name, mono_now(), tid, args or None)
        trace = CycleTrace(trace_id or _gen_trace_id(), kind or name, root)
        with self._lock:
            self._active_by_thread[tid] = trace
            if self._primary is None:
                self._primary = trace
        return _CycleCtx(self, trace)

    def span(self, name: str, **args):
        """A timed region inside the active cycle; no-op (shared object,
        zero allocation) when disabled or no live cycle is reachable."""
        if not self.enabled:
            return _NOOP
        owner, parent = self._resolve()
        if owner is None:
            return _NOOP
        if owner.span_count >= _SPAN_CAP:
            owner.overflow += 1
            return _NOOP
        span = Span(name, mono_now(), threading.get_ident(), args or None)
        parent.children.append(span)
        owner.span_count += 1
        return _SpanCtx(self, span)

    def note(self, name: str, **args) -> None:
        """Instant event (zero-duration span): per-transfer bytes, cache
        resets.  Same no-op economics as span()."""
        if not self.enabled:
            return
        owner, parent = self._resolve()
        if owner is None:
            return
        if owner.span_count >= _SPAN_CAP:
            owner.overflow += 1
            return
        span = Span(name, mono_now(), threading.get_ident(), args or None)
        parent.children.append(span)
        owner.span_count += 1

    def annotate(self, **args) -> None:
        """Attach args to the owning cycle's root (failover reason,
        degraded flag): attribution survives even when the annotating code
        runs deep inside a worker thread."""
        if not self.enabled:
            return
        owner, _parent = self._resolve()
        if owner is None:
            return
        if owner.root.args is None:
            owner.root.args = {}
        owner.root.args.update(args)

    def graft(self, remote: dict, under: Optional[Span] = None) -> None:
        """Attach a REMOTE process's serialized span tree (Span.to_dict
        offset form, as shipped in the sidecar response) beneath the
        current span: offsets re-base at the graft point's start, so the
        remote spans land inside the RPC span that covered them.  The
        remote pid keeps its own track in the Chrome export."""
        if not self.enabled:
            return
        if under is not None:
            parent = under
        else:
            owner, parent = self._resolve()
            if owner is None:
                return
        if parent is None:
            return

        def build(d: dict, base: float, root: bool) -> Span:
            s = Span(d.get("name", "remote"), base + float(d.get("off_s", 0.0)), 0, None)
            s.t1 = s.t0 + float(d.get("dur_s", 0.0))
            args = dict(d.get("args") or {})
            if root:
                # only the graft ROOT is marked remote (+ carries the
                # remote pid): the Chrome exporter switches the process
                # track there and descendants inherit it.
                args.setdefault("remote", True)
            s.args = args or None
            s.children = [
                build(c, base, False) for c in d.get("children", ())
            ]
            return s

        grafted = build(remote, parent.t0, True)
        parent.children.append(grafted)

    def _finish_cycle(self, trace: CycleTrace) -> None:
        with self._lock:
            trace.finished = True
            tid = threading.get_ident()
            if self._active_by_thread.get(tid) is trace:
                del self._active_by_thread[tid]
            if getattr(self._tls, "trace", None) is trace:
                self._tls.trace = None
            if self._primary is trace:
                self._primary = next(
                    iter(self._active_by_thread.values()), None
                )
            self.ring.append(trace)
        # Stage histograms: the root's DIRECT children are the cycle's
        # stages; same-named stages within one cycle accumulate.
        by_stage: dict[str, float] = {}
        for child in trace.root.children:
            by_stage[child.name] = by_stage.get(child.name, 0.0) + child.dur_s
        for stage, dur in by_stage.items():
            self.registry.histogram(f"stage.{stage}").record(dur)
        self.registry.histogram("cycle").record(trace.root.dur_s)

    # ----------------------------------------------------------- readers ----

    def last(self, n: Optional[int] = None) -> list:
        with self._lock:
            traces = list(self.ring)
        return traces if n is None else traces[-n:]

    def stage_snapshot(self) -> dict:
        """Per-stage latency distributions (the prometheus + bench feed)."""
        return self.registry.snapshot()

    def last_stages(self) -> dict:
        """stage -> seconds for the newest finished cycle (bench's
        stage_*_s keys; deterministic, unlike the histograms)."""
        traces = self.last(1)
        if not traces:
            return {}
        out: dict[str, float] = {}
        for child in traces[-1].root.children:
            out[child.name] = out.get(child.name, 0.0) + child.dur_s
        return out

    def healthz_block(self) -> dict:
        """The /healthz ``trace`` block: last cycle's identity + top spans
        by duration (flattened), small enough to read at a glance."""
        traces = self.last(1)
        if not traces:
            return {"cycles": len(self.ring)}
        t = traces[-1]
        return {
            "cycles": len(self.ring),
            "trace_id": t.trace_id,
            "kind": t.kind,
            "duration_s": round(t.root.dur_s, 6),
            "args": t.root.args or {},
            "top_spans": top_spans(t.root.to_dict(t.root.t0)),
        }

    def dump(self) -> dict:
        """Offset-form dump of the whole ring (the wire/disk form
        tools/trace_dump.py and armadactl trace consume)."""
        return {"traces": [t.to_dict() for t in self.last()]}

    def reset(self) -> None:
        with self._lock:
            self.ring.clear()
            self._active_by_thread.clear()
            self._primary = None
        self.registry.reset()

    # -------------------------------------------------------- jax bridge ----

    @staticmethod
    def _enter_jax(name: str):
        """Optional jax.profiler.TraceAnnotation bridge
        (ARMADA_TRACE_JAX=1): host spans appear in device traces so a
        jax-profiler capture lines up with this module's timeline."""
        try:
            from jax.profiler import TraceAnnotation
        except ImportError:  # pragma: no cover - older jax
            return None
        try:
            ctx = TraceAnnotation(name)
            ctx.__enter__()
            return ctx
        except Exception:  # noqa: BLE001 - tracing must never break the cycle
            return None


def top_spans(root: dict, n: int = 12) -> list:
    """The N longest spans of one offset-form tree (Span.to_dict), each as
    ``{"name", "depth", "dur_s"}`` -- the ONE flatten/rank implementation
    behind the /healthz trace block and `armadactl trace --summary`."""
    flat: list[tuple[float, str, int]] = []

    def walk(d: dict, depth: int) -> None:
        for c in d.get("children", ()):
            flat.append((float(c.get("dur_s", 0.0)), c.get("name", ""), depth))
            walk(c, depth + 1)

    walk(root, 1)
    flat.sort(reverse=True)
    return [
        {"name": name, "depth": depth, "dur_s": round(dur, 6)}
        for dur, name, depth in flat[:n]
    ]


# ---------------------------------------------------------------------------
# Chrome trace-event export (Perfetto-loadable)
# ---------------------------------------------------------------------------

def chrome_trace(traces=None, recorder_: Optional[TraceRecorder] = None) -> dict:
    """Chrome trace-event JSON for a set of cycle traces.

    ``traces`` may be CycleTrace objects or their offset-form dicts (the
    dump()/wire shape) -- armadactl trace stitches a REMOTE plane's dump
    without reconstructing objects.  Cycles are laid out sequentially on a
    shared timeline (each cycle's root starts where exporting placed it),
    with ``ph: "X"`` complete events, ``ph: "i"`` instants for
    zero-duration notes, and ``ph: "M"`` process/thread metadata --
    exactly the fields Perfetto's JSON importer requires (name, ph, ts,
    dur, pid, tid).
    """
    rec = recorder_ if recorder_ is not None else recorder()
    if traces is None:
        traces = rec.last()
    events: list[dict] = []
    tracks_seen: set = set()
    cursor_us = 0.0

    def emit_meta(pid: int, tid: int, pname: str) -> None:
        if (pid, 0) not in tracks_seen:
            tracks_seen.add((pid, 0))
            events.append(
                {"ph": "M", "name": "process_name", "pid": pid, "tid": 0,
                 "args": {"name": pname}}
            )
        if (pid, tid) not in tracks_seen:
            tracks_seen.add((pid, tid))
            events.append(
                {"ph": "M", "name": "thread_name", "pid": pid, "tid": tid,
                 "args": {"name": f"thread-{tid}"}}
            )

    def emit(d: dict, origin_us: float, pid_here: int, trace_id: str,
             tid_inherit: int) -> float:
        args = dict(d.get("args") or {})
        tid = int(d.get("tid", tid_inherit)) or tid_inherit
        if args.pop("remote", False):
            # graft root: switch to the remote process's track; the
            # recursion carries the switched pid to every descendant.
            pid_here = int(args.pop("pid", pid_here + 1_000_000))
            tid = 1
            emit_meta(pid_here, tid, f"armada-remote-{pid_here}")
        else:
            emit_meta(pid_here, tid, f"armada-{pid_here}")
        ts = origin_us + float(d.get("off_s", 0.0)) * 1e6
        dur = float(d.get("dur_s", 0.0)) * 1e6
        args["trace_id"] = trace_id
        ev = {"name": d.get("name", "span"), "cat": "armada",
              "pid": pid_here, "tid": tid}
        if dur <= 0.0 and not d.get("children"):
            ev.update({"ph": "i", "ts": ts, "s": "t", "args": args})
        else:
            ev.update({"ph": "X", "ts": ts, "dur": max(dur, 0.001),
                       "args": args})
        events.append(ev)
        end = ts + dur
        for c in d.get("children", ()):  # children are offset from the ROOT
            end = max(end, emit(c, origin_us, pid_here, trace_id, tid))
        return end

    for t in traces:
        doc = t.to_dict() if isinstance(t, CycleTrace) else t
        pid = int(doc.get("pid", os.getpid()))
        root = doc.get("root", {})
        end = emit(root, cursor_us, pid, doc.get("trace_id", ""), 1)
        cursor_us = end + 1000.0  # 1ms gutter between cycles
    return {"traceEvents": events, "displayTimeUnit": "ms"}


# ---------------------------------------------------------------------------
# process-global singleton (the watchdog-supervisor / SLO-recorder idiom)
# ---------------------------------------------------------------------------

_recorder: Optional[TraceRecorder] = None
_recorder_lock = make_lock("trace.global")


def recorder() -> TraceRecorder:
    global _recorder
    with _recorder_lock:
        if _recorder is None:
            _recorder = TraceRecorder()
        return _recorder


def reset_recorder(ring: Optional[int] = None) -> TraceRecorder:
    """Fresh process-global recorder (tests/bench arms)."""
    global _recorder
    with _recorder_lock:
        _recorder = TraceRecorder(ring=ring)
        return _recorder
