"""Streaming metrics primitives: log-bucketed histograms + a tiny registry.

The scheduler's observability so far was prometheus gauges/counters
(scheduler/metrics.py) and one-shot bench numbers; a *standing* load harness
needs latency **distributions** that are O(1) per record, O(buckets) memory,
mergeable, and readable as JSON from /healthz, the sidecar stats and the
bench line without a prometheus scrape.  :class:`LogHistogram` is that type:
geometric buckets between ``lo`` and ``hi`` (HDR-histogram style), exact
rank-based percentile semantics pinned by a numpy oracle in
tests/test_slo_metrics.py.

Clock discipline (machine-checked by armada-lint rule ``slo-wallclock``):
SLO latency math in this module and in ``armada_tpu/loadgen/`` /
``scheduler/slo.py`` must never read an event-order-bearing wall clock --
wall time skews across hosts and steps backwards under NTP, which turns a
latency histogram into fiction.  Every clock read routes through
:func:`mono_now`, the single named monotonic source.
"""

from __future__ import annotations

import math
import time
from typing import Optional

import numpy as np

from armada_tpu.analysis.tsan import make_lock


def mono_now() -> float:
    """The ONE clock SLO code may read: monotonic seconds, meaningful only
    as differences within this process.  armada-lint's ``slo-wallclock``
    rule pins every other clock call out of the SLO modules."""
    return time.monotonic()


class LogHistogram:
    """Log-bucketed streaming histogram: O(1) record, fixed memory.

    Buckets are geometric: edges[i] = lo * growth**i for i in [0, n); a
    value lands in the first bucket whose upper edge is >= value
    (np.searchsorted(edges, v, side="left") semantics, shared verbatim with
    the numpy oracle so percentile math is EXACT, not approximately equal).
    Values <= lo fall in bucket 0, values >= hi clamp to the last bucket --
    the histogram never drops a sample, it only loses resolution at the
    extremes (true min/max are tracked exactly alongside).

    ``quantile(q)`` is rank-based: the representative (upper edge) of the
    bucket holding the ceil(q*n)-th smallest recorded sample.  Relative
    resolution is ``growth - 1`` (default 2**(1/8) ~ 9%).
    """

    __slots__ = (
        "name",
        "lo",
        "hi",
        "edges",
        "counts",
        "count",
        "total",
        "vmin",
        "vmax",
        "_lock",
    )

    def __init__(
        self,
        name: str = "",
        lo: float = 1e-4,
        hi: float = 1e4,
        growth: float = 2 ** 0.125,
    ):
        if not (0 < lo < hi) or growth <= 1.0:
            raise ValueError("need 0 < lo < hi and growth > 1")
        self.name = name
        self.lo = float(lo)
        self.hi = float(hi)
        n = int(math.ceil(math.log(hi / lo) / math.log(growth))) + 1
        # Edge array is the single source of truth for bucketing: record()
        # and the test oracle both searchsorted into it, so they can never
        # disagree by a ULP the way two log/floor implementations can.
        self.edges = self.lo * np.power(float(growth), np.arange(n))
        self.edges[-1] = max(self.edges[-1], self.hi)
        self.counts = np.zeros(n, dtype=np.int64)
        self.count = 0
        self.total = 0.0
        self.vmin = math.inf
        self.vmax = -math.inf
        self._lock = make_lock(f"metrics.hist.{name or 'anon'}")

    def bucket_index(self, value: float) -> int:
        """The bucket a value lands in; clamped, never out of range."""
        # lint: allow(searchsorted-dtype) -- scalar float probe into a ~300-entry f64 edge array; nothing to copy
        idx = int(np.searchsorted(self.edges, value, side="left"))
        return min(idx, len(self.edges) - 1)

    def record(self, value: float) -> None:
        v = float(value)
        if v != v or v < 0:  # NaN / negative: a broken clock, not a latency
            v = 0.0
        with self._lock:
            self.counts[self.bucket_index(v)] += 1
            self.count += 1
            self.total += v
            if v < self.vmin:
                self.vmin = v
            if v > self.vmax:
                self.vmax = v

    def merge(self, other: "LogHistogram") -> None:
        """Fold another histogram with IDENTICAL bucketing into this one."""
        if len(other.edges) != len(self.edges) or other.lo != self.lo:
            raise ValueError("histogram bucketing mismatch")
        with self._lock:
            self.counts += other.counts
            self.count += other.count
            self.total += other.total
            self.vmin = min(self.vmin, other.vmin)
            self.vmax = max(self.vmax, other.vmax)

    def quantile(self, q: float) -> Optional[float]:
        """Upper edge of the bucket holding the ceil(q*n)-th smallest sample
        (q in (0, 1]); None when empty.  q=0 answers the exact minimum."""
        with self._lock:
            if self.count == 0:
                return None
            if q <= 0.0:
                return self.vmin
            rank = min(int(math.ceil(q * self.count)), self.count)
            cum = int(np.searchsorted(np.cumsum(self.counts), rank, side="left"))
            return float(self.edges[cum])

    def snapshot(self) -> dict:
        """JSON-able summary (the /healthz / bench / sidecar shape)."""
        with self._lock:
            if self.count == 0:
                return {"count": 0}
            mean = self.total / self.count
            snap = {
                "count": int(self.count),
                "sum_s": round(self.total, 6),
                "mean_s": round(mean, 6),
                "min_s": round(self.vmin, 6),
                "max_s": round(self.vmax, 6),
            }
        for label, q in (("p50", 0.50), ("p90", 0.90), ("p95", 0.95), ("p99", 0.99)):
            v = self.quantile(q)
            if v is not None:
                snap[label + "_s"] = round(v, 6)
        return snap

    def reset(self) -> None:
        with self._lock:
            self.counts[:] = 0
            self.count = 0
            self.total = 0.0
            self.vmin = math.inf
            self.vmax = -math.inf


class Counter:
    __slots__ = ("name", "value", "_lock")

    def __init__(self, name: str = ""):
        self.name = name
        self.value = 0
        self._lock = make_lock(f"metrics.counter.{name or 'anon'}")

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self.value += n

    def reset(self) -> None:
        with self._lock:
            self.value = 0

    def snapshot(self) -> int:
        return int(self.value)


class Gauge:
    __slots__ = ("name", "value")

    def __init__(self, name: str = ""):
        self.name = name
        self.value = 0.0

    def set(self, v: float) -> None:
        self.value = float(v)

    def snapshot(self) -> float:
        return float(self.value)


class MetricsRegistry:
    """Named gauges/counters/histograms with one JSON-able snapshot().

    Registration is get-or-create so instrumented code and its readers can
    both ask by name without an ordering contract; types are checked on
    re-registration (a counter silently shadowing a histogram would corrupt
    every reader)."""

    def __init__(self, namespace: str = ""):
        self.namespace = namespace
        self._metrics: dict[str, object] = {}
        self._lock = make_lock(f"metrics.registry.{namespace or 'anon'}")

    def _get_or_create(self, name: str, factory, kind):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = factory()
                self._metrics[name] = m
            elif not isinstance(m, kind):
                raise TypeError(
                    f"metric {name!r} already registered as {type(m).__name__}"
                )
            return m

    def histogram(self, name: str, **kw) -> LogHistogram:
        return self._get_or_create(
            name, lambda: LogHistogram(name=name, **kw), LogHistogram
        )

    def counter(self, name: str) -> Counter:
        return self._get_or_create(name, lambda: Counter(name), Counter)

    def gauge(self, name: str) -> Gauge:
        return self._get_or_create(name, lambda: Gauge(name), Gauge)

    def snapshot(self) -> dict:
        with self._lock:
            items = list(self._metrics.items())
        return {name: m.snapshot() for name, m in items}

    def reset(self) -> None:
        with self._lock:
            items = list(self._metrics.values())
        for m in items:
            if hasattr(m, "reset"):
                m.reset()
