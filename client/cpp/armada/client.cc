// See client.h.  Transport: minimal HTTP/1.1 over POSIX sockets -- the
// gateway always answers with Content-Length, so reads are exact.

#include "client.h"

#include <netdb.h>
#include <sys/socket.h>
#include <unistd.h>

#include <cstring>
#include <sstream>

#include <google/protobuf/util/json_util.h>

namespace armada {

namespace {

int Dial(const std::string& host, int port) {
  struct addrinfo hints;
  std::memset(&hints, 0, sizeof(hints));
  hints.ai_family = AF_UNSPEC;
  hints.ai_socktype = SOCK_STREAM;
  struct addrinfo* res = nullptr;
  const std::string port_s = std::to_string(port);
  if (getaddrinfo(host.c_str(), port_s.c_str(), &hints, &res) != 0 || !res) {
    throw ClientError{0, "cannot resolve " + host};
  }
  int fd = -1;
  for (struct addrinfo* ai = res; ai; ai = ai->ai_next) {
    fd = socket(ai->ai_family, ai->ai_socktype, ai->ai_protocol);
    if (fd < 0) continue;
    if (connect(fd, ai->ai_addr, ai->ai_addrlen) == 0) break;
    close(fd);
    fd = -1;
  }
  freeaddrinfo(res);
  if (fd < 0) throw ClientError{0, "cannot connect to " + host + ":" + port_s};
  return fd;
}

void WriteAll(int fd, const std::string& data) {
  size_t off = 0;
  while (off < data.size()) {
    ssize_t n = write(fd, data.data() + off, data.size() - off);
    if (n <= 0) {
      close(fd);
      throw ClientError{0, "short write"};
    }
    off += static_cast<size_t>(n);
  }
}

}  // namespace

HttpResponse Client::Request(const std::string& method, const std::string& path,
                             const std::string& body) {
  int fd = Dial(host_, port_);
  std::ostringstream req;
  req << method << " " << path << " HTTP/1.1\r\n"
      << "Host: " << host_ << "\r\n"
      << "Connection: close\r\n"
      << "Content-Type: application/json\r\n";
  if (!principal_.empty()) req << "x-armada-principal: " << principal_ << "\r\n";
  if (!groups_.empty()) req << "x-armada-groups: " << groups_ << "\r\n";
  req << "Content-Length: " << body.size() << "\r\n\r\n" << body;
  WriteAll(fd, req.str());

  std::string raw;
  char buf[8192];
  ssize_t n;
  while ((n = read(fd, buf, sizeof(buf))) > 0) raw.append(buf, static_cast<size_t>(n));
  close(fd);

  HttpResponse resp;
  const size_t header_end = raw.find("\r\n\r\n");
  if (header_end == std::string::npos) throw ClientError{0, "malformed response"};
  const size_t sp = raw.find(' ');
  resp.status = std::stoi(raw.substr(sp + 1, 3));
  resp.body = raw.substr(header_end + 4);
  return resp;
}

std::string Client::CallRaw(const std::string& method, const std::string& path,
                            const std::string& body) {
  HttpResponse resp = Request(method, path, body);
  if (resp.status < 200 || resp.status >= 300) {
    throw ClientError{resp.status, resp.body};
  }
  return resp.body;
}

std::string Client::CallJson(const std::string& method, const std::string& path,
                             const google::protobuf::Message* request) {
  std::string body;
  if (request != nullptr) {
    auto status =
        google::protobuf::util::MessageToJsonString(*request, &body);
    if (!status.ok()) throw ClientError{0, "request encode failed"};
  }
  return CallRaw(method, path, body);
}

void Client::Call(const std::string& method, const std::string& path,
                  const google::protobuf::Message* request,
                  google::protobuf::Message* response) {
  std::string body = CallJson(method, path, request);
  if (response != nullptr) {
    google::protobuf::util::JsonParseOptions opts;
    opts.ignore_unknown_fields = true;
    auto status = google::protobuf::util::JsonStringToMessage(
        body.empty() ? "{}" : body, response, opts);
    if (!status.ok()) {
      throw ClientError{0, "response decode failed: " + body};
    }
  }
}

void Client::CreateQueue(const armada_tpu::api::Queue& queue) {
  armada_tpu::api::Empty empty;
  Call("POST", "/v1/queue", &queue, &empty);
}

void Client::UpdateQueue(const armada_tpu::api::Queue& queue) {
  armada_tpu::api::Empty empty;
  Call("PUT", "/v1/queue/" + queue.name(), &queue, &empty);
}

void Client::DeleteQueue(const std::string& name) {
  armada_tpu::api::Empty empty;
  Call("DELETE", "/v1/queue/" + name, nullptr, &empty);
}

armada_tpu::api::Queue Client::GetQueue(const std::string& name) {
  armada_tpu::api::Queue out;
  Call("GET", "/v1/queue/" + name, nullptr, &out);
  return out;
}

armada_tpu::api::QueueListResponse Client::ListQueues() {
  armada_tpu::api::QueueListResponse out;
  Call("GET", "/v1/batched/queues", nullptr, &out);
  return out;
}

armada_tpu::api::SubmitJobsResponse Client::SubmitJobs(
    const armada_tpu::api::SubmitJobsRequest& request) {
  armada_tpu::api::SubmitJobsResponse out;
  Call("POST", "/v1/job/submit", &request, &out);
  return out;
}

void Client::CancelJobs(const armada_tpu::api::CancelJobsRequest& request) {
  armada_tpu::api::Empty empty;
  Call("POST", "/v1/job/cancel", &request, &empty);
}

void Client::CancelJobSet(const armada_tpu::api::CancelJobSetRequest& request) {
  armada_tpu::api::Empty empty;
  Call("POST", "/v1/jobset/cancel", &request, &empty);
}

void Client::PreemptJobs(const armada_tpu::api::PreemptJobsRequest& request) {
  armada_tpu::api::Empty empty;
  Call("POST", "/v1/job/preempt", &request, &empty);
}

void Client::ReprioritizeJobs(
    const armada_tpu::api::ReprioritizeJobsRequest& request) {
  armada_tpu::api::Empty empty;
  Call("POST", "/v1/job/reprioritize", &request, &empty);
}

std::string Client::GetJobs(const std::string& query_json) {
  return CallRaw("POST", "/v1/jobs/list", query_json);
}

std::string Client::GroupJobs(const std::string& query_json) {
  return CallRaw("POST", "/v1/jobs/groups", query_json);
}

std::string Client::GetJobDetails(const std::string& job_id) {
  return CallJson("GET", "/v1/job/" + job_id + "/details", nullptr);
}

std::string Client::GetJobReport(const std::string& job_id) {
  return CallJson("GET", "/v1/reports/job/" + job_id, nullptr);
}

std::string Client::GetQueueReport(const std::string& queue) {
  return CallJson("GET", "/v1/reports/queue/" + queue, nullptr);
}

std::string Client::GetPoolReport(const std::string& pool) {
  return CallJson(
      "GET", pool.empty() ? "/v1/reports/pool" : "/v1/reports/pool/" + pool,
      nullptr);
}

std::vector<armada_tpu::api::JobSetEventMessage> Client::GetJobSetEvents(
    const std::string& queue, const std::string& jobset, long from_idx) {
  std::string body = CallJson(
      "GET",
      "/v1/job-set/" + queue + "/" + jobset +
          "?from_idx=" + std::to_string(from_idx),
      nullptr);
  std::vector<armada_tpu::api::JobSetEventMessage> out;
  std::istringstream lines(body);
  std::string line;
  google::protobuf::util::JsonParseOptions opts;
  opts.ignore_unknown_fields = true;
  while (std::getline(lines, line)) {
    if (line.empty()) continue;
    armada_tpu::api::JobSetEventMessage msg;
    auto status =
        google::protobuf::util::JsonStringToMessage(line, &msg, opts);
    if (!status.ok()) throw ClientError{0, "event decode failed: " + line};
    out.push_back(std::move(msg));
  }
  return out;
}

}  // namespace armada
