"""Structured logging with context-propagated fields.

Equivalent of the reference's armadacontext (internal/common/armadacontext/
armada_context.go) + zerolog structured fields (internal/common/logging):
a context carries key=value fields and every log line emitted under it is
stamped with them, so one request/cycle/executor can be traced across
components without threading loggers through every call.

Usage:

    log = get_logger(__name__)
    with log_context(cycle=42, pool="default"):
        log.info("scheduling")          # ... cycle=42 pool=default

Fields nest (inner contexts extend outer ones) and propagate across threads
started via `spawn_with_context` (contextvars do not cross threads on their
own).
"""

from __future__ import annotations

import contextlib
import contextvars
import logging
import threading
from typing import Any, Callable, Iterator

_FIELDS: contextvars.ContextVar[tuple] = contextvars.ContextVar(
    "armada_log_fields", default=()
)


@contextlib.contextmanager
def log_context(**fields: Any) -> Iterator[None]:
    """Extend the current logging context with `fields` for the duration."""
    token = _FIELDS.set(_FIELDS.get() + tuple(fields.items()))
    try:
        yield
    finally:
        _FIELDS.reset(token)


def current_fields() -> dict:
    out: dict = {}
    for k, v in _FIELDS.get():
        out[k] = v
    return out


class _ContextFilter(logging.Filter):
    """Stamps records with the ambient fields (filters run for every record,
    unlike adapters, so third-party log calls inside a context get them too)."""

    def filter(self, record: logging.LogRecord) -> bool:
        fields = current_fields()
        record.armada_fields = fields
        suffix = " ".join(f"{k}={v}" for k, v in fields.items())
        record.armada_suffix = f" [{suffix}]" if suffix else ""
        return True


_FORMAT = "%(asctime)s %(levelname)s %(name)s: %(message)s%(armada_suffix)s"
_configured = False


def get_logger(name: str) -> logging.Logger:
    """A logger whose records carry the ambient context fields."""
    _ensure_configured()
    logger = logging.getLogger(name)
    if not any(isinstance(f, _ContextFilter) for f in logger.filters):
        logger.addFilter(_ContextFilter())
    return logger


def _ensure_configured() -> None:
    global _configured
    if _configured:
        return
    _configured = True
    root = logging.getLogger("armada_tpu")
    # Self-configure ONLY when nothing else is: if the operator wired the
    # root logger (logging.basicConfig, json shippers, pytest caplog),
    # records must keep propagating there -- hijacking them onto our own
    # stderr handler would bypass the operator's formatting/shipping.
    if not root.handlers and not logging.getLogger().handlers:
        handler = logging.StreamHandler()
        handler.setFormatter(logging.Formatter(_FORMAT))
        handler.addFilter(_ContextFilter())
        root.addHandler(handler)
        root.setLevel(logging.INFO)
        root.propagate = False


def spawn_with_context(
    target: Callable, *args, daemon: bool = True, **kwargs
) -> threading.Thread:
    """threading.Thread whose body runs under the CURRENT logging context
    (contextvars are per-thread; the reference's armadacontext rides Go's
    ctx through goroutines, this is the Python analog).  Daemon by default:
    a spawned worker wedged on a dead backend must never block process
    exit; pass daemon=False only with an explicit join discipline."""
    ctx = contextvars.copy_context()
    t = threading.Thread(
        target=lambda: ctx.run(target, *args, **kwargs), daemon=daemon
    )
    return t
