"""loadgen units: arrival processes, the seeded workload mix, the
lifecycle tracker's double-lease / dropped-job detection."""

from __future__ import annotations

import pytest

from armada_tpu.events import events_pb2 as pb
from armada_tpu.loadgen.arrivals import (
    BurstyArrivals,
    PoissonArrivals,
    RampArrivals,
    make_arrivals,
)
from armada_tpu.loadgen.lifecycle import LifecycleTracker
from armada_tpu.loadgen.workload import (
    CancelOp,
    MixConfig,
    ReprioritizeOp,
    SubmitOp,
    WorkloadGenerator,
)


# ------------------------------------------------------------- arrivals ----


def _timeline(proc, horizon_s, step_s=0.5):
    counts = []
    t = 0.0
    while t < horizon_s:
        t += step_s
        counts.append(proc.due_until(t))
    return counts


def test_poisson_is_deterministic_and_near_rate():
    a = _timeline(PoissonArrivals(50.0, seed=3), 200.0)
    b = _timeline(PoissonArrivals(50.0, seed=3), 200.0)
    assert a == b  # bit-identical timetable per seed
    total = sum(a)
    assert abs(total - 50.0 * 200.0) < 0.05 * 50.0 * 200.0  # ~5 sigma
    c = _timeline(PoissonArrivals(50.0, seed=4), 200.0)
    assert a != c  # the seed is the only source of variation


def test_open_loop_backlog_survives_a_stall():
    """A driver stall does not stretch the timetable: everything that came
    due during the stall is returned at the next poll (open loop)."""
    p = PoissonArrivals(100.0, seed=1)
    before = p.due_until(1.0)
    stalled = p.due_until(11.0)  # 10s stall
    assert abs((before + stalled) - 1100) < 250
    assert stalled > 800


def test_due_until_cap_bounds_one_poll():
    p = PoissonArrivals(1000.0, seed=0)
    n = p.due_until(10.0, cap=100)
    assert n == 100
    assert p.due_until(10.0) > 0  # remainder still due


def test_bursty_mean_rate_and_burstiness():
    proc = BurstyArrivals(25.0, 100.0, period_s=10.0, duty=0.2, seed=5)
    counts = _timeline(proc, 400.0, step_s=1.0)
    mean_rate = sum(counts) / 400.0
    assert abs(mean_rate - 40.0) < 8.0  # duty*burst + (1-duty)*base = 40
    # on-window seconds are visibly hotter than off-window seconds
    on = [c for i, c in enumerate(counts) if i % 10 == 0]
    off = [c for i, c in enumerate(counts) if 3 <= i % 10 <= 8]
    assert sum(on) / len(on) > 2.0 * sum(off) / len(off)


def test_ramp_rate_grows():
    proc = RampArrivals(10.0, 190.0, ramp_s=60.0, seed=2)
    counts = _timeline(proc, 60.0, step_s=1.0)
    early, late = sum(counts[:15]), sum(counts[-15:])
    assert late > 3.0 * early


def test_make_arrivals_factory():
    assert isinstance(make_arrivals("poisson", 10.0), PoissonArrivals)
    assert isinstance(make_arrivals("bursty", 10.0), BurstyArrivals)
    assert isinstance(make_arrivals("ramp", 10.0), RampArrivals)
    with pytest.raises(ValueError):
        make_arrivals("constant", 10.0)


# ------------------------------------------------------------- workload ----


def _drain(gen, n, feed_ids=True):
    """Apply n events; simulate the server assigning (unique) ids."""
    ops = gen.next_ops(n)
    seq = getattr(gen, "_test_id_seq", 0)
    for op in ops:
        if isinstance(op, SubmitOp) and feed_ids:
            ids = [f"{op.queue}-j{seq + i}" for i in range(len(op.items))]
            seq += len(op.items)
            gen.note_submitted(op.queue, ids)
    gen._test_id_seq = seq
    return ops


def test_workload_mix_is_deterministic():
    mix = MixConfig(num_queues=3)
    a, b = WorkloadGenerator(mix, seed=9), WorkloadGenerator(mix, seed=9)
    for _ in range(5):
        ops_a, ops_b = _drain(a, 200), _drain(b, 200)
        assert [type(o).__name__ for o in ops_a] == [
            type(o).__name__ for o in ops_b
        ]
    assert a.counts == b.counts


def test_workload_mix_ratios_converge():
    mix = MixConfig(num_queues=4, gang_fraction=0.1)
    gen = WorkloadGenerator(mix, seed=1)
    for _ in range(20):
        _drain(gen, 500)
    total = sum(gen.counts.values()) - gen.counts["gang_jobs"]
    assert total == 20 * 500
    assert 0.75 < gen.counts["submit"] / total < 0.95
    assert 0.02 < gen.counts["cancel"] / total < 0.10
    assert 0.05 < gen.counts["reprioritize"] / total < 0.16
    assert gen.counts["gang_jobs"] > 0


def test_gang_submits_are_well_formed():
    mix = MixConfig(num_queues=2, gang_fraction=1.0)
    gen = WorkloadGenerator(mix, seed=0)
    ops = _drain(gen, 20)
    gangs = [op for op in ops if isinstance(op, SubmitOp) and op.gang]
    assert gangs
    seen_ids = set()
    for op in gangs:
        gid = op.items[0].gang_id
        assert gid and gid not in seen_ids  # fresh id per gang
        seen_ids.add(gid)
        assert all(it.gang_id == gid for it in op.items)
        assert all(it.gang_cardinality == len(op.items) for it in op.items)
        assert (
            mix.gang_size_min <= len(op.items) <= mix.gang_size_max
        )


def test_cancel_targets_are_never_reused():
    mix = MixConfig(
        num_queues=1, submit_weight=0.5, cancel_weight=0.5, reprioritize_weight=0.0
    )
    gen = WorkloadGenerator(mix, seed=4)
    targeted = []
    for _ in range(30):
        for op in _drain(gen, 50):
            if isinstance(op, CancelOp):
                targeted.extend(op.job_ids)
    assert targeted
    assert len(targeted) == len(set(targeted))


def test_cold_pool_degrades_to_submit():
    mix = MixConfig(
        num_queues=1, submit_weight=0.0, cancel_weight=1.0, reprioritize_weight=0.0
    )
    gen = WorkloadGenerator(mix, seed=0)
    ops = gen.next_ops(5)  # nothing live yet: every cancel degrades
    assert all(isinstance(op, SubmitOp) for op in ops)
    assert gen.counts["submit"] == 5 and gen.counts["cancel"] == 0


# ------------------------------------------------------------ lifecycle ----


def _seq(*events):
    return pb.EventSequence(queue="q", jobset="s", events=list(events))


def _leased(jid, rid):
    return pb.Event(job_run_leased=pb.JobRunLeased(job_id=jid, run_id=rid))


def test_tracker_normal_flow_no_violations():
    tr = LifecycleTracker()
    tr.note_submitted("q", ["j1"])
    tr.observe_sequence(
        _seq(
            _leased("j1", "r1"),
            pb.Event(job_succeeded=pb.JobSucceeded(job_id="j1")),
        )
    )
    assert tr.violations == []
    assert tr.summary()["leased"] == 1
    assert tr.summary()["job_succeeded"] == 1
    assert tr.ttfl_values() and tr.ttfl_values()[0] >= 0


def test_tracker_detects_double_lease():
    tr = LifecycleTracker()
    tr.note_submitted("q", ["j1"])
    tr.observe_sequence(_seq(_leased("j1", "r1"), _leased("j1", "r2")))
    assert len(tr.violations) == 1
    assert "double lease" in tr.violations[0]


def test_tracker_requeue_then_lease_is_legal():
    tr = LifecycleTracker()
    tr.note_submitted("q", ["j1"])
    tr.observe_sequence(
        _seq(
            _leased("j1", "r1"),
            pb.Event(
                job_requeued=pb.JobRequeued(job_id="j1", update_sequence_number=1)
            ),
            _leased("j1", "r2"),
        )
    )
    assert tr.violations == []
    assert tr.jobs["j1"].lease_count == 2


def test_tracker_lease_after_terminal_is_a_violation():
    tr = LifecycleTracker()
    tr.note_submitted("q", ["j1"])
    tr.observe_sequence(
        _seq(
            pb.Event(cancelled_job=pb.CancelledJob(job_id="j1")),
            _leased("j1", "r1"),
        )
    )
    assert any("lease after terminal" in v for v in tr.violations)


def test_tracker_dropped_job_detection():
    tr = LifecycleTracker()
    tr.note_submitted("q", ["gone", "queued-fine", "done"])
    tr.observe_sequence(
        _seq(pb.Event(job_succeeded=pb.JobSucceeded(job_id="done")))
    )
    tr.check_dropped({"queued-fine": "queued"})
    assert len(tr.violations) == 1
    assert "dropped: job gone" in tr.violations[0]


def test_tracker_ignores_foreign_jobs():
    tr = LifecycleTracker()
    tr.note_submitted("q", ["mine"])
    tr.observe_sequence(_seq(_leased("other", "r1")))
    assert tr.events_seen == 0 and tr.violations == []
