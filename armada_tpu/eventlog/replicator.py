"""Cross-host event-log replication: followers tail the leader's log.

The reference survives a node loss because its durable state lives in
Pulsar + Postgres, off the scheduler hosts (leader.go:112-190 only elects;
state is remote).  This repo's log is host-local (native/eventlog.cc), so a
replicated deployment WITHOUT shared storage needs the follower to carry
its own copy: `LogReplicator` tails every partition of the leader's log
over the LogReplication gRPC service into the follower's local log.

Records are byte-framed with offset == byte position, so appending the
same records in the same order reproduces IDENTICAL offsets -- after
takeover the follower's ingest pipelines resume from their own committed
consumer positions against a log that is a byte-for-byte prefix-equal
copy of the leader's.

Replication is asynchronous (the tail of Pulsar-style geo-replication,
not synchronous quorum writes): an event the leader committed but had not
yet streamed when it died is lost with the leader's disk.  The window is
one poll interval (~50ms); deployments that cannot tolerate it need
shared/remote storage for the log itself.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Callable, Optional

from armada_tpu.eventlog.log import EventLog

log = logging.getLogger("armada.replicator")


class ReplicationDiverged(RuntimeError):
    """The local log is not a prefix of the leader's (e.g. this replica
    previously led and accepted writes the current leader never saw).
    Automatic repair would silently drop committed local records -- an
    operator must pick a survivor (wipe this replica's data dir)."""


class LogReplicator:
    """Tail the current leader's log into `local` (all partitions).

    `leader_address` returns the address to tail: None/"" = no leader to
    follow right now (we ARE the leader, or an election gap) -- the
    replicator idles and re-resolves.  `client_factory(address)` returns an
    object with `tail_log(partition, from_offset, follow, idle_timeout_s)`
    yielding LogRecord messages and a `close()` (rpc.client.ReplicationClient).
    """

    def __init__(
        self,
        local: EventLog,
        leader_address: Callable[[], Optional[str]],
        client_factory,
        poll_interval_s: float = 0.2,
        idle_timeout_s: float = 5.0,
    ):
        self.local = local
        self._leader_address = leader_address
        self._client_factory = client_factory
        self._poll = poll_interval_s
        self._idle = idle_timeout_s
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []
        # partition -> replicated end offset (observability/tests)
        self.replicated_to: dict[int, int] = {
            p: local.end_offset(p) for p in range(local.num_partitions)
        }
        self.diverged = threading.Event()

    def start(self) -> None:
        for p in range(self.local.num_partitions):
            t = threading.Thread(
                target=self._run_partition, args=(p,), daemon=True,
                name=f"log-replicator-p{p}",
            )
            t.start()
            self._threads.append(t)

    def stop(self) -> None:
        self._stop.set()
        for t in self._threads:
            t.join(timeout=5)

    # ------------------------------------------------------------------------

    def _run_partition(self, partition: int) -> None:
        from armada_tpu.core.backoff import Backoff

        # Bounded exponential backoff + jitter on tail failures: a dead
        # leader must not be hammered at poll frequency by every partition
        # thread of every follower in lockstep; cap keeps takeover lag
        # bounded once the peer returns.
        backoff = Backoff(base_s=self._poll, cap_s=30.0)
        while not self._stop.is_set():
            address = None
            try:
                address = self._leader_address()
            except Exception:
                pass
            if not address:
                # we lead (None) or nobody does (""): nothing to tail
                self._stop.wait(self._poll)
                continue
            try:
                self._tail_once(partition, address)
                backoff.reset()
            except ReplicationDiverged:
                self.diverged.set()
                log.error(
                    "partition %d: local log diverged from leader %s -- "
                    "replication halted (operator action required)",
                    partition,
                    address,
                )
                return
            except Exception as e:
                delay = backoff.next_delay()
                log.warning(
                    "partition %d: tail of %s failed (%s); attempt %d, "
                    "retrying in %.2fs",
                    partition,
                    address,
                    e,
                    backoff.attempts,
                    delay,
                )
                self._stop.wait(delay)

    def _tail_once(self, partition: int, address: str) -> None:
        client = self._client_factory(address)
        try:
            start = self.local.end_offset(partition)
            info = client.get_log_info()
            leader_end = list(info.end_offsets)[partition]
            if start > leader_end:
                # local log is LONGER than the leader's: we hold committed
                # records the leader never saw (e.g. this replica led once)
                raise ReplicationDiverged(
                    f"partition {partition}: local end {start} beyond "
                    f"leader end {leader_end}"
                )
            for record in client.tail_log(
                partition,
                from_offset=start,
                follow=True,
                idle_timeout_s=self._idle,
            ):
                if self._stop.is_set():
                    return
                local_end = self.local.end_offset(partition)
                if record.offset != local_end:
                    # Gap (leader compacted?) or overlap mismatch: either
                    # way the byte-prefix property is broken.
                    raise ReplicationDiverged(
                        f"partition {partition}: leader streams offset "
                        f"{record.offset}, local end is {local_end}"
                    )
                self.local.append(partition, record.key, record.payload)
                self.replicated_to[partition] = self.local.end_offset(
                    partition
                )
        except Exception as e:
            # A local end offset that is not a record BOUNDARY in the
            # leader's log makes the leader's read fail with its corrupt-
            # record error: that is divergence (mismatched histories), not
            # a transient stream failure.
            if "corrupt record" in str(e):
                raise ReplicationDiverged(
                    f"partition {partition}: local end is not a record "
                    f"boundary in the leader's log ({e})"
                ) from e
            raise
        finally:
            client.close()

    def caught_up_to(self, end_offsets: dict[int, int]) -> bool:
        """True when every partition has replicated at least to the given
        end offsets (test/drain helper)."""
        return all(
            self.local.end_offset(p) >= off for p, off in end_offsets.items()
        )
