"""FairSchedulingAlgo: the per-cycle scheduling decision over all pools.

Equivalent of the reference's SchedulingAlgo interface + FairSchedulingAlgo
(internal/scheduler/scheduling/scheduling_algo.go:36-41,100-848): collect
healthy executors' nodes, the queued and running jobs per pool, run one
scheduling round per pool -- here the jitted TPU kernel
(armada_tpu.models.run_scheduling_round) instead of the Go
PreemptingQueueScheduler -- and apply the decisions to the JobDb transaction.

Executor health filters mirror scheduling_algo.go:
  * stale executors (heartbeat older than executor_timeout_s) are excluded
    entirely (filterStaleExecutors:798);
  * cordoned executors keep their nodes visible (running jobs still count for
    fairness) but unschedulable (filterCordonedExecutors:780);
  * lagging executors (too many unacknowledged leases) likewise stop receiving
    new jobs but keep their allocation counted (filterLaggingExecutors:816).
"""

from __future__ import annotations

import dataclasses
import uuid
from typing import Callable, Optional, Sequence

from armada_tpu.core.config import SchedulingConfig
from armada_tpu.core.pipeline import (
    pipeline_enabled,
    pool_parallel_enabled,
    prefetch_worthwhile,
)
from armada_tpu.core.types import JobSpec, NodeSpec, Queue, RunningJob
from armada_tpu.jobdb.job import Job, JobRun
from armada_tpu.jobdb.jobdb import WriteTxn
from armada_tpu.models import (
    PoolRoundSpec,
    RoundOutcome,
    collect_round_stats,
    dispatch_pool_rounds,
    run_round_on_device,
    run_scheduling_round,
)
from armada_tpu.ops.metrics import mono_now
from armada_tpu.ops.trace import recorder as _trace
from armada_tpu.scheduler.executors import ExecutorSnapshot
from armada_tpu.scheduler.ratelimit import SchedulingRateLimiters


@dataclasses.dataclass
class PoolStats:
    pool: str
    outcome: RoundOutcome
    num_nodes: int
    num_queued: int
    num_running: int
    # Per-pool round observability (round 17): wall seconds of THIS pool's
    # round (prepare+dispatch share+fetch+apply) and whether it paid a
    # failover window (fallback-count delta across the round, the
    # degraded-attribution rule) -- feeds SLORecorder.observe_pool_round
    # so a slow tenant is visible behind its neighbours.
    round_s: float = 0.0
    degraded: bool = False
    # Market pools only (cycle_metrics.go:534,455,456): configured-shape
    # prices, the per-queue idealised ("boundary-less cluster") values, and
    # the realised values of what actually scheduled -- idealised minus
    # realised is the expectation gap (idealised_value_scheduler.go:28-33).
    market: bool = False
    indicative_prices: dict = dataclasses.field(default_factory=dict)
    idealised_values: dict = dataclasses.field(default_factory=dict)
    realised_values: dict = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class SchedulerResult:
    """The decisions of one cycle (the reference's SchedulerResult)."""

    # (job AFTER lease applied, its new run)
    scheduled: list = dataclasses.field(default_factory=list)
    # (job AFTER preemption applied, the preempted run)
    preempted: list = dataclasses.field(default_factory=list)
    # job ids attempted but unplaceable this round; lazily chained -- a round
    # can retire an entire unfeasible key class (~the whole backlog), and
    # materialising those ids costs seconds at 1M jobs (models/problem.py
    # LazyJobIds).
    failed: "object" = None
    pools: list = dataclasses.field(default_factory=list)  # list[PoolStats]

    def __post_init__(self):
        if self.failed is None:
            from armada_tpu.models.problem import ChainedJobIds

            self.failed = ChainedJobIds()


def _new_run_id() -> str:
    return uuid.uuid4().hex


def _running_of(job: Job, run: JobRun) -> RunningJob:
    """RunningJob view of a (job, run) pair for round inputs."""
    return RunningJob(
        job=dataclasses.replace(job.spec, priority=job.priority),
        node_id=run.node_id,
        priority=run.scheduled_at_priority or 0,
        away=run.pool_scheduled_away,
    )


class FairSchedulingAlgo:
    """Schedule(ctx, txn) over every pool; mutates the txn with the outcome."""

    def __init__(
        self,
        config: SchedulingConfig,
        queues: Callable[[], Sequence[Queue]],
        clock_ns: Callable[[], int],
        run_id_factory: Callable[[], str] = _new_run_id,
        collect_stats: bool = True,
        bid_prices=None,
        priority_overrides=None,
        feed=None,
    ):
        """bid_prices: BidPriceProvider for market-driven pools;
        priority_overrides: PriorityOverrideProvider replacing per-(pool,
        queue) fair-share weights (scheduler/providers.py);
        feed: scheduler.incremental_algo.IncrementalProblemFeed -- when set,
        non-market pool rounds assemble from cycle-persistent builders
        instead of re-reading every Job from the txn (the reference keeps its
        jobDb between cycles, scheduler.go:240-246).  The feed must be
        attached to the same JobDb the txns come from."""
        self.feed = feed
        self.config = config
        self._queues = queues
        self._clock_ns = clock_ns
        self._run_id = run_id_factory
        self.bid_prices = bid_prices
        self.priority_overrides = priority_overrides
        market_pools = [p.name for p in config.pools if p.market_driven]
        if market_pools and bid_prices is None:
            raise ValueError(
                f"pools {market_pools} are market driven: FairSchedulingAlgo "
                "needs a bid_prices provider (scheduler/providers.py)"
            )
        from armada_tpu.scheduler.short_job_penalty import ShortJobPenalty

        self.short_job_penalty = ShortJobPenalty(
            config.short_job_penalty_cutoffs()
        )
        self.gang_pricer = None
        if any(p.market_driven and p.gangs_to_price for p in config.pools):
            from armada_tpu.scheduler.pricer import IndicativeGangPricer

            self.gang_pricer = IndicativeGangPricer(config)
        self.optimiser = None
        if config.optimiser_enabled:
            from armada_tpu.scheduler.optimiser import Optimiser, OptimiserConfig

            self.optimiser = Optimiser(
                config,
                OptimiserConfig(
                    enabled=True,
                    maximum_job_size_to_preempt=(
                        config.optimiser_maximum_job_size_to_preempt
                    ),
                    max_stuck_jobs_per_cycle=config.optimiser_max_stuck_jobs,
                ),
            )
        # Per-queue share stats cost an extra device->host transfer; turn off
        # when neither metrics nor reports are wired.  The optimiser's ideal
        # victim order NEEDS the shares, and metric events publish them, so
        # either forces collection.
        self.collect_stats = (
            collect_stats
            or self.optimiser is not None
            or config.publish_metric_events
        )
        # Rate limiters (maximumSchedulingRate token buckets): clamp the
        # per-round burst caps so sustained throughput meets the config.
        self.rate_limiters = SchedulingRateLimiters(
            config.maximum_scheduling_rate,
            config.maximum_scheduling_burst,
            config.maximum_per_queue_scheduling_rate,
            config.maximum_per_queue_scheduling_burst,
            clock=lambda: self._clock_ns() / 1e9,
        )

    # --- executor health (scheduling_algo.go:780-830) -----------------------

    def _healthy_executors(
        self, executors: Sequence[ExecutorSnapshot], now_ns: int
    ) -> list[ExecutorSnapshot]:
        timeout_ns = int(self.config.executor_timeout_s * 1e9)
        out = []
        for ex in executors:
            if now_ns - ex.last_update_ns > timeout_ns:
                continue  # stale: invisible this round
            lagging = (
                len(ex.unacknowledged_runs)
                > self.config.max_unacknowledged_jobs_per_executor
            )
            if ex.cordoned or lagging:
                ex = dataclasses.replace(
                    ex,
                    nodes=tuple(
                        dataclasses.replace(n, unschedulable=True) for n in ex.nodes
                    ),
                )
            out.append(ex)
        return out

    # --- the per-cycle entry point ------------------------------------------

    def schedule(
        self,
        txn: WriteTxn,
        executors: Sequence[ExecutorSnapshot],
        now_ns: Optional[int] = None,
        quarantined_nodes: frozenset = frozenset(),
        shadow_work: Optional[list] = None,
    ) -> SchedulerResult:
        """quarantined_nodes: node ids excluded for high failure rates
        (README.md:28; scheduler/quarantine.py) -- treated like cordoned
        nodes: running jobs keep counting, nothing new lands.

        shadow_work: zero-arg callables the caller wants run in a kernel
        shadow (decision-independent host work -- the shadow pipeline's
        stage (a)/(b)); drained in the first device round's shadow, or
        inline before returning when no round runs.  Decisions are
        identical either way -- shadow thunks must not read this cycle's
        outcome or mutate its problem inputs."""
        now_ns = self._clock_ns() if now_ns is None else now_ns
        pending_shadow = list(shadow_work or [])

        def drain_shadow():
            while pending_shadow:
                pending_shadow.pop(0)()

        result = SchedulerResult()
        if self.config.disable_scheduling:
            # Incident brake (config disableScheduling): an EMPTY result, not
            # a skipped cycle, so metrics/reports cadence continues
            # (scheduling_algo.go:116 returns an empty SchedulerResult).
            drain_shadow()
            return result

        healthy = self._healthy_executors(executors, now_ns)
        nodes: list[NodeSpec] = []
        executor_of_node: dict[str, str] = {}
        for ex in healthy:
            for n in ex.nodes:
                if n.id in quarantined_nodes and not n.unschedulable:
                    n = dataclasses.replace(n, unschedulable=True)
                nodes.append(n)
                executor_of_node[n.id] = ex.id

        queues = list(self._queues())
        known_queues = {q.name for q in queues}

        pools = [p.name for p in self.config.pools]
        for n in nodes:
            if n.pool not in pools:
                pools.append(n.pool)

        incremental = self.feed is not None
        market_pools = {p.name for p in self.config.pools if p.market_driven}
        if incremental:
            # Overlay this txn's uncommitted changes onto the persistent
            # builders.  overlay() records what it applied so the same
            # deltas firing again (later pools' overlays, the commit
            # subscription) skip the idempotent re-apply instead of paying
            # for it.
            self.feed.overlay(txn._upserts, txn._deletes)
        # The full per-job txn scans below are what the incremental feed
        # exists to avoid; they remain for the legacy path and the short-job
        # penalty (derived from retained TERMINAL jobs the feed drops).
        # Market OBSERVABILITY (idealised/realised/indicative) used to force
        # them too; incremental market pools now compute it straight off the
        # builder columns (scheduler/idealised_columnar.py, pricer
        # _prepare_columnar), so a 1M-job market cycle stays O(deltas).
        need_job_scan = not incremental
        need_run_scan = (not incremental) or self.short_job_penalty.enabled

        # Queued jobs: validated, in a known queue, with their CURRENT priority
        # (reprioritisation updates Job.priority, not the immutable spec).
        queued_jobs: list[JobSpec] = []
        job_of_spec: dict[str, Job] = {}
        banned_nodes: dict[str, tuple] = {}  # retry anti-affinity
        if need_job_scan:
            for qname in txn.queues_with_queued_jobs():
                if qname not in known_queues:
                    continue
                for job in txn.queued_jobs(qname):
                    if not job.validated:
                        continue
                    # Validated pools (Job.pools) override the requested ones.
                    queued_jobs.append(
                        dataclasses.replace(
                            job.spec,
                            priority=job.priority,
                            pools=job.pools or job.spec.pools,
                        )
                    )
                    job_of_spec[job.id] = job
                    bans = job.anti_affinity_nodes()
                    if bans:
                        banned_nodes[job.id] = bans

        # Running jobs, grouped by pool of their run; short-job penalties
        # accumulate per (run pool, queue) off retained terminal jobs
        # (scheduling_algo.go:342-360 shortJobPenaltyByQueue).
        running_by_pool: dict[str, list[RunningJob]] = {p: [] for p in pools}
        penalty_by_pool: dict[str, dict[str, "object"]] = {}
        if need_run_scan:
            for job in txn.all_jobs():
                run = job.latest_run
                if job.queue not in known_queues:
                    continue
                if run is not None and self.short_job_penalty.applies(job, now_ns):
                    if job.spec.resources is not None:
                        pool_map = penalty_by_pool.setdefault(run.pool or "default", {})
                        prev = pool_map.get(job.queue)
                        atoms = job.spec.resources.atoms
                        pool_map[job.queue] = (
                            atoms
                            if prev is None
                            else [a + b for a, b in zip(prev, atoms)]
                        )
                    continue
                if run is None or run.in_terminal_state() or job.in_terminal_state():
                    continue
                pool = run.pool or "default"
                if pool not in running_by_pool:
                    running_by_pool[pool] = []
                running_by_pool[pool].append(_running_of(job, run))

        bid_price_of = None
        if self.bid_prices is not None:
            provider = self.bid_prices
            # Providers may scope bids per pool (pkg/bidstore keys prices by
            # pool; external_providers.BidPriceServiceClient takes pool=);
            # static in-process providers ignore the extra argument.
            import inspect

            takes_pool = "pool" in inspect.signature(provider.price).parameters

            def _pool_pricer(pool: str):
                if takes_pool:
                    return lambda job: provider.price(
                        job.queue, job.price_band, pool
                    )
                return lambda job: provider.price(job.queue, job.price_band)

            bid_price_of = _pool_pricer("")

        def pool_queues(pool: str) -> list:
            if self.priority_overrides is None:
                return queues
            return [
                (
                    Queue(q.name, ov)
                    if (ov := self.priority_overrides.override(pool, q.name))
                    is not None
                    else q
                )
                for q in queues
            ]

        queue_names = [q.name for q in queues]

        def round_tokens():
            return self.rate_limiters.tokens(queue_names)

        def consume_round(outcome):
            by_queue: dict[str, int] = {}
            for jid in outcome.scheduled:
                job = job_of_spec.get(jid) or txn.get(jid)
                if job is not None:
                    by_queue[job.queue] = by_queue.get(job.queue, 0) + 1
            if by_queue:
                self.rate_limiters.consume(by_queue)

        def commit_outcome(
            pool, outcome, *, num_queued, num_running, pool_nodes,
            market_b=None, running=(), bid_price_of=None, round_s=0.0,
            degraded=False,
        ):
            """The common per-pool tail -- consume, apply, overlay, stats --
            shared by the serial loop and the pool-parallel window's fetch
            phase.  ALWAYS called in pool-list order: the cross-pool apply
            order (and so the event order) is identical in every mode."""
            nonlocal queued_jobs
            consume_round(outcome)
            with _trace().span(
                "apply_outcome",
                pool=pool,
                scheduled=len(outcome.scheduled),
                preempted=len(outcome.preempted),
            ):
                self._apply_outcome(
                    txn, outcome, pool, executor_of_node, now_ns, result
                )
            if incremental:
                # Later pools must see this pool's leases/preemptions; the
                # overlay registry keeps this O(this pool's changes), not
                # O(all txn upserts so far).  (Under the pool-parallel
                # window this is additionally a certified no-op on the
                # OTHER window pools' tables -- pools_independent -- and it
                # fires in the same order as the serial loop regardless.)
                self.feed.overlay(txn._upserts)
            stats = PoolStats(
                pool=pool,
                outcome=outcome,
                num_nodes=len(pool_nodes),
                num_queued=num_queued,
                num_running=num_running,
                round_s=round_s,
                degraded=degraded,
            )
            pool_cfg = next(
                (p for p in self.config.pools if p.name == pool), None
            )
            if pool_cfg is not None and pool_cfg.market_driven:
                stats.market = True
                if incremental:
                    self._market_observability_columnar(
                        stats, pool, pool_nodes, txn, market_b, outcome,
                        bid_price_of,
                    )
                else:
                    self._market_observability(
                        stats, pool, pool_nodes, pool_queues(pool),
                        queued_jobs, running, outcome, bid_price_of,
                    )
            result.pools.append(stats)
            # Jobs scheduled in this pool are no longer queued for later pools.
            scheduled_ids = set(outcome.scheduled)
            if scheduled_ids:
                queued_jobs = [
                    j for j in queued_jobs if j.id not in scheduled_ids
                ]

        # --- pool-parallel serving (round 17, ARMADA_POOL_PARALLEL) ----------
        # Consecutive eligible pools form a WINDOW whose rounds all dispatch
        # through the device before any fetch (pool B's delta upload + kernel
        # dispatch fire while pool A's transfer is in flight), and
        # shape-matched window pools batch into ONE stacked kernel launch
        # (models.dispatch_pool_rounds).  Decisions stay bit-identical to the
        # serial loop: fetch/decode/apply runs strictly in pool-list order,
        # and the window only forms when the cycle CERTIFIES independence --
        #   * every queued job restricted to exactly one pool
        #     (feed.pools_independent(): pool A's apply then provably cannot
        #     touch pool B's assembled problem -- leases land in A's builder
        #     only, removes target ids B never held);
        #   * rate-limiter tokens provably NON-BINDING for the whole window
        #     (armed buckets make pool B's token reading depend on pool A's
        #     consumption; when every windowed pool's tokens minus the
        #     window's worst-case prior consumption still exceed its whole
        #     backlog, the caps cannot trip in either order and the reading
        #     difference is decision-inert);
        #   * non-market pools only (market observability reads builder
        #     state between rounds).
        # Anything else drains the window and runs serially -- a per-cycle
        # decision (a tenant submitting a multi-pool job just flips the next
        # cycle back to serial; scheduler/pool_serving.py counts it).
        from armada_tpu.core.watchdog import supervisor as _supervisor

        pool_parallel_armed = (
            pool_parallel_enabled() and incremental and len(pools) > 1
        )
        pool_parallel_ok = (
            pool_parallel_armed and self.feed.pools_independent()
        )
        window: list = []  # prepared, undispatched eligible pool rounds
        window_demand = [0]  # queued members across the open window
        pool_round_s: dict = {}
        cycle_stacked = [0, 0]  # launches, pools covered
        parallel_used = [False]
        pools_t0 = mono_now()

        def finish_window_round(entry, fin, deg0, fb_seen, failed) -> None:
            pool = entry["pool"]
            sup = _supervisor()
            t0 = mono_now()
            with _trace().span("round", pool=pool, parallel=True):
                res, outcome = fin()
            if self.collect_stats:
                collect_round_stats(
                    res, entry["pview"], entry["ctx"], self.config, outcome
                )
            dt = mono_now() - t0 + entry["prep_s"]
            pool_round_s[pool] = dt
            # Degraded-attribution rule across the WINDOW: deg0/fb_seen were
            # snapshotted BEFORE the dispatch phase (a drill-speed re-probe
            # can promote back before any fetch returns -- the round-10
            # misfiling); dispatch-phase failovers are attributed exactly
            # via the dispatch_failed set, finish-phase ones via the
            # fallback-count delta since the previous finish.
            fb_now = sup.fallbacks
            commit_outcome(
                pool,
                outcome,
                num_queued=entry["num_queued"],
                num_running=entry["num_running"],
                pool_nodes=entry["pool_nodes"],
                round_s=dt,
                degraded=deg0 or failed or fb_now > fb_seen[0],
            )
            fb_seen[0] = fb_now

        def flush_window() -> None:
            if not window:
                return
            entries = list(window)
            window.clear()
            window_demand[0] = 0
            specs = [e["spec"] for e in entries]
            sup = _supervisor()
            deg0 = sup.degraded
            t0 = mono_now()
            finishes, stacked, stacked_pools, dispatch_failed = (
                dispatch_pool_rounds(specs, self.config)
            )
            share = (mono_now() - t0) / len(entries)
            # baseline AFTER dispatch: dispatch-phase fallbacks are already
            # attributed per pool via dispatch_failed, so only finish-phase
            # deltas ride the counter.
            fb_seen = [sup.fallbacks]
            cycle_stacked[0] += stacked
            cycle_stacked[1] += stacked_pools
            if len(entries) >= 2:
                parallel_used[0] = True
            for i, (e, fin) in enumerate(zip(entries, finishes)):
                e["prep_s"] += share
                finish_window_round(
                    e, fin, deg0, fb_seen, i in dispatch_failed
                )

        for pool in pools:
            pool_nodes = [n for n in nodes if n.pool == pool]
            if not pool_nodes:
                continue
            window_eligible = pool_parallel_ok and pool not in market_pools
            if not window_eligible:
                # Ineligible pool ahead: every windowed round fetches and
                # applies NOW, so this pool's prepare sees exactly the state
                # the serial loop would have shown it.
                flush_window()
            bid_price_of = _pool_pricer(pool) if self.bid_prices is not None else None
            running = running_by_pool.get(pool, [])
            if incremental:
                prep_t0 = mono_now()
                b = self.feed.builder_for(pool, txn)
                # Market prices are re-read from the provider every cycle;
                # the builder's _prices() snapshot uses this callable.
                b.bid_price_of = bid_price_of
                b.set_queues(pool_queues(pool))
                b.set_nodes(pool_nodes)
                num_queued = len(b.jobs.key_of_id) + len(b.gang_jobs)
                num_running = len(b.runs.key_of_id)
                if not num_queued and not num_running:
                    continue
                g_tokens, q_tokens = round_tokens()
                if window_eligible:
                    # Token certification: this pool's burst caps must stay
                    # non-binding even if every EARLIER window pool schedules
                    # its entire backlog first (serial tokens >= this
                    # parallel reading minus that worst case).  num_queued
                    # counts gang MEMBERS, the unit the caps count.  A
                    # failure drains the window and runs this pool serially.
                    cum = window_demand[0]
                    tokens_ok = (
                        g_tokens is None or g_tokens - cum >= num_queued
                    ) and (
                        q_tokens is None
                        or all(
                            v - cum >= num_queued for v in q_tokens.values()
                        )
                    )
                    if not tokens_ok:
                        window_eligible = False
                        flush_window()
                        # The flush consumed the windowed pools' tokens;
                        # re-read so this pool's serial round sees exactly
                        # what the serial loop would have handed it.
                        g_tokens, q_tokens = round_tokens()
                # Slot-stable slab deltas: O(deltas) device upload per cycle
                # (models/slab.py); the round runs on the device-resident
                # problem the cache keeps current by scatter.
                bundle, ctx = b.assemble_delta(
                    global_tokens=g_tokens,
                    queue_tokens=q_tokens,
                    queue_penalty=penalty_by_pool.get(pool),
                )
                pview = bundle.stats_view()
                # Thunk, not a value: the device apply/upload runs inside
                # the watchdog deadline (a hung scatter IS a device loss),
                # and materialize() is the host-table ground truth the CPU
                # failover re-runs from.  Both close over live slab state,
                # which is unmutated until the decisions apply below.
                # EARLY-bound (default args, cache resolved NOW): an
                # abandoned watchdog worker that unwedges later must only
                # ever touch the cache object of ITS round -- by then the
                # orphaned garbage the reset hook replaced -- never the
                # live cache or a later iteration's bundle.
                devcache = self.feed.devcache_for(pool)
                if window_eligible:
                    # Window prepare: dispatch is deferred to the flush so
                    # shape-matched pools can stack into one launch; the
                    # spec mirrors the serial run_round_on_device call
                    # exactly.  The cross-pool content prefetch thunk is
                    # omitted here -- every window pool's bundle uploads at
                    # this flush anyway, and prefetch is bit-neutral by
                    # design (tests/test_pipeline.py).
                    window_demand[0] += num_queued
                    window.append(
                        dict(
                            pool=pool,
                            pview=pview,
                            ctx=ctx,
                            num_queued=num_queued,
                            num_running=num_running,
                            pool_nodes=pool_nodes,
                            prep_s=mono_now() - prep_t0,
                            spec=PoolRoundSpec(
                                problem=pview,
                                ctx=ctx,
                                device_problem=(
                                    lambda dc=devcache, b_=bundle: dc.apply(b_)
                                ),
                                host_problem=bundle.materialize,
                                shadow_work=(drain_shadow,),
                            ),
                        )
                    )
                    continue
                # Kernel shadow: the caller's deferred thunks plus the OTHER
                # pools' decision-independent slab prefetch (their submit
                # overlays are already final; this pool's bundle just
                # applied, so it is skipped) ride this round's kernel +
                # result transfer.
                shadow = [drain_shadow]
                if (
                    pipeline_enabled()
                    and len(self.feed.builders) > 1
                    and prefetch_worthwhile()
                ):
                    shadow.append(
                        lambda p=pool: self.feed.prefetch_content(skip_pool=p)
                    )
                # Mesh serving: the round span carries the device count the
                # resident slab is sharded over (0/absent = single device),
                # so a Perfetto timeline shows which ladder rung served it.
                mesh_n = getattr(devcache, "mesh_devices", 0)
                span_kw = {"mesh_devices": mesh_n} if mesh_n else {}
                sup = _supervisor()
                deg0 = sup.degraded
                fb0 = sup.fallbacks  # plain counter read: snapshot() takes the lock
                with _trace().span("round", pool=pool, **span_kw):
                    res, outcome = run_round_on_device(
                        pview,
                        ctx,
                        self.config,
                        device_problem=lambda dc=devcache, b_=bundle: dc.apply(
                            b_
                        ),
                        host_problem=bundle.materialize,
                        shadow_work=shadow,
                    )
                if self.collect_stats:
                    collect_round_stats(res, pview, ctx, self.config, outcome)
                dt = mono_now() - prep_t0  # prepare + round + stats
                pool_round_s[pool] = dt
                commit_outcome(
                    pool, outcome, num_queued=num_queued,
                    num_running=num_running, pool_nodes=pool_nodes,
                    market_b=b, running=running, bid_price_of=bid_price_of,
                    round_s=dt,
                    degraded=deg0 or sup.fallbacks > fb0,
                )
            else:
                if not queued_jobs and not running:
                    continue
                num_queued, num_running = len(queued_jobs), len(running)
                g_tokens, q_tokens = round_tokens()
                sup = _supervisor()
                deg0 = sup.degraded
                fb0 = sup.fallbacks  # plain counter read: snapshot() takes the lock
                t0 = mono_now()
                with _trace().span("round", pool=pool, legacy=True):
                    outcome = run_scheduling_round(
                        self.config,
                        pool=pool,
                        nodes=pool_nodes,
                        queues=pool_queues(pool),
                        queued_jobs=queued_jobs,
                        running=running,
                        collect_stats=self.collect_stats,
                        bid_price_of=bid_price_of,
                        global_tokens=g_tokens,
                        queue_tokens=q_tokens,
                        banned_nodes=banned_nodes,
                        queue_penalty=penalty_by_pool.get(pool),
                    )
                dt = mono_now() - t0
                pool_round_s[pool] = dt
                commit_outcome(
                    pool, outcome, num_queued=num_queued,
                    num_running=num_running, pool_nodes=pool_nodes,
                    running=running, bid_price_of=bid_price_of, round_s=dt,
                    degraded=deg0 or sup.fallbacks > fb0,
                )
        flush_window()
        if pool_round_s:
            # Cycle-level pool observability: the overlap ratio (sum of
            # per-pool round seconds over the pool section's wall clock --
            # ~1.0 serial, > 1.0 when dispatches overlapped fetches) rides
            # the cycle root span; the pool_serving ledger feeds /healthz
            # and bench.
            from armada_tpu.scheduler.pool_serving import pool_serving_stats

            wall = max(mono_now() - pools_t0, 1e-9)
            overlap = sum(pool_round_s.values()) / wall
            _trace().annotate(pool_overlap_ratio=round(overlap, 3))
            pool_serving_stats().record_cycle(
                parallel=parallel_used[0],
                armed=pool_parallel_armed,
                pool_round_s=pool_round_s,
                stacked_launches=cycle_stacked[0],
                stacked_pools=cycle_stacked[1],
                overlap_ratio=overlap,
            )

        # Away pass (scheduling_algo.go:216-283, nodePools:282): a pool's
        # still-queued jobs borrow nodes FROM its configured away_pools, at the
        # away priority level so the host pool's home jobs can always evict
        # them.  The host's running set is refreshed with this cycle's own
        # decisions (leases added, preemptions removed) so the away round
        # cannot double-book capacity the home rounds just committed.
        preempted_ids = {job.id for job, _ in result.preempted}
        extra_running: dict[str, list[RunningJob]] = {}
        for job, run in result.scheduled:
            extra_running.setdefault(run.pool, []).append(_running_of(job, run))

        def host_running(host: str) -> list[RunningJob]:
            kept = [
                r
                for r in running_by_pool.get(host, [])
                if r.job.id not in preempted_ids
            ]
            return kept + extra_running.get(host, [])

        for pool_cfg in self.config.pools:
            if not pool_cfg.away_pools:
                continue
            home_pool = pool_cfg.name
            # The feed tracks pool-restricted queued jobs in a side set, so
            # the away candidate scan is O(candidates), not O(backlog).
            away_pool_source = (
                self.feed.away_candidates(txn) if incremental else queued_jobs
            )
            away_jobs = [
                j
                for j in away_pool_source
                if j.pools and home_pool in j.pools
            ]
            if not away_jobs:
                continue
            if incremental:
                # Retry anti-affinity for away candidates (the legacy scan
                # collected these into banned_nodes already).
                for j in away_jobs:
                    job = txn.get(j.id)
                    bans = job.anti_affinity_nodes() if job is not None else ()
                    if bans:
                        banned_nodes[j.id] = bans
            for host in pool_cfg.away_pools:
                host_nodes = [n for n in nodes if n.pool == host]
                if not host_nodes or not away_jobs:
                    continue
                g_tokens, q_tokens = round_tokens()
                with _trace().span("away_round", host=host, home=home_pool):
                    outcome = run_scheduling_round(
                        self.config,
                        pool=host,
                        nodes=host_nodes,
                        queues=pool_queues(host),
                        queued_jobs=[
                            dataclasses.replace(j, pools=(host,))
                            for j in away_jobs
                        ],
                        running=(
                            self.feed.running_of(host, txn)
                            if incremental
                            else host_running(host)
                        ),
                        collect_stats=False,
                        bid_price_of=(
                            _pool_pricer(host)
                            if self.bid_prices is not None
                            else None
                        ),
                        away_mode=True,
                        global_tokens=g_tokens,
                        queue_tokens=q_tokens,
                        banned_nodes=banned_nodes,
                        queue_penalty=penalty_by_pool.get(host),
                    )
                consume_round(outcome)
                self._apply_outcome(
                    txn, outcome, host, executor_of_node, now_ns, result, away=True
                )
                if incremental:
                    self.feed.overlay(txn._upserts)
                scheduled_ids = set(outcome.scheduled)
                if scheduled_ids:
                    queued_jobs = [
                        j for j in queued_jobs if j.id not in scheduled_ids
                    ]
                    away_jobs = [
                        j for j in away_jobs if j.id not in scheduled_ids
                    ]
                    for job, run in result.scheduled:
                        if job.id in scheduled_ids:
                            extra_running.setdefault(run.pool, []).append(
                                _running_of(job, run)
                            )

        # Optimiser pass (optimiser/node_scheduler.go via pqs.go:250-272):
        # jobs the rounds could not place get one targeted-preemption attempt.
        if self.optimiser is not None:
            self._optimise_stuck(
                txn,
                result,
                queued_jobs,
                nodes,
                running_by_pool,
                extra_running,
                executor_of_node,
                now_ns,
                banned_nodes,
            )

        # No device round ran (or the legacy path): the caller's thunks
        # still execute exactly once, just without a shadow to hide in.
        drain_shadow()
        return result

    def _market_observability(
        self,
        stats: PoolStats,
        pool: str,
        pool_nodes: list,
        queues: list,
        queued_jobs: list,
        running: list,
        outcome: RoundOutcome,
        bid_price_of,
    ) -> None:
        """Market-pool extras: indicative gang prices against the post-round
        state (pqs.go runPricer:596) and idealised per-queue values
        (scheduling_algo.go:595 CalculateIdealisedValue)."""
        if bid_price_of is None:
            return
        if self.gang_pricer is not None:
            preempted_now = set(outcome.preempted)
            by_id = {j.id: j for j in queued_jobs}
            running_now = [r for r in running if r.job.id not in preempted_now]
            for jid, nid in outcome.scheduled.items():
                job = by_id.get(jid)
                if job is not None:
                    running_now.append(RunningJob(job=job, node_id=nid))
            stats.indicative_prices = self.gang_pricer.price_pool_gangs(
                pool, pool_nodes, running_now, bid_price_of
            )
        from armada_tpu.scheduler.idealised import (
            calculate_idealised_values,
            value_of_jobs,
        )

        stats.idealised_values = calculate_idealised_values(
            self.config,
            pool=pool,
            nodes=pool_nodes,
            queues=queues,
            queued_jobs=queued_jobs,
            running=running,
            bid_price_of=bid_price_of,
        )
        # Realised value: what this round's actual placements are worth --
        # newly scheduled jobs plus evicted-and-rescheduled ones
        # (scheduling_algo.go:670-676 valueFromSchedulingResult on the real
        # context), in the SAME valuation currency as idealised.
        spec_of = {j.id: j for j in queued_jobs}
        spec_of.update({r.job.id: r.job for r in running})
        placed = (
            spec_of[jid]
            for jid in list(outcome.scheduled) + list(outcome.rescheduled)
            if jid in spec_of
        )
        stats.realised_values = value_of_jobs(
            placed, bid_price_of, self.config.resource_list_factory()
        )

    def _market_observability_columnar(
        self,
        stats: PoolStats,
        pool: str,
        pool_nodes: list,
        txn: WriteTxn,
        builder,
        outcome: RoundOutcome,
        bid_price_of,
    ) -> None:
        """Incremental-mode market observability: the same three quantities
        as _market_observability, read off the builder columns instead of
        spec lists (the builder's runs table already reflects this pool's
        leases and preemptions -- feed.overlay() ran before stats).
        Realised values stay O(decisions) via txn lookups."""
        if bid_price_of is None:
            return
        from armada_tpu.scheduler.idealised import value_of_jobs
        from armada_tpu.scheduler.idealised_columnar import (
            _band_price_table,
            calculate_idealised_values_columnar,
        )

        price_table = _band_price_table(builder, bid_price_of)
        if self.gang_pricer is not None:
            stats.indicative_prices = self.gang_pricer.price_pool_gangs_columnar(
                pool, pool_nodes, builder, bid_price_of, price_table
            )
        # The mega round's candidate set is the PRE-round state
        # (idealised_value.go:68-76): jobs preempted this cycle already left
        # the builder tables (feed.overlay() ran), so they re-enter here
        # explicitly -- O(preempted) txn lookups.
        preempted_specs = []
        for jid in outcome.preempted:
            job = txn.get(jid)
            if job is not None:
                preempted_specs.append(
                    dataclasses.replace(
                        job.spec,
                        priority=job.priority,
                        pools=job.pools or job.spec.pools,
                    )
                )
        stats.idealised_values = calculate_idealised_values_columnar(
            self.config,
            pool=pool,
            builder=builder,
            bid_price_of=bid_price_of,
            extra_candidates=tuple(preempted_specs),
            price_table=price_table,
        )
        placed = []
        for jid in list(outcome.scheduled) + list(outcome.rescheduled):
            job = txn.get(jid)
            if job is not None:
                placed.append(job.spec)
        stats.realised_values = value_of_jobs(
            placed, bid_price_of, self.config.resource_list_factory()
        )

    def _optimise_stuck(
        self,
        txn: WriteTxn,
        result: SchedulerResult,
        queued_jobs: list,
        nodes: list,
        running_by_pool: dict,
        extra_running: dict,
        executor_of_node: dict,
        now_ns: int,
        banned_nodes: Optional[dict] = None,
    ) -> None:
        preempted_ids = {job.id for job, _ in result.preempted}
        still_queued = {j.id: j for j in queued_jobs}

        def resolve_queued(jid):
            spec = still_queued.get(jid)
            if spec is not None:
                return spec
            # Incremental mode keeps no spec list; the txn is the truth.
            job = txn.get(jid)
            if job is None or not job.queued or not job.validated:
                return None
            return dataclasses.replace(
                job.spec, priority=job.priority, pools=job.pools or job.spec.pools
            )

        # The optimiser places at most max_stuck_jobs_per_cycle; collecting a
        # generous multiple of that preserves its own candidate ordering
        # while keeping the scan O(candidates), not O(failed backlog) -- a
        # round can retire whole key classes (~the entire backlog in
        # outcome.failed, decoded lazily in chunks).
        candidate_cap = max(100, 10 * self.optimiser.opt.max_stuck_jobs_per_cycle)
        for stats in result.pools:
            pool = stats.pool
            stuck = []
            for jid in stats.outcome.failed:
                spec = resolve_queued(jid)
                if spec is not None:
                    stuck.append(spec)
                    if len(stuck) >= candidate_cap:
                        break
            if not stuck:
                continue
            pool_nodes = [n for n in nodes if n.pool == pool]
            if self.feed is not None:
                running_now = self.feed.running_of(pool, txn)
            else:
                running_now = [
                    r
                    for r in running_by_pool.get(pool, [])
                    if r.job.id not in preempted_ids
                ] + extra_running.get(pool, [])
            if self.feed is not None and banned_nodes is not None:
                # Incremental mode skipped the legacy scan that collects
                # retry anti-affinity: resolve bans for the stuck set so the
                # optimiser cannot re-place a job on the node it died on.
                for spec in stuck:
                    job = txn.get(spec.id)
                    bans = job.anti_affinity_nodes() if job is not None else ()
                    if bans:
                        banned_nodes[spec.id] = bans
            shares = stats.outcome.queue_stats
            decisions = self.optimiser.optimise(
                stuck,
                pool_nodes,
                running_now,
                actual_share={q: s["actual_share"] for q, s in shares.items()},
                fair_share={
                    q: s["adjusted_fair_share"] for q, s in shares.items()
                },
                banned_nodes=banned_nodes,
            )
            for d in decisions:
                # The rate limiters gate optimiser placements too.
                spec = resolve_queued(d.job_id)
                queue = spec.queue if spec is not None else ""
                g_tokens, q_tokens = self.rate_limiters.tokens([queue])
                if g_tokens is not None and g_tokens < 1:
                    break
                if q_tokens is not None and q_tokens.get(queue, 1) < 1:
                    continue
                synthetic = RoundOutcome(
                    scheduled={d.job_id: d.node_id},
                    preempted=list(d.preempted_job_ids),
                    failed=[],
                    num_iterations=0,
                    termination="optimiser",
                )
                self._apply_outcome(
                    txn, synthetic, pool, executor_of_node, now_ns, result
                )
                self.rate_limiters.consume({queue: 1})
                still_queued.pop(d.job_id, None)

    # --- applying a pool outcome to the txn ---------------------------------

    def _apply_outcome(
        self,
        txn: WriteTxn,
        outcome: RoundOutcome,
        pool: str,
        executor_of_node: dict,
        now_ns: int,
        result: SchedulerResult,
        away: bool = False,
    ) -> None:
        away_priority = self.config.priority_ladder()[0]
        for job_id, node_id in outcome.scheduled.items():
            job = txn.get(job_id)
            if job is None:
                continue
            pc = job.priority_class(self.config)
            run = JobRun(
                id=self._run_id(),
                job_id=job_id,
                created_ns=now_ns,
                executor=executor_of_node.get(node_id, ""),
                node_id=node_id,
                node_name=node_id,
                pool=pool,
                scheduled_at_priority=away_priority if away else pc.priority,
                pool_scheduled_away=away,
            )
            job = job.with_new_run(run)
            txn.upsert(job)
            result.scheduled.append((job, run))

        for job_id in outcome.preempted:
            job = txn.get(job_id)
            if job is None or job.in_terminal_state():
                continue
            run = job.latest_run
            if run is None or run.in_terminal_state():
                continue
            run = run.with_preempted()
            job = job.with_updated_run(run).with_failed()
            txn.upsert(job)
            result.preempted.append((job, run))

        result.failed.extend(outcome.failed)
