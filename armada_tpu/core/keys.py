"""Categorical compression: node types and scheduling keys.

The reference collapses nodes into `NodeType`s -- the hash of (taints, indexed
labels) -- so that taint/label fit is checked once per (job, nodeType) instead of per
(job, node) (internaltypes/node_type.go; nodedb/nodematching.go:127-145), and
collapses jobs into `SchedulingKey`s -- the hash of everything that affects where a
job can run (internaltypes/podutils.go SchedulingKeyGenerator) -- used both to skip
identical unfeasible jobs (gang_scheduler.go:64-98) and to cache submit checks
(submitcheck.go:243).

Here the same idea becomes the device-side representation: the (key x type) static
fit matrix is precomputed on host with exact string matching, and on device fit is a
single gather `compat[job_key, node_type]` -- no string ever reaches the TPU.
"""

from __future__ import annotations

import dataclasses
from typing import Mapping, Optional, Sequence

import numpy as np

from armada_tpu.core.types import (
    JobSpec,
    NodeSpec,
    Taint,
    Toleration,
    selector_matches,
    taints_tolerated,
)


@dataclasses.dataclass(frozen=True)
class NodeType:
    """Identity of a class of nodes indistinguishable to static fit checks."""

    taints: tuple[Taint, ...]
    indexed_labels: tuple[tuple[str, str], ...]  # sorted (label, value) pairs
    # Hardware type (NodeSpec.node_type, executor-reported): two nodes with
    # identical taints/labels but different hardware are NOT interchangeable
    # once any job declares per-type scores, so the hardware axis is part of
    # node-type identity.  "" (the default) keeps single-type worlds on the
    # exact pre-hetero identities.
    hw_type: str = ""


@dataclasses.dataclass(frozen=True)
class SchedulingKey:
    """Identity of a class of jobs indistinguishable to the scheduler."""

    resources: tuple[int, ...]  # atoms, fixed axis order
    node_selector: tuple[tuple[str, str], ...]
    tolerations: tuple[Toleration, ...]
    priority_class: str
    priority: int
    # Retry anti-affinity terms (scheduler.go:522-568): the reference folds
    # affinity into the key via the pod requirements, so a retried job never
    # shares an unfeasible-key class with a clean one.
    banned_nodes: tuple[str, ...] = ()
    # (uniformity label, chosen domain value) for gangs constrained to one
    # node domain (gang_scheduler.go NodeUniformity): a domain-restricted
    # gang must never retire the unrestricted jobs' key class.
    uniformity: tuple[str, str] = ("", "")
    # Per-node-type effective-throughput map (JobSpec.node_type_scores,
    # sorted).  Part of key identity because the key must determine EVERY
    # placement-relevant property: the per-key fit cache and commit_k's head
    # certification key on it, and a type-sensitive job sharing a key class
    # with an insensitive twin would poison both (docs/lint.md ledger:
    # "key must absorb the type axis").  () = type-insensitive.
    type_scores: tuple[tuple[str, float], ...] = ()


class NodeTypeIndex:
    """Assigns each node a dense node-type id; built per round on host."""

    def __init__(self, indexed_labels: Sequence[str]):
        self.indexed_labels = tuple(sorted(set(indexed_labels)))
        self.types: list[NodeType] = []
        self._ids: dict[NodeType, int] = {}

    def type_of(self, node: NodeSpec) -> int:
        labels = tuple(
            (k, node.labels[k]) for k in self.indexed_labels if k in node.labels
        )
        nt = NodeType(tuple(node.taints), labels, node.node_type)
        tid = self._ids.get(nt)
        if tid is None:
            tid = len(self.types)
            self.types.append(nt)
            self._ids[nt] = tid
        return tid

    def __len__(self) -> int:
        return len(self.types)


def class_signature(job: JobSpec, node_id_label: str) -> tuple:
    """The hashable identity of a job's scheduling class -- EXACTLY the
    fields SchedulingKeyIndex.key_of folds into the key (minus per-gang bans
    and uniformity, which are gang-level).  Shared by the problem builder's
    provisional gang grouping and the SubmitChecker so their class splits can
    never diverge from the interned keys (the node-id pinning label is
    excluded in both, matching key_of)."""
    selector = (
        tuple(
            sorted(
                (k, v) for k, v in job.node_selector.items() if k != node_id_label
            )
        )
        if job.node_selector
        else ()
    )
    return (
        job.resources.atoms_tuple() if job.resources else (),
        selector,
        tuple(job.tolerations),
        job.priority_class,
        job.priority,
        tuple(job.node_type_scores),
    )


class SchedulingKeyIndex:
    """Assigns each job a dense scheduling-key id; built per round on host."""

    def __init__(self):
        self.keys: list[SchedulingKey] = []
        self._ids: dict[SchedulingKey, int] = {}

    def key_of(
        self,
        job: JobSpec,
        node_id_label: str = "kubernetes.io/hostname",
        banned_nodes: Sequence[str] = (),
        uniformity: tuple = ("", ""),
    ) -> int:
        # The node-id pinning label is excluded: pinning is handled positionally via
        # the pinned-node tensor, the way the reference injects node-id selectors
        # for evicted jobs (internal/scheduler/api.go addNodeIdSelector:278).
        # Hot path (one call per queued job per round): probe with a plain
        # tuple and only materialize the SchedulingKey dataclass on a miss.
        selector = (
            tuple(
                sorted(
                    (k, v)
                    for k, v in job.node_selector.items()
                    if k != node_id_label
                )
            )
            if job.node_selector
            else ()
        )
        resources = job.resources.atoms_tuple() if job.resources else ()
        tolerations = tuple(job.tolerations)
        bans = tuple(sorted(banned_nodes)) if banned_nodes else ()
        uni = tuple(uniformity)
        tscores = tuple(job.node_type_scores)
        probe = (
            resources, selector, tolerations, job.priority_class, job.priority,
            bans, uni, tscores,
        )
        kid = self._ids.get(probe)
        if kid is None:
            kid = len(self.keys)
            self.keys.append(
                SchedulingKey(
                    resources=resources,
                    node_selector=selector,
                    tolerations=tolerations,
                    priority_class=job.priority_class,
                    priority=job.priority,
                    banned_nodes=bans,
                    uniformity=uni,
                    type_scores=tscores,
                )
            )
            self._ids[probe] = kid
        return kid

    def __len__(self) -> int:
        return len(self.keys)


def type_feasible(key: SchedulingKey, nt: NodeType) -> bool:
    """Does the key's type-score map admit hardware type `nt.hw_type`?

    A NONEMPTY map is a whitelist with weights (Gavel-style: a job has a
    throughput on each type it can run on): hardware types absent from the
    map, or mapped to a throughput <= 0, are infeasible.  An empty map (the
    default) admits every type.
    """
    if not key.type_scores:
        return True
    for name, thr in key.type_scores:
        if name == nt.hw_type:
            return thr > 0
    return False


def static_fit_matrix(
    keys: Sequence[SchedulingKey],
    types: Sequence[NodeType],
    *,
    pre_type: bool = False,
) -> np.ndarray:
    """bool[K, T]: does job-class k statically fit node-class t?

    Static fit = tolerations cover the type's blocking taints AND the selector is
    satisfied by the type's indexed labels (nodematching.go NodeTypeJobRequirementsMet
    :127 + StaticJobRequirementsMet:161) AND the key's node-type-score map admits
    the type's hardware (`type_feasible`).  Callers must index every label referenced
    by a selector (the problem builder does, via labels_referenced_by_selectors);
    a selector naming an unindexed label never matches.

    pre_type=True skips the hardware-type gate -- the explain pass's
    type-mismatch partition needs "would this fit if the type map admitted
    everything" to tell type-gated infeasibility from shape infeasibility.
    """
    out = np.zeros((len(keys), len(types)), dtype=bool)
    type_labels = [dict(nt.indexed_labels) for nt in types]
    for ki, key in enumerate(keys):
        sel = dict(key.node_selector)
        for ti, nt in enumerate(types):
            if not taints_tolerated(nt.taints, key.tolerations):
                continue
            if not selector_matches(sel, type_labels[ti]):
                continue
            if pre_type or type_feasible(key, nt):
                out[ki, ti] = True
    return out


# Packing scores live in [0, R] (per-resource terms are alloc/scale <= 1);
# a bias of 1024 per unit of (1/throughput - 1) tiers nodes by declared
# throughput (types differing >= ~1% in 1/throughput never lose to packing)
# while equal-throughput types still pack best-fit.  Power of two: the
# f32 add `score + bias` the kernel and the sequential oracle both perform
# stays exactly mirrorable.
TYPE_BIAS_SCALE = 1024.0


def type_score_tables(
    keys: Sequence[SchedulingKey],
    types: Sequence[NodeType],
    K: int,
    T: int,
    *,
    row_bucket: int = 8,
) -> tuple[np.ndarray, np.ndarray]:
    """The kernel's per-type score-adjust tables, padded to (K, T).

    Returns (key_type_row i32[K], type_bias f32[TR, T]):

    - `key_type_row[k]` = the bias row of key k; row 0 is the all-zero
      insensitive row, so every key with an empty type-score map (and every
      padded key slot) shares it and TR == 1 means "no sensitive key in
      this problem" -- the structural switch the kernel uses to compile the
      exact pre-hetero body.
    - `type_bias[r, t]` = (1/throughput - 1) * TYPE_BIAS_SCALE for hardware
      types the row's map names feasibly; 0 elsewhere (infeasible types are
      excluded by the compat gate, never by bias).  Computed in f32.

    Distinct nonempty maps intern distinct rows; TR pads to `row_bucket`
    past 1 so a newly interned map mid-steady-state rarely changes the
    compiled shape (the compat-table discipline).
    """
    rows: dict[tuple, int] = {}
    key_type_row = np.zeros((K,), np.int32)
    for ki, key in enumerate(keys):
        if not key.type_scores:
            continue
        row = rows.get(key.type_scores)
        if row is None:
            row = len(rows) + 1
            rows[key.type_scores] = row
        key_type_row[ki] = row
    if not rows:
        return key_type_row, np.zeros((1, T), np.float32)
    TR = ((len(rows) + 1 + row_bucket - 1) // row_bucket) * row_bucket
    type_bias = np.zeros((TR, T), np.float32)
    hw_of = [nt.hw_type for nt in types]
    for tscores, row in rows.items():
        by_name = dict(tscores)
        for ti, hw in enumerate(hw_of):
            thr = by_name.get(hw)
            if thr is not None and thr > 0:
                type_bias[row, ti] = np.float32(
                    (np.float32(1.0) / np.float32(thr) - np.float32(1.0))
                    * np.float32(TYPE_BIAS_SCALE)
                )
    return key_type_row, type_bias


def labels_referenced_by_selectors(
    jobs: Sequence[JobSpec], node_id_label: str
) -> set[str]:
    """Labels that must be folded into node types for exact static fit."""
    out: set[str] = set()
    for job in jobs:
        for k in job.node_selector:
            if k != node_id_label:
                out.add(k)
    return out
