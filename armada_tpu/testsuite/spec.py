"""TestSpec: one declarative end-to-end scenario.

Mirror of the reference's pkg/api/testspec.proto:13-53 in YAML:

    name: gang-lifecycle
    queue: e2e-test            # created if missing
    timeout: 60                # seconds to see all expected events
    jobs:                      # same job shape as armadactl submit
      - count: 2
        resources: {cpu: "1", memory: 1Gi}
        gangId: g1
        gangCardinality: 2
    expectedEvents: [submitted, leased, running, succeeded]
    cancel: none               # none | byId | bySet -- cancel after submit
"""

from __future__ import annotations

import dataclasses
from typing import Sequence

# testsuite event vocabulary -> our event kinds (testspec.proto expected events)
EVENT_NAMES = {
    "submitted": "submit_job",
    "validated": "job_validated",
    "leased": "job_run_leased",
    "pending": "job_run_assigned",
    "running": "job_run_running",
    "succeeded": "job_succeeded",
    "failed": "job_errors",
    "cancelled": "cancelled_job",
    "preempted": "job_run_preempted",
    "requeued": "job_requeued",
}


@dataclasses.dataclass(frozen=True)
class TestSpec:
    __test__ = False  # tell pytest this is not a test class

    name: str
    queue: str
    jobs: tuple  # tuple[JobSubmitItem, ...]
    expected_events: tuple[str, ...]  # in EVENT_NAMES vocabulary
    timeout_s: float = 60.0
    cancel: str = "none"  # none | byId | bySet
    queue_weight: float = 1.0

    def __post_init__(self):
        for ev in self.expected_events:
            if ev not in EVENT_NAMES:
                raise ValueError(
                    f"spec {self.name}: unknown expected event {ev!r} "
                    f"(known: {', '.join(sorted(EVENT_NAMES))})"
                )
        if self.cancel not in ("none", "byId", "bySet"):
            raise ValueError(f"spec {self.name}: invalid cancel mode {self.cancel!r}")
        if not self.jobs:
            raise ValueError(f"spec {self.name}: no jobs")


def _items_from_yaml(job_docs: Sequence[dict]):
    from armada_tpu.cli.armadactl import job_items_from_docs

    return tuple(job_items_from_docs(job_docs))


def load_spec(path: str) -> TestSpec:
    import yaml

    with open(path) as f:
        doc = yaml.safe_load(f)
    return TestSpec(
        name=doc.get("name") or path,
        queue=doc["queue"],
        jobs=_items_from_yaml(doc.get("jobs", [])),
        expected_events=tuple(doc.get("expectedEvents", ["submitted", "succeeded"])),
        timeout_s=float(doc.get("timeout", 60.0)),
        cancel=doc.get("cancel", "none"),
        queue_weight=float(doc.get("queueWeight", 1.0)),
    )
