"""The scheduler main loop: sync -> transition -> schedule -> publish -> commit.

Equivalent of the reference's Scheduler (internal/scheduler/scheduler.go:33-41
docstring, Run:142, cycle:246).  Each cycle:

  1. syncState: incremental fetch from the scheduler DB (rows whose serial
     advanced) reconciled into the JobDb txn (scheduler.go syncState:386).
  2. Leader check: followers commit the synced state and stop (scheduler.go:263).
  3. generateUpdateMessages: derive state-transition events from what the DB
     told us -- cancellations, run success/failure, retries/requeues,
     validation (scheduler.go:698, submitCheck:1011).
  4. expireJobsIfNecessary: executors past their heartbeat timeout lose their
     active runs; the jobs are returned and requeued (scheduler.go:929).
  5. schedulingAlgo.Schedule: the TPU round over every pool (the replaceable
     interface, scheduling_algo.go:36-41).
  6. eventsFromSchedulerResult: leases + preemptions as events
     (scheduler.go:570).
  7. Re-validate leadership (token fencing), publish every event sequence to
     the log, commit the JobDb txn (scheduler.go:355,375).

If publish fails (or leadership was lost) the txn aborts: no decision leaks
into local state that is not also in the log -- the log stays the source of
truth, and the next cycle re-derives everything from the DB.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Callable, Iterable, Optional, Sequence

from armada_tpu.core.config import SchedulingConfig
from armada_tpu.core.logging import get_logger
from armada_tpu.core.pipeline import pipeline_enabled, prefetch_worthwhile
from armada_tpu.events import events_pb2 as pb
from armada_tpu.eventlog.publisher import Publisher, wait_for_markers
from armada_tpu.ingest.schedulerdb import SchedulerDb
from armada_tpu.jobdb.job import Job, JobRun
from armada_tpu.jobdb.jobdb import JobDb, WriteTxn
from armada_tpu.scheduler.algo import FairSchedulingAlgo, SchedulerResult
from armada_tpu.scheduler.executors import ExecutorSnapshot
from armada_tpu.scheduler.leader import LeaderController, LeaderToken
from armada_tpu.scheduler.quarantine import NodeQuarantine
from armada_tpu.scheduler.reconciliation import apply_rows
from armada_tpu.scheduler.short_job_penalty import ShortJobPenalty
from armada_tpu.scheduler.submitcheck import SubmitChecker

MAX_RETRIES_EXCEEDED = "maxRetriesExceeded"
PREEMPTED_REASON = "preempted"
LEASE_EXPIRED = "leaseExpired"

_log = get_logger(__name__)


@dataclasses.dataclass
class CycleResult:
    """What one cycle did (inputs to metrics + tests)."""

    leader: bool = False
    scheduled: bool = False
    synced_jobs: list = dataclasses.field(default_factory=list)
    published: list = dataclasses.field(default_factory=list)  # EventSequences
    scheduler_result: Optional[SchedulerResult] = None

    def events_by_kind(self) -> dict:
        out: dict = {}
        for seq in self.published:
            for ev in seq.events:
                kind = ev.WhichOneof("event")
                out[kind] = out.get(kind, 0) + 1
        return out


class _SequenceBuilder:
    """Accumulates events grouped per (queue, jobset) EventSequence."""

    def __init__(self):
        self._seqs: dict[tuple[str, str], pb.EventSequence] = {}

    def add(self, queue: str, jobset: str, event: pb.Event) -> None:
        key = (queue, jobset)
        seq = self._seqs.get(key)
        if seq is None:
            seq = pb.EventSequence(queue=queue, jobset=jobset)
            self._seqs[key] = seq
        seq.events.append(event)

    def build(self) -> list[pb.EventSequence]:
        return [s for s in self._seqs.values() if len(s.events)]


class Scheduler:
    """The scheduling service main loop (scheduler.go:142)."""

    def __init__(
        self,
        db: SchedulerDb,
        jobdb: JobDb,
        algo: FairSchedulingAlgo,
        publisher: Publisher,
        leader: LeaderController,
        config: Optional[SchedulingConfig] = None,
        clock: Callable[[], float] = time.time,
        metrics=None,
        reports=None,
        ingest_step: Optional[Callable[[], int]] = None,
    ):
        """ingest_step: drives an in-process ingestion pipeline during marker
        fencing (deployments with background ingester threads leave it None)."""
        self.db = db
        self.jobdb = jobdb
        self.algo = algo
        self.publisher = publisher
        self.leader = leader
        self.config = config or jobdb.config
        self._clock = clock
        self.submit_checker = SubmitChecker(self.config)
        self.short_job_penalty = ShortJobPenalty(
            self.config.short_job_penalty_cutoffs()
        )
        self.node_quarantine = NodeQuarantine(
            failure_threshold=self.config.node_quarantine_failure_threshold,
            window_s=self.config.node_quarantine_window_s,
            cooldown_s=self.config.node_quarantine_cooldown_s,
        )
        # Optional observability hooks (SchedulerMetrics /
        # SchedulingReportsRepository); None = disabled.
        self.metrics = metrics
        self.reports = reports
        self.ingest_step = ingest_step
        # Incremental-fetch cursors (scheduler.go jobsSerial/runsSerial:79-81).
        self._jobs_serial = 0
        self._runs_serial = 0
        self._was_leader = False
        # Terminal jobs kept in the JobDb for the short-job penalty window
        # (scheduler.go:436-447); swept in sync_state once the window lapses.
        self._retained_terminal: set = set()
        # Durable checkpoints (scheduler/checkpoint.py): serve wires a
        # CheckpointManager + interval; the run loop snapshots the
        # materialized plane while leading, and `armadactl checkpoint`
        # triggers one on demand through the same method.
        self.checkpointer = None
        self.checkpoint_interval_s: float = 0.0
        self._last_checkpoint_mono: float = 0.0
        self.last_checkpoint: Optional[dict] = None
        # Replicated deployments: serve points this at the LogReplicator's
        # status() so the durability block carries replication lag.
        self.replication_status = None

    def now_ns(self) -> int:
        return int(self._clock() * 1e9)

    # --- state sync (scheduler.go syncState:386) ----------------------------

    def sync_state(self, txn: WriteTxn) -> list[str]:
        job_rows, run_rows = self.db.fetch_job_updates(
            self._jobs_serial, self._runs_serial
        )
        touched = apply_rows(
            txn,
            job_rows,
            run_rows,
            self.config,
            retained_terminal=(
                self._retained_terminal if self.short_job_penalty.enabled else None
            ),
        )
        if job_rows:
            self._jobs_serial = max(r["serial"] for r in job_rows)
        if run_rows:
            self._runs_serial = max(r["serial"] for r in run_rows)
        if self._retained_terminal:
            # Sweep ONLY the jobs retained from DB-terminal rows, once their
            # penalty window lapses (scheduler.go:436-447 retains; the
            # lapse-side delete is ours -- the reference only re-examines
            # changed jobs and so leaks these).  O(retained), and never
            # touches locally-terminal jobs still awaiting their round-trip.
            now_ns = self.now_ns()
            for job_id in list(self._retained_terminal):
                job = txn.get(job_id)
                if job is None or not self.short_job_penalty.applies(job, now_ns):
                    if job is not None:
                        txn.delete(job_id)
                    self._retained_terminal.discard(job_id)
        return touched

    # --- recovery fencing (scheduler.go ensureDbUpToDate:1120) --------------

    def ensure_db_up_to_date(
        self,
        ingest_step: Optional[Callable[[], int]] = None,
        timeout_s: float = 30.0,
        poll_interval_s: float = 0.05,
    ) -> None:
        """Publish a marker to every partition and wait until the ingestion
        path has materialized all of them: after this, the DB reflects every
        event published before our leadership began.  `ingest_step` (if given)
        drives an in-process ingestion pipeline between polls."""
        group = self.publisher.publish_markers()
        deadline = time.monotonic() + timeout_s
        num_parts = self.publisher._log.num_partitions
        while True:
            if ingest_step is not None:
                ingest_step()
            if self.db.has_marker(group, num_parts):
                return
            if time.monotonic() >= deadline:
                raise TimeoutError(
                    f"marker group {group} not materialized within {timeout_s}s"
                )
            time.sleep(poll_interval_s)

    # --- executors ----------------------------------------------------------

    def _executors(self) -> list[ExecutorSnapshot]:
        factory = self.config.resource_list_factory()
        # Operator cordon state overlays the snapshots (the reference reads
        # executor_settings separately and filters cordoned executors,
        # scheduling_algo.go:250,779-791); it is event-sourced via the
        # "$control-plane" stream, so every replica converges by replay.
        settings = self.db.executor_settings()
        out = []
        for row in self.db.executors():
            snap = ExecutorSnapshot.from_json(row["snapshot"], factory)
            s = settings.get(snap.id)
            if s is not None and s["cordoned"] and not snap.cordoned:
                snap = dataclasses.replace(snap, cordoned=True)
            out.append(snap)
        return out

    # --- the cycle (scheduler.go cycle:246) ---------------------------------

    def cycle(self, schedule: bool = True) -> CycleResult:
        from armada_tpu.core.logging import log_context

        start = time.monotonic()
        self._cycle_seq = getattr(self, "_cycle_seq", 0) + 1
        # Context fields ride every log line this cycle emits, in any
        # component (armadacontext parity, armada_context.go).
        from armada_tpu.core.watchdog import supervisor as _supervisor

        sup0 = _supervisor()
        fallbacks0 = sup0.snapshot()["fallbacks"]
        degraded0 = sup0.degraded
        from armada_tpu.ops.trace import recorder as _trace_recorder

        with log_context(cycle=self._cycle_seq, scheduling=schedule):
            # Cycle trace root (ops/trace.py): every span the cycle's
            # components open -- feed apply, assemble, slab scatters, the
            # round's kernel/fetch/failover, publish -- lands under this
            # tree; the ring keeps the last N for armadactl trace/healthz.
            with _trace_recorder().cycle(
                "scheduler_cycle",
                kind="cycle",
                seq=self._cycle_seq,
                scheduling=schedule,
            ):
                result = self._cycle(schedule)
        duration = time.monotonic() - start
        # A cycle counts as degraded if it RAN degraded at any point:
        # degraded BEFORE (a promotion can land mid-cycle while the round
        # still runs on the CPU failover), a fallback DURING (the fallback
        # delta -- a drill-speed re-probe can promote back before the
        # failed-over round even returns), or degraded AFTER.  Post-cycle
        # state alone misfiles exactly the cycles the failover window
        # exists to measure.
        sup = _supervisor()
        degraded = (
            degraded0
            or sup.degraded
            or sup.snapshot()["fallbacks"] > fallbacks0
        )
        self._observe_slo(result, duration, degraded)
        if self.metrics is not None:
            self.metrics.observe_cycle(result, duration, now=self._clock())
            from armada_tpu.core.watchdog import supervisor

            self.metrics.observe_device(supervisor().snapshot())
            from armada_tpu.models.verify import healthz_block as _verify_block

            self.metrics.observe_verify(_verify_block())
            self.metrics.observe_slo(self._slo().snapshot())
            self.metrics.observe_trace(_trace_recorder().stage_snapshot())
            self.metrics.observe_durability(self.durability_status())
            from armada_tpu.ingest.stats import registry as _ingest_stats

            self.metrics.observe_ingest(_ingest_stats().snapshot())
            from armada_tpu.ingest.dlq import registry as _dlq_registry

            self.metrics.observe_dlq(_dlq_registry().snapshot())
        if self.reports is not None and result.scheduler_result is not None:
            self.reports.record_cycle(result.scheduler_result, now=self._clock())
        return result

    @staticmethod
    def _slo():
        from armada_tpu.scheduler.slo import recorder

        return recorder()

    def _observe_slo(
        self, result: CycleResult, duration_s: float, degraded: bool = False
    ) -> None:
        """Feed the streaming SLO layer (scheduler/slo.py): cycle latency
        (scheduling cycles only; reconcile ticks are a different
        distribution), ingest->visible lag for tracked submits that became
        visible this cycle, TTFL for first leases, and forget jobs that
        terminated without ever leasing (cancel-before-lease, validation
        failure) so the tracking maps stay bounded."""
        rec = self._slo()
        if result.scheduled:
            rec.observe_cycle(duration_s, degraded=degraded)
            # Per-pool round latency (round 17): one cycle number spanning
            # all pools hides a slow tenant -- each PoolStats carries its
            # own round seconds + the per-round fallback-delta degraded
            # flag (scheduler/algo.py), recorded into per-pool histograms.
            sched_pools = getattr(result.scheduler_result, "pools", None)
            if sched_pools:
                for ps in sched_pools:
                    if ps.round_s:
                        rec.observe_pool_round(
                            ps.pool, ps.round_s, degraded=ps.degraded
                        )
        if result.synced_jobs:
            rec.note_visible(result.synced_jobs)
        sched = result.scheduler_result
        if sched is not None and sched.scheduled:
            rec.note_leased([job.id for job, _run in sched.scheduled])
        if rec.pending_lease_count() and result.published:
            ended = [
                getattr(getattr(ev, kind), "job_id", "")
                for seq in result.published
                for ev in seq.events
                for kind in (ev.WhichOneof("event"),)
                if kind in ("cancelled_job", "job_errors")
            ]
            if ended:
                rec.forget([jid for jid in ended if jid])

    def _cycle(self, schedule: bool = True) -> CycleResult:
        from armada_tpu.ops.trace import recorder as _trace

        trace = _trace()
        result = CycleResult()
        # Fetch cursors only advance with a COMMITTED txn: an aborted cycle
        # must re-fetch the same rows next time or their transitions are lost.
        cursors0 = (self._jobs_serial, self._runs_serial)
        txn = self.jobdb.write_txn()
        try:
            with trace.span("sync_state"):
                touched = self.sync_state(txn)
            result.synced_jobs = touched

            token: LeaderToken = self.leader.get_token()
            result.leader = token.leader
            if not token.leader:
                self._was_leader = False
                txn.commit()
                return result
            # Epoch fence: the publisher rejects publishes stamped with an
            # older generation than the election record's current one, so a
            # deposed leader's in-flight cycle cannot append after a
            # successor was elected -- even between our validate_token and
            # the actual append (eventlog/publisher.py set_epoch).
            set_epoch = getattr(self.publisher, "set_epoch", None)
            if set_epoch is not None:
                set_epoch(token.generation)
            if not self._was_leader:
                # Crash drill: die mid-promotion (after winning the
                # election, before the recovery fence completes).  The
                # cycle's except rewinds cursors and aborts the txn;
                # _was_leader stays False, so the next cycle re-runs the
                # whole promotion -- promotion must be idempotent.
                from armada_tpu.core import faults

                faults.check("leader_promote")
                # Leadership acquired (first cycle or follower -> leader):
                # replay everything already published -- possibly by the
                # previous leader -- before taking decisions
                # (scheduler.go:169-181, ensureDbUpToDate:1120), and treat
                # EVERY job as touched so transitions ingested while we were
                # not leader still generate their update messages (the
                # reference's updateAll on leadership change).
                self.ensure_db_up_to_date(ingest_step=self.ingest_step)
                self.sync_state(txn)
                touched = sorted({j.id for j in txn.all_jobs()})
                result.synced_jobs = touched
            self._was_leader = True

            builder = _SequenceBuilder()
            now_ns = self.now_ns()

            # Refresh the submit checker's fleet BEFORE the update messages:
            # the requeue anti-affinity gate (_fail_or_requeue) consults it.
            with trace.span("transitions", touched=len(touched)):
                self._refresh_checker_fleet(now_ns)
                self._generate_update_messages(txn, touched, builder, now_ns)
                self._validate_jobs(txn, builder, now_ns)
                self._expire_executor_jobs(txn, builder, now_ns)

            if schedule:
                quarantined = self.node_quarantine.quarantined(now_ns)
                executors = self._executors()
                if self.metrics is not None:
                    self.metrics.quarantined_nodes.set(len(quarantined))
                    self.metrics.observe_executor_usage(
                        executors, self.config.resource_list_factory()
                    )
                with trace.span("schedule"):
                    sched = self.algo.schedule(
                        txn,
                        executors,
                        now_ns,
                        quarantined_nodes=quarantined,
                    )
                result.scheduler_result = sched
                result.scheduled = True
                self._events_from_scheduler_result(sched, builder, now_ns)
                if self.config.publish_metric_events:
                    self._metric_events(sched, builder, now_ns)

            sequences = builder.build()
            if sequences:
                # Fencing: never publish with stale authority (scheduler.go:355).
                if not self.leader.validate_token(token):
                    txn.abort()
                    self._jobs_serial, self._runs_serial = cursors0
                    # Leadership lost: the next acquisition must re-fence.
                    self._was_leader = False
                    result.leader = False
                    return result
                with trace.span(
                    "event_publish",
                    sequences=len(sequences),
                    events=sum(len(s.events) for s in sequences),
                ):
                    self.publisher.publish(sequences)
            result.published = sequences

            if self.config.enable_assertions:
                txn.assert_invariants()
            with trace.span("commit"):
                txn.commit()
            feed = getattr(self.algo, "feed", None)
            if (
                schedule
                and feed is not None
                and pipeline_enabled()
                and prefetch_worthwhile()
            ):
                # Shadow-pipeline stage (b): the commit's subscriber fire
                # just applied this cycle's decisions to the builders; start
                # their slab upload NOW so the transfer overlaps the
                # inter-cycle idle and the next cycle's sync instead of
                # serializing inside the next device apply.  Best-effort:
                # the txn is COMMITTED -- a device error here must not
                # reach the except below, whose cursor rewind assumes the
                # cycle did not commit (the rows ride the next bundle).
                try:
                    feed.prefetch_content()
                except Exception:
                    _log.warning("content prefetch failed", exc_info=True)
            return result
        except BaseException:
            txn.abort()
            self._jobs_serial, self._runs_serial = cursors0
            raise

    # --- job state transitions (scheduler.go generateUpdateMessages:698) ----

    def _generate_update_messages(
        self,
        txn: WriteTxn,
        touched: Iterable[str],
        builder: _SequenceBuilder,
        now_ns: int,
    ) -> None:
        for job_id in touched:
            job = txn.get(job_id)
            if job is None or job.in_terminal_state():
                continue

            # Cancellation requested (by job or jobset).
            if job.cancel_requested or job.cancel_by_jobset_requested:
                run = job.latest_run
                if run is not None and not run.in_terminal_state():
                    builder.add(
                        job.queue,
                        job.jobset,
                        pb.Event(
                            created_ns=now_ns,
                            job_run_cancelled=pb.JobRunCancelled(
                                job_id=job.id, run_id=run.id
                            ),
                        ),
                    )
                    job = job.with_updated_run(run.with_cancelled())
                builder.add(
                    job.queue,
                    job.jobset,
                    pb.Event(
                        created_ns=now_ns,
                        cancelled_job=pb.CancelledJob(job_id=job.id),
                    ),
                )
                txn.upsert(job.with_cancelled())
                continue

            run = job.latest_run

            # Operator-requested preemption (persisted on the job row so a
            # request that arrives before the lease materializes still acts).
            if job.preempt_requested:
                if run is None or run.in_terminal_state():
                    if job.queued or run is None:
                        # Preempted before it ever started: cancel it.
                        builder.add(
                            job.queue,
                            job.jobset,
                            pb.Event(
                                created_ns=now_ns,
                                cancelled_job=pb.CancelledJob(
                                    job_id=job.id, reason=PREEMPTED_REASON
                                ),
                            ),
                        )
                        txn.upsert(job.with_cancelled())
                        continue
                elif not run.preempt_requested:
                    # Ask the executor to stop the run; its report closes the loop.
                    builder.add(
                        job.queue,
                        job.jobset,
                        pb.Event(
                            created_ns=now_ns,
                            job_run_preemption_requested=pb.JobRunPreemptionRequested(
                                job_id=job.id, run_id=run.id, reason=PREEMPTED_REASON
                            ),
                        ),
                    )
                    job = job.with_updated_run(run.with_preempt_requested())
                    txn.upsert(job)
                    run = job.latest_run

            if run is None:
                continue

            if run.succeeded and not job.succeeded:
                builder.add(
                    job.queue,
                    job.jobset,
                    pb.Event(
                        created_ns=now_ns,
                        job_succeeded=pb.JobSucceeded(job_id=job.id),
                    ),
                )
                txn.upsert(job.with_succeeded())
            elif run.preempted:
                # Executor-confirmed preemption terminates the job
                # (scheduler.go: preempted runs fail their job).
                builder.add(
                    job.queue,
                    job.jobset,
                    pb.Event(
                        created_ns=now_ns,
                        job_errors=pb.JobErrors(
                            job_id=job.id,
                            errors=[
                                pb.Error(
                                    reason=PREEMPTED_REASON,
                                    message=f"run {run.id} preempted",
                                    terminal=True,
                                    node=run.node_name,
                                )
                            ],
                        ),
                    ),
                )
                txn.upsert(job.with_failed())
            elif run.failed and not run.returned:
                self.node_quarantine.record_failure(run.node_id, now_ns)
                # A failed run means a terminal error was reported
                # (instructions.go handleJobRunErrors): the job fails with it.
                builder.add(
                    job.queue,
                    job.jobset,
                    pb.Event(
                        created_ns=now_ns,
                        job_errors=pb.JobErrors(
                            job_id=job.id,
                            errors=[
                                pb.Error(
                                    reason="runFailed",
                                    message=f"run {run.id} failed on {run.node_name}",
                                    terminal=True,
                                    node=run.node_name,
                                )
                            ],
                        ),
                    ),
                )
                txn.upsert(job.with_failed())
            elif run.returned and not job.queued:
                # Returned leases count whether or not the pod started: a
                # stuck-PENDING return (podStuckPending) is the clearest
                # broken-node signal and never sets run_attempted.
                self.node_quarantine.record_failure(run.node_id, now_ns)
                self._fail_or_requeue(
                    txn,
                    job,
                    builder,
                    now_ns,
                    reason="runReturned",
                    message=f"run {run.id} returned by {run.executor}",
                )

    def _fail_or_requeue(
        self,
        txn: WriteTxn,
        job: Job,
        builder: _SequenceBuilder,
        now_ns: int,
        reason: str,
        message: str,
    ) -> None:
        """Requeue up to max_retries attempted runs, else fail terminally
        (scheduler.go:473-568 retry logic)."""
        requeue = job.num_attempts() <= self.config.max_retries and not (
            job.cancel_requested or job.cancel_by_jobset_requested
        )
        bans = job.anti_affinity_nodes() if requeue else ()
        if bans and self.submit_checker.have_executors:
            # A retry must avoid every node where an attempt died; if that
            # leaves nowhere it can run, fail it now instead of requeueing it
            # to starve forever (scheduler.go:826-840
            # addNodeAntiAffinitiesForAttemptedRunsIfSchedulable).  Validated
            # pools override the requested ones, exactly as the algo offers
            # them (algo.py): the gate must judge the pools the job will
            # actually be scheduled into.
            spec = dataclasses.replace(
                job.spec,
                priority=job.priority,
                pools=job.pools or job.spec.pools,
            )
            if not self.submit_checker.check_gang([spec], banned_nodes=bans).ok:
                requeue = False
                message = (
                    f"job was attempted {job.num_attempts()} times and has been "
                    "tried once on all nodes it can run on - "
                    "this job will no longer be retried"
                ) + f" ({message})"
        if requeue:
            builder.add(
                job.queue,
                job.jobset,
                pb.Event(
                    created_ns=now_ns,
                    job_requeued=pb.JobRequeued(
                        job_id=job.id,
                        update_sequence_number=job.queued_version + 1,
                    ),
                ),
            )
            txn.upsert(job.with_queued(True))
        else:
            builder.add(
                job.queue,
                job.jobset,
                pb.Event(
                    created_ns=now_ns,
                    job_errors=pb.JobErrors(
                        job_id=job.id,
                        errors=[
                            pb.Error(
                                reason=MAX_RETRIES_EXCEEDED,
                                message=message,
                                terminal=True,
                            )
                        ],
                    ),
                ),
            )
            txn.upsert(job.with_failed())

    # --- metric events (pkg/metricevents; cycle_metrics.go:637-671) ---------

    METRICS_QUEUE = "armada-metrics"
    METRICS_JOBSET = "cycle-metrics"

    def _metric_events(
        self, sched, builder: "_SequenceBuilder", now_ns: int
    ) -> None:
        """One CycleMetrics event per pool onto the log under the reserved
        ("armada-metrics", "cycle-metrics") stream: the reference's
        metric-events topic, watchable via the ordinary Event API.  The
        published totals are the round's OWN fairness denominator (node +
        floating capacity, RoundOutcome.pool_totals) -- every share in the
        event is a fraction of exactly these numbers."""
        for stats in sched.pools:
            alloc = pb.Resources(milli=dict(stats.outcome.pool_totals))
            qm = [
                pb.QueueCycleMetrics(
                    queue=qname,
                    actual_share=qs.get("actual_share", 0.0),
                    demand=qs.get("demand_share_raw", 0.0),
                    constrained_demand=qs.get("demand_share", 0.0),
                    fair_share=qs.get("fair_share", 0.0),
                    adjusted_fair_share=qs.get("adjusted_fair_share", 0.0),
                    short_job_penalty=qs.get("short_job_penalty", 0.0),
                )
                for qname, qs in stats.outcome.queue_stats.items()
            ]
            builder.add(
                self.METRICS_QUEUE,
                self.METRICS_JOBSET,
                pb.Event(
                    created_ns=now_ns,
                    cycle_metrics=pb.CycleMetrics(
                        pool=stats.pool,
                        queue_metrics=qm,
                        allocatable_resources=alloc,
                        spot_price=stats.outcome.spot_price or 0.0,
                        cycle_time_ns=now_ns,
                    ),
                ),
            )

    # --- validation (scheduler.go submitCheck:1011, submitcheck.go Check:181)

    def _refresh_checker_fleet(self, now_ns: int) -> None:
        """Update the SubmitChecker's fleet snapshot for this cycle.  Same
        staleness filter as the scheduling algo: a dead executor's snapshot
        must not vouch for (or block) a job's schedulability."""
        timeout_ns = int(self.config.executor_timeout_s * 1e9)
        live = [
            ex
            for ex in self._executors()
            if now_ns - ex.last_update_ns <= timeout_ns
        ]
        self.submit_checker.update_executors(live)

    def _validate_jobs(
        self, txn: WriteTxn, builder: _SequenceBuilder, now_ns: int
    ) -> None:
        unvalidated = txn.unvalidated_jobs()
        if not unvalidated:
            return
        if not self.submit_checker.have_executors:
            # No fleet yet: defer -- nothing can be judged unschedulable
            # against zero executors, and nothing can lease anyway.
            return

        # Gangs validate atomically (one check per gang, like the reference
        # checking whole gangs against mini NodeDbs).
        gangs: dict = {}
        for job in unvalidated:
            key = (job.queue, job.spec.gang_id) if job.spec.gang_id else (job.id, "")
            gangs.setdefault(key, []).append(job)

        for members in gangs.values():
            specs = [
                dataclasses.replace(j.spec, priority=j.priority) for j in members
            ]
            result = self.submit_checker.check_gang(specs)
            if result.ok:
                for job in members:
                    builder.add(
                        job.queue,
                        job.jobset,
                        pb.Event(
                            created_ns=now_ns,
                            job_validated=pb.JobValidated(
                                job_id=job.id, pools=result.pools
                            ),
                        ),
                    )
                    txn.upsert(job.with_validated(result.pools))
            else:
                for job in members:
                    builder.add(
                        job.queue,
                        job.jobset,
                        pb.Event(
                            created_ns=now_ns,
                            job_errors=pb.JobErrors(
                                job_id=job.id,
                                errors=[
                                    pb.Error(
                                        reason="unschedulable",
                                        message=result.reason,
                                        terminal=True,
                                    )
                                ],
                            ),
                        ),
                    )
                    txn.upsert(job.with_failed())

    # --- executor expiry (scheduler.go expireJobsIfNecessary:929) -----------

    def _expire_executor_jobs(
        self, txn: WriteTxn, builder: _SequenceBuilder, now_ns: int
    ) -> None:
        timeout_ns = int(self.config.executor_timeout_s * 1e9)
        stale = {
            ex.id
            for ex in self._executors()
            if now_ns - ex.last_update_ns > timeout_ns
        }
        if not stale:
            return
        for job in txn.all_jobs():
            run = job.latest_run
            if (
                job.in_terminal_state()
                or run is None
                or run.in_terminal_state()
                or run.executor not in stale
            ):
                continue
            builder.add(
                job.queue,
                job.jobset,
                pb.Event(
                    created_ns=now_ns,
                    job_run_errors=pb.JobRunErrors(
                        job_id=job.id,
                        run_id=run.id,
                        errors=[
                            pb.Error(
                                reason=LEASE_EXPIRED,
                                message=f"executor {run.executor} stopped heartbeating",
                                terminal=False,
                                lease_returned=True,
                            )
                        ],
                    ),
                ),
            )
            job = job.with_updated_run(run.with_returned(run_attempted=run.running))
            txn.upsert(job)
            self._fail_or_requeue(
                txn,
                job,
                builder,
                now_ns,
                reason=LEASE_EXPIRED,
                message=f"executor {run.executor} lost",
            )

    # --- decision events (scheduler.go eventsFromSchedulerResult:570) -------

    def _events_from_scheduler_result(
        self, sched: SchedulerResult, builder: _SequenceBuilder, now_ns: int
    ) -> None:
        for job, run in sched.scheduled:
            builder.add(
                job.queue,
                job.jobset,
                pb.Event(
                    created_ns=now_ns,
                    job_run_leased=pb.JobRunLeased(
                        job_id=job.id,
                        run_id=run.id,
                        executor_id=run.executor,
                        node_id=run.node_id,
                        pool=run.pool,
                        scheduled_at_priority=run.scheduled_at_priority or 0,
                        pool_scheduled_away=run.pool_scheduled_away,
                        update_sequence_number=job.queued_version,
                    ),
                ),
            )
        for job, run in sched.preempted:
            builder.add(
                job.queue,
                job.jobset,
                pb.Event(
                    created_ns=now_ns,
                    job_run_preempted=pb.JobRunPreempted(
                        job_id=job.id, run_id=run.id, reason=PREEMPTED_REASON
                    ),
                ),
            )
            builder.add(
                job.queue,
                job.jobset,
                pb.Event(
                    created_ns=now_ns,
                    job_errors=pb.JobErrors(
                        job_id=job.id,
                        errors=[
                            pb.Error(
                                reason=PREEMPTED_REASON,
                                message=f"run {run.id} preempted by the scheduler",
                                terminal=True,
                            )
                        ],
                    ),
                ),
            )

    # --- durable checkpoints (scheduler/checkpoint.py) ----------------------

    def checkpoint(self) -> dict:
        """Snapshot the materialized plane NOW; returns the written
        checkpoint's identity.  Safe from any thread (the export runs under
        the store lock, on an ingestion batch boundary): the armadactl
        trigger calls this from an RPC worker while the loop runs."""
        if self.checkpointer is None:
            raise RuntimeError("no checkpoint directory configured")
        from armada_tpu.scheduler.checkpoint import snapshot_plane

        epoch = 0
        gen = getattr(self.leader, "current_generation", None)
        if gen is not None:
            try:
                epoch = gen()
            except Exception:  # noqa: BLE001 - a flaky peek must not block snapshots
                epoch = 0
        payload = snapshot_plane(
            self.db, scheduler=self, epoch=epoch, clock=self._clock
        )
        path = self.checkpointer.write(payload)
        self._last_checkpoint_mono = time.monotonic()
        self.last_checkpoint = {
            "path": path,
            "created_ns": payload["created_ns"],
            "fence": payload["fence"],
            "epoch": epoch,
        }
        _log.info(
            "checkpoint written: %s (fence total %d, epoch %d)",
            path,
            sum(payload["fence"].values()),
            epoch,
        )
        return self.last_checkpoint

    def _maybe_checkpoint(self, leader: bool) -> None:
        """Interval-triggered checkpoint from the run loop.  Leader-only:
        follower stores trail replication anyway, and two replicas
        snapshotting shared storage would race.  Failures are logged and
        retried next interval -- a broken disk must not take the loop down
        with the next cycle's work."""
        if (
            self.checkpointer is None
            or self.checkpoint_interval_s <= 0
            or not leader
        ):
            return
        if (
            time.monotonic() - self._last_checkpoint_mono
            < self.checkpoint_interval_s
        ):
            return
        try:
            self.checkpoint()
        except Exception:  # noqa: BLE001 - keep cycling; next interval retries
            # Mark the attempt so a persistently failing disk retries at the
            # interval cadence, not every cycle.
            self._last_checkpoint_mono = time.monotonic()
            _log.exception("periodic checkpoint failed")

    def durability_status(self) -> dict:
        """The /healthz durability block + prometheus gauge source: newest
        snapshot age/fence/epoch plus this process's current election epoch.
        Cheap (sidecar metadata only)."""
        out: dict = {"epoch": 0}
        gen = getattr(self.leader, "current_generation", None)
        if gen is not None:
            try:
                out["epoch"] = gen()
            except Exception:  # noqa: BLE001 - peek failure is not unhealth
                pass
        if self.checkpointer is not None:
            out["checkpoint"] = self.checkpointer.status(clock=self._clock)
        if self.last_checkpoint is not None:
            out["last_checkpoint"] = self.last_checkpoint
        if self.replication_status is not None:
            try:
                out["replication"] = self.replication_status()
            except Exception as exc:  # noqa: BLE001 - observability only
                out["replication"] = {"error": str(exc)}
        return out

    # --- service loop (scheduler.go Run:142) --------------------------------

    def run(
        self,
        stop,
        cycle_interval_s: float = 1.0,
        schedule_interval_s: float = 10.0,
    ) -> None:
        """Tick cycles until `stop` (a threading.Event) is set: a full
        scheduling round every schedule_interval, cheap reconcile cycles in
        between (cyclePeriod/schedulePeriod, config/scheduler/config.yaml:1-3).

        A failed cycle must not kill the loop: the cycle already aborted its
        txn and rewound its fetch cursors (no partial commit), so the next
        attempt re-derives everything -- a transient publish/DB failure
        costs retries with bounded jittered backoff, not the service (the
        reference's Run keeps cycling on cycle errors, scheduler.go:142).
        KeyboardInterrupt/SystemExit still propagate."""
        from armada_tpu.core.backoff import Backoff

        backoff = Backoff(base_s=max(cycle_interval_s, 0.05), cap_s=30.0)
        last_schedule = 0.0
        while not stop.is_set():
            start = self._clock()
            do_schedule = start - last_schedule >= schedule_interval_s
            try:
                result = self.cycle(schedule=do_schedule)
            except Exception:  # noqa: BLE001 - the loop must survive
                delay = backoff.next_delay()
                _log.exception(
                    "scheduler cycle failed (attempt %d); retrying in %.2fs",
                    backoff.attempts,
                    delay,
                )
                # last_schedule stays: a failed scheduling cycle retries
                # scheduling at the next tick, not a schedule_interval later.
                stop.wait(delay)
                continue
            backoff.reset()
            self._maybe_checkpoint(result.leader)
            if do_schedule:
                last_schedule = start
            elapsed = self._clock() - start
            stop.wait(max(0.0, cycle_interval_s - elapsed))
