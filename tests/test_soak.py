"""Soak subsystem smoke: downscaled sustained traffic through the full
in-process serving stack, with and without a mid-soak device-loss fault.

The chaos leg is THE acceptance gate of the soak subsystem (fast tier):
an injected ``device_round:hang`` mid-window must degrade latency (the
failover window lands in the degraded histogram) without an SLO gap
(every schedule cycle recorded), without TSAN violations, and without
dropping or double-leasing any job.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

import pytest

from armada_tpu.loadgen.soak import SoakConfig, run_soak

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_soak_chaos_smoke_device_hang_mid_window(tmp_path):
    cfg = SoakConfig(
        window_s=10.0,
        target_eps=40.0,
        num_nodes=4,
        num_queues=2,
        drain_s=2.5,
        cycle_interval_s=0.2,
        schedule_interval_s=0.5,
        fault="device_round:hang",
        fault_at_frac=0.5,
        watchdog_s=3.0,
        seed=11,
    )
    report = run_soak(cfg, str(tmp_path))
    assert report["ok"], report
    # the fault fired and the plane failed over + re-promoted under load
    assert report["device_state"]["fallbacks"] >= 1
    assert report["promoted"] is True
    # degradation is a latency DISTRIBUTION: the failed-over cycle(s) land
    # in the degraded histogram, at >= the armed deadline
    assert report["degraded_cycles"] >= 1
    assert report["slo_degraded"]["min_s"] >= cfg.watchdog_s
    # no SLO gap: every schedule cycle is in exactly one of the histograms
    total = (
        report["slo"]["cycle_latency_s"]["count"]
        + report["slo"]["cycle_latency_degraded_s"]["count"]
    )
    assert total == report["schedule_cycles"]
    # invariants under chaos: nothing dropped, nothing double-leased, no
    # races recorded by the armed tsan harness
    assert report["violations"] == 0
    assert report["tsan_violations"] == 0
    # and the load was real: jobs flowed end-to-end during the window
    assert report["jobs"]["leased"] > 0
    assert report["slo"]["time_to_first_lease_s"]["count"] > 0
    assert report["slo"]["ingest_visible_lag_s"]["count"] > 0
    assert report["achieved_eps"] > 0


def test_soak_clean_window_report_contract(tmp_path):
    report = run_soak(
        SoakConfig(
            window_s=6.0,
            target_eps=30.0,
            num_nodes=4,
            num_queues=2,
            drain_s=2.0,
            cycle_interval_s=0.2,
            schedule_interval_s=0.5,
            seed=3,
        ),
        str(tmp_path),
    )
    assert report["ok"], report
    assert report["violations"] == 0
    # headline keys the bench line and runbook read
    for key in (
        "window_s",
        "achieved_eps",
        "cycle_p50_s",
        "cycle_p99_s",
        "ttfl_p50_s",
        "ttfl_p99_s",
        "ingest_lag_p99_s",
        "schedule_cycles",
    ):
        assert key in report, key
    # the mix really exercised cancel/reprioritise alongside submits
    assert report["events"]["cancel"] > 0
    assert report["events"]["reprioritize"] > 0
    assert report["events"]["gang_jobs"] > 0
    # no fault configured -> no degraded samples, no fault keys
    assert report["slo"]["cycle_latency_degraded_s"]["count"] == 0
    assert "fault" not in report
    # the JSON line is valid JSON end to end
    assert json.loads(json.dumps(report, default=float))["ok"] is True


@pytest.mark.slow
def test_mid_soak_kill_restart_leg(tmp_path):
    """The crash-under-load drill (ISSUE 7): mid-window checkpoint -> fire
    the committed-but-unacked ingest crash window -> abandon the serving
    world without drain -> wipe the materialized store -> rebuild from the
    snapshot + log-suffix replay.  RTO lands in restart_recovery_s;
    LifecycleTracker pins zero dropped/double-leased jobs ACROSS the
    restart; the armed tsan harness records zero races."""
    cfg = SoakConfig(
        window_s=12.0,
        target_eps=50.0,
        num_nodes=4,
        num_queues=2,
        drain_s=4.0,
        cycle_interval_s=0.2,
        schedule_interval_s=0.5,
        crash_at_frac=0.5,
        seed=13,
    )
    report = run_soak(cfg, str(tmp_path))
    assert report["ok"], report
    crash = report["crash"]
    assert crash["restored_from_checkpoint"]
    assert crash["rto_s"] is not None and crash["rto_s"] > 0
    # bounded replay: only the post-fence suffix replayed after the wipe
    assert crash["replayed_sequences"] > 0
    # the RTO rode the SLO layer as a distribution
    assert report["slo"]["restart_recovery_s"]["count"] == 1
    assert "restart_p50_s" in report
    # invariants across the restart: nothing dropped, nothing
    # double-leased, no SLO gap (tracked jobs resolve), no races
    assert report["violations"] == 0
    assert report["tsan_violations"] == 0
    assert report["jobs"]["leased"] > 0


def test_tools_soak_prints_exactly_one_json_line():
    env = dict(os.environ)
    env.update(
        JAX_PLATFORMS="cpu",
        ARMADA_SOAK_WINDOW_S="6",
        ARMADA_SOAK_RATE="30",
        ARMADA_SOAK_NODES="4",
        ARMADA_SOAK_QUEUES="2",
    )
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "soak.py"), "--json", "--seed", "5"],
        capture_output=True,
        text=True,
        cwd=REPO,
        timeout=420,
        env=env,
    )
    lines = [l for l in out.stdout.splitlines() if l.strip()]
    assert len(lines) == 1, out.stdout + out.stderr
    report = json.loads(lines[0])
    assert out.returncode == (0 if report["ok"] else 1), out.stderr
    assert report["tool"] == "soak"
    assert report["platform"] == "cpu"


def test_armadactl_soak_parser_wiring():
    from armada_tpu.cli.armadactl import cmd_soak, build_parser

    args = build_parser().parse_args(
        ["soak", "--window", "5", "--rate", "10", "--fault", "device_round:error"]
    )
    assert args.fn is cmd_soak
    assert args.window == 5.0 and args.rate == 10.0
    assert args.fault == "device_round:error"
    assert args.fault_at == 0.5 and args.watchdog_s == 5.0
    # kill/restart leg wiring: bare --crash means the 0.5 default fraction
    args = build_parser().parse_args(["soak", "--crash"])
    assert args.crash == 0.5
    assert build_parser().parse_args(["soak"]).crash is None
    # heterogeneous-fleet leg: the flag overrides ARMADA_SOAK_NODE_TYPES;
    # absent (None) means from_env's default survives
    args = build_parser().parse_args(["soak", "--node-types", "v4, v5e"])
    assert args.node_types == "v4, v5e"
    assert build_parser().parse_args(["soak"]).node_types is None
