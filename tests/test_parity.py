"""Placement parity: the TPU round kernel vs an independent sequential oracle.

BASELINE.json's gate is placement parity with the reference's greedy
semantics (docs/scheduling_and_preempting_jobs.md:144-249: one gang at a
time, cheapest-queue first, best-fit node).  This oracle re-implements those
semantics directly in plain Python -- no shared code with the kernel beyond
the input types -- and the property tests assert the kernel lands in the same
equivalence class on randomized problems: identical scheduled-job sets where
ordering is deterministic, identical per-queue counts and total allocations
where only node-choice ties differ (SURVEY.md section 7 "Hard parts").
"""

import numpy as np
import pytest

from armada_tpu.core.config import SchedulingConfig
from armada_tpu.core.types import JobSpec, NodeSpec, Queue
from armada_tpu.models import run_scheduling_round

CFG = SchedulingConfig(shape_bucket=32)
F = CFG.resource_list_factory()


# --- the oracle: sequential greedy, written independently --------------------


def oracle_round(config, nodes, queues, jobs):
    """Schedule singleton jobs one at a time:
    - queue order: minimal proposed DRF cost (max over resources of
      (alloc+req)/total, divided by weight); ties -> queue name order.
    - within a queue: jobs in (pc priority desc, priority asc, submit, id).
    - node: best-fit = fullest node that fits (min free capacity sum, scaled);
      ties -> node order.
    - stop when burst reached or nothing fits (a queue whose head fails is
      done -- identical-shape retirement).
    """
    total = {}
    free = {}
    for n in nodes:
        free[n.id] = np.array(n.total_resources.atoms, dtype=float)
    total_pool = sum(free.values()) if free else np.zeros(F.num_resources)
    scale = np.maximum.reduce([free[n.id] for n in nodes]) if nodes else None

    per_queue = {q.name: [] for q in queues}
    for j in jobs:
        pc = config.priority_class(j.priority_class)
        per_queue[j.queue].append((( -pc.priority, j.priority, j.submit_time, j.id), j))
    for q in per_queue:
        per_queue[q].sort(key=lambda t: t[0])
    heads = {q: 0 for q in per_queue}
    alloc = {q.name: np.zeros(F.num_resources) for q in queues}
    weight = {q.name: q.weight for q in queues}
    drf = np.array(
        [1.0 if name in config.dominant_resource_fairness_resources else 0.0 for name in F.names]
    )

    def cost(qname, extra):
        with np.errstate(divide="ignore", invalid="ignore"):
            frac = np.where(total_pool > 0, (alloc[qname] + extra) / np.maximum(total_pool, 1e-9), 0.0)
        return float((frac * drf).max()) / weight[qname]

    scheduled = {}
    burst = config.maximum_scheduling_burst
    dead = set()  # resource shapes retired as unfeasible (scheduling keys
    # exclude the queue, so retirement is round-global, gang_scheduler.go:85-96)
    # per-round resource cap (maximumResourceFractionToSchedule): exceeding it
    # TERMINATES the round (CheckRoundConstraints semantics)
    round_cap = np.full(F.num_resources, np.inf)
    for name, fracv in config.maximum_resource_fraction_to_schedule.items():
        round_cap[F.index_of(name)] = fracv * total_pool[F.index_of(name)]
    sched_res = np.zeros(F.num_resources)
    while len(scheduled) < burst:
        candidates = []
        for qname in sorted(per_queue):
            # skip heads whose shape was retired (unfeasible-key skip)
            while heads[qname] < len(per_queue[qname]):
                job = per_queue[qname][heads[qname]][1]
                if tuple(job.resources.atoms) in dead:
                    heads[qname] += 1
                else:
                    break
            if heads[qname] >= len(per_queue[qname]):
                continue
            job = per_queue[qname][heads[qname]][1]
            req = np.array(job.resources.atoms, dtype=float)
            candidates.append((cost(qname, req), qname, job, req))
        if not candidates:
            break
        candidates.sort(key=lambda c: (c[0], c[1]))
        _, qname, job, req = candidates[0]
        if np.any(sched_res + req > round_cap):
            break  # round over (global constraint)
        # best-fit node
        inv_scale = np.divide(
            1.0, scale, out=np.zeros_like(scale), where=scale > 0
        )
        best = None
        for n in nodes:
            f = free[n.id]
            if np.all(f >= req):
                score = float((f * inv_scale).sum())
                if best is None or score < best[0]:
                    best = (score, n.id)
        if best is None:
            # shape-level retirement: identical jobs are skipped round-wide
            dead.add(tuple(job.resources.atoms))
            continue
        free[best[1]] -= req
        alloc[qname] += req
        sched_res += req
        scheduled[job.id] = best[1]
        heads[qname] += 1
    return scheduled


def random_problem(rng, num_nodes, num_jobs, num_queues, distinct_shapes=True):
    nodes = [
        NodeSpec(
            id=f"n{i:03d}",
            pool="default",
            total_resources=F.from_mapping(
                {"cpu": int(rng.choice([8, 16, 32])), "memory": int(rng.choice([32, 64]))}
            ),
        )
        for i in range(num_nodes)
    ]
    queues = [Queue(f"q{i}", float(rng.choice([1.0, 2.0, 3.0]))) for i in range(num_queues)]
    jobs = []
    for i in range(num_jobs):
        if distinct_shapes:
            cpu = int(rng.choice([1, 2, 4, 8]))
            mem = int(rng.choice([1, 2, 4]))
        else:
            cpu, mem = 2, 2
        jobs.append(
            JobSpec(
                id=f"j{i:04d}",
                queue=f"q{int(rng.integers(num_queues))}",
                submit_time=float(i),
                resources=F.from_mapping({"cpu": cpu, "memory": mem}),
            )
        )
    return nodes, queues, jobs


@pytest.mark.parametrize("seed", [1, 7, 13, 42, 99])
def test_kernel_matches_oracle_scheduled_set(seed):
    rng = np.random.default_rng(seed)
    nodes, queues, jobs = random_problem(rng, num_nodes=12, num_jobs=80, num_queues=4)
    expected = oracle_round(CFG, nodes, queues, jobs)
    outcome = run_scheduling_round(
        CFG, pool="default", nodes=nodes, queues=queues, queued_jobs=jobs
    )
    assert set(outcome.scheduled) == set(expected), (
        f"seed {seed}: kernel∖oracle={set(outcome.scheduled) - set(expected)}, "
        f"oracle∖kernel={set(expected) - set(outcome.scheduled)}"
    )


@pytest.mark.parametrize("seed", [3, 21])
def test_kernel_matches_oracle_under_saturation(seed):
    """Demand far exceeds capacity: the exact fair split must match."""
    rng = np.random.default_rng(seed)
    nodes, queues, jobs = random_problem(rng, num_nodes=4, num_jobs=120, num_queues=3)
    expected = oracle_round(CFG, nodes, queues, jobs)
    outcome = run_scheduling_round(
        CFG, pool="default", nodes=nodes, queues=queues, queued_jobs=jobs
    )
    assert set(outcome.scheduled) == set(expected)
    # per-queue counts identical (fair-share parity)
    def by_queue(sched):
        out = {}
        jq = {j.id: j.queue for j in jobs}
        for jid in sched:
            out[jq[jid]] = out.get(jq[jid], 0) + 1
        return out

    assert by_queue(outcome.scheduled) == by_queue(expected)


def test_placements_identical_when_ties_absent():
    """With unique node shapes (no score ties) even the node CHOICES match."""
    rng = np.random.default_rng(5)
    nodes = [
        NodeSpec(
            id=f"n{i}",
            pool="default",
            total_resources=F.from_mapping({"cpu": 8 + 2 * i, "memory": 32 + 4 * i}),
        )
        for i in range(6)
    ]
    queues = [Queue("a"), Queue("b", 2.0)]
    jobs = [
        JobSpec(
            id=f"j{i:02d}",
            queue=("a", "b")[i % 2],
            submit_time=float(i),
            resources=F.from_mapping({"cpu": int(rng.choice([2, 3, 4])), "memory": 4}),
        )
        for i in range(20)
    ]
    expected = oracle_round(CFG, nodes, queues, jobs)
    outcome = run_scheduling_round(
        CFG, pool="default", nodes=nodes, queues=queues, queued_jobs=jobs
    )
    assert outcome.scheduled == expected  # same jobs AND same nodes
