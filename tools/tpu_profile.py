"""Ad-hoc TPU-cycle profiler: where do the seconds go over the axon tunnel?

Measures (1) raw host->device and device->host bandwidth, (2) per-field
upload cost of the 1M-gang SchedulingProblem, (3) kernel time cached vs
uncached (cache_slots A/B -- the fit caches were tuned for XLA:CPU's scalar
argmin; TPU has a real vector unit), (4) decode readback cost.

Usage: python tools/tpu_profile.py [jobs] [nodes]
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np


def bw_probe():
    for mb in (8, 64):
        x = np.ones((mb * 1024 * 1024 // 4,), np.float32)
        t0 = time.perf_counter()
        d = jax.device_put(x)
        d.block_until_ready()
        up = time.perf_counter() - t0
        t0 = time.perf_counter()
        _ = np.asarray(d)
        down = time.perf_counter() - t0
        print(f"bw {mb}MB: up {up:.3f}s ({mb/up:.1f} MB/s)  down {down:.3f}s ({mb/down:.1f} MB/s)")


def main():
    num_jobs = int(sys.argv[1]) if len(sys.argv) > 1 else 1_000_000
    num_nodes = int(sys.argv[2]) if len(sys.argv) > 2 else 50_000
    print("platform:", jax.devices()[0].platform)
    bw_probe()

    from armada_tpu.models.fair_scheduler import schedule_round
    from armada_tpu.models.problem import SchedulingProblem
    from armada_tpu.models.synthetic import synthetic_problem

    problem, meta = synthetic_problem(
        num_nodes=num_nodes,
        num_gangs=num_jobs,
        num_queues=64,
        num_runs=num_nodes // 2,
        global_burst=1_000,
        perq_burst=1_000,
        seed=7,
    )
    total_bytes = 0
    t_all = time.perf_counter()
    devs = []
    for name, arr in zip(problem._fields, problem):
        a = np.asarray(arr)
        t0 = time.perf_counter()
        d = jax.device_put(a)
        d.block_until_ready()
        dt = time.perf_counter() - t0
        total_bytes += a.nbytes
        if a.nbytes > 1 << 20 or dt > 0.05:
            print(f"  upload {name:16s} {a.nbytes/1e6:8.1f}MB {dt:6.3f}s")
        devs.append(d)
    print(f"upload total {total_bytes/1e6:.1f}MB {time.perf_counter()-t_all:.3f}s")
    dev = SchedulingProblem(*devs)

    kw = dict(
        num_levels=meta["num_levels"],
        max_slots=meta["max_slots"],
        slot_width=meta["slot_width"],
    )
    for label, extra in (("cached", {}), ("uncached", {"cache_slots": 0})):
        t0 = time.perf_counter()
        r = schedule_round(dev, **kw, **extra)
        jax.block_until_ready(r)
        compile_s = time.perf_counter() - t0
        times = []
        for _ in range(3):
            t0 = time.perf_counter()
            r = schedule_round(dev, **kw, **extra)
            jax.block_until_ready(r)
            times.append(time.perf_counter() - t0)
        print(
            f"kernel[{label}]: compile+1st {compile_s:.2f}s  best {min(times):.4f}s"
            f"  iters {int(r.iterations)} scheduled {int(r.scheduled_count)}"
        )

    # decode readback: what does pulling the result cost?
    t0 = time.perf_counter()
    host = jax.tree_util.tree_map(np.asarray, r)
    dt = time.perf_counter() - t0
    nbytes = sum(
        getattr(x, "nbytes", 0) for x in jax.tree_util.tree_leaves(host)
    )
    print(f"result readback {nbytes/1e6:.1f}MB {dt:.3f}s")
    for name, x in zip(r._fields, host):
        if getattr(x, "nbytes", 0) > 1 << 20:
            print(f"  result {name:20s} {x.nbytes/1e6:8.1f}MB")


if __name__ == "__main__":
    main()
