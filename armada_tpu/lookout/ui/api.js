// Fetch wrapper: the session cookie rides along automatically; a 401 from
// an OIDC-enabled server means the session died (expired + refresh failed,
// or logged out elsewhere) -- bounce through /login and come back to the
// exact URL we were on (OidcAuthProvider signinRedirect(state: href) parity).
export class AuthRequired extends Error {}

function bounceToLogin() {
  const next = location.pathname + location.search + location.hash;
  location.assign("/login?next=" + encodeURIComponent(next));
}

export async function j(url, init) {
  const r = await fetch(url, init);
  if (r.status === 401) {
    let d = {};
    try { d = await r.json(); } catch (e) { /* non-JSON 401 */ }
    if (d.login) { bounceToLogin(); throw new AuthRequired("redirecting to login"); }
  }
  return r.json();
}

// Shared operator-action POST (details panel + jobset rows): returns null
// on success or an error message; rides raw() so an expired OIDC session
// bounces to /login like every other API call.
export async function postAction(path, body) {
  try {
    const r = await raw(path, {
      method: "POST", headers: {"Content-Type": "application/json"},
      body: JSON.stringify(body),
    });
    if (!r.ok) {
      let msg = r.statusText;
      try { msg = (await r.json()).error || msg; } catch (e) { /* non-JSON */ }
      return msg;
    }
    return null;
  } catch (e) {
    if (e instanceof AuthRequired) throw e;
    return String(e);
  }
}

// Raw variant for callers that need status + body (logs viewer).
export async function raw(url, init) {
  const r = await fetch(url, init);
  if (r.status === 401) {
    let d = {};
    try { d = await r.clone().json(); } catch (e) { /* non-JSON 401 */ }
    if (d.login) { bounceToLogin(); throw new AuthRequired("redirecting to login"); }
  }
  return r;
}
