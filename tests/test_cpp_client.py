"""C++ client smoke test: build with make, run against a live control plane.

The reference ships native non-Go clients (client/DotNet, client/java,
client/scala); ours is C++ (client/cpp) over the grpc-gateway-parity REST
surface (armada_tpu/server/gateway.py).  This test is the CI-fashion gate:
protoc+g++ build, then the binary creates a queue, submits, and observes the
lease/success through the event stream -- a user driving the system end to
end from native code.
"""

import json
import shutil
import subprocess
from pathlib import Path

import pytest

from armada_tpu.server import QueueRecord
from armada_tpu.server.gateway import RestGateway
from tests.control_plane import ControlPlane

REPO = Path(__file__).resolve().parent.parent
CPP_DIR = REPO / "client" / "cpp"

pytestmark = pytest.mark.skipif(
    shutil.which("g++") is None or shutil.which("protoc") is None,
    reason="C++ toolchain not available",
)


@pytest.fixture(scope="module")
def cpp_binary():
    out = subprocess.run(
        ["make"], cwd=CPP_DIR, capture_output=True, text=True, timeout=300
    )
    assert out.returncode == 0, f"C++ client build failed:\n{out.stderr}"
    binary = CPP_DIR / "build" / "armadactl-cpp"
    assert binary.exists()
    return str(binary)


@pytest.fixture
def world(tmp_path):
    from armada_tpu.ingest.pipeline import IngestionPipeline
    from armada_tpu.lookout import LookoutDb, LookoutQueries, lookout_converter
    from armada_tpu.scheduler.reports import SchedulingReportsRepository

    plane = ControlPlane.build(tmp_path)
    lookoutdb = LookoutDb(":memory:")
    lookout_pipeline = IngestionPipeline(
        plane.log, lookoutdb, lookout_converter, consumer_name="lookout"
    )
    gateway = RestGateway(
        plane.server,
        plane.event_api,
        port=0,
        lookout_queries=LookoutQueries(lookoutdb),
        reports=SchedulingReportsRepository(),
    )
    yield plane, gateway, lookout_pipeline
    gateway.stop()
    plane.close()
    lookoutdb.close()


def run_cli(binary, gateway, *args):
    return subprocess.run(
        [binary, "127.0.0.1", str(gateway.port), *args],
        capture_output=True,
        text=True,
        timeout=60,
    )


def test_cpp_client_full_lifecycle(cpp_binary, world):
    plane, gateway, lookout_pipeline = world

    out = run_cli(cpp_binary, gateway, "create-queue", "cpp-q", "2.0")
    assert out.returncode == 0, out.stderr
    # duplicate create -> 409 surfaces as a client error
    dup = run_cli(cpp_binary, gateway, "create-queue", "cpp-q", "2.0")
    assert dup.returncode == 1 and "409" in dup.stderr + dup.stdout

    out = run_cli(cpp_binary, gateway, "list-queues")
    assert out.returncode == 0 and "cpp-q weight=2" in out.stdout

    out = run_cli(cpp_binary, gateway, "submit", "cpp-q", "cpp-js", "1", "1", "2")
    assert out.returncode == 0, out.stderr
    job_ids = out.stdout.split()
    assert len(job_ids) == 2

    # let the system schedule and finish the jobs
    plane.run_until(
        lambda: all(s == "succeeded" for s in plane.job_states().values())
        and len(plane.job_states()) == 2,
        tick_s=3.0,
    )

    out = run_cli(cpp_binary, gateway, "events", "cpp-q", "cpp-js")
    assert out.returncode == 0, out.stderr
    kinds = [line.split()[-1] for line in out.stdout.splitlines()]
    for expected in ("submit_job", "job_run_leased", "job_succeeded"):
        assert kinds.count(expected) == 2, (expected, kinds)

    # lookout + reports query surfaces from native code (VERDICT r4 weak #7:
    # non-Python clients beyond the submit/cancel/watch happy paths)
    lookout_pipeline.run_until_caught_up()
    out = run_cli(cpp_binary, gateway, "jobs", "cpp-q")
    assert out.returncode == 0, out.stderr
    rows = json.loads(out.stdout)
    assert {r["job_id"] for r in rows} == set(job_ids)
    assert all(r["state"] == "SUCCEEDED" for r in rows)
    out = run_cli(cpp_binary, gateway, "describe-job", job_ids[0])
    assert out.returncode == 0, out.stderr
    details = json.loads(out.stdout)
    assert details["job_id"] == job_ids[0] and details["runs"]
    # reports: an empty repository answers the route (404 for unknown job)
    out = run_cli(cpp_binary, gateway, "queue-report", "cpp-q")
    assert out.returncode == 0 and json.loads(out.stdout) == []
    out = run_cli(cpp_binary, gateway, "job-report", job_ids[0])
    assert out.returncode == 1 and "404" in out.stderr


def test_cpp_client_cancel(cpp_binary, world):
    plane, gateway, _ = world
    plane.server.create_queue(QueueRecord("cpp-q2", weight=1.0))
    out = run_cli(cpp_binary, gateway, "submit", "cpp-q2", "js", "1", "1")
    assert out.returncode == 0, out.stderr
    job_id = out.stdout.strip()

    out = run_cli(cpp_binary, gateway, "cancel", "cpp-q2", "js", job_id)
    assert out.returncode == 0, out.stderr
    plane.run_until(lambda: plane.job_states().get(job_id) == "cancelled")
