"""gRPC transport tests: the full stack over the wire.

Equivalent coverage to the reference's client/server integration
(pkg/client against internal/server, executor against ExecutorApi over its
stream): same system as test_e2e_stack but every interaction crosses
localhost gRPC.
"""

import threading
import time

import grpc
import pytest

from armada_tpu.executor import ExecutorService, FakeClusterContext
from armada_tpu.rpc.client import ArmadaClient, ExecutorApiClient
from armada_tpu.rpc.server import make_server
from armada_tpu.server import JobSubmitItem, QueueRecord
from tests.control_plane import ControlPlane


@pytest.fixture
def wired(tmp_path):
    cp = ControlPlane.build(tmp_path, runtime_s=4.0)
    server, port = make_server(
        submit_server=cp.server,
        event_api=cp.event_api,
        executor_api=cp.executor_api,
        factory=cp.config.resource_list_factory(),
    )
    client = ArmadaClient(f"127.0.0.1:{port}")
    yield cp, client, port
    client.close()
    server.stop(None)
    cp.close()


def item(cpu="2"):
    return JobSubmitItem(resources={"cpu": cpu, "memory": "2"})


def test_queue_crud_over_wire(wired):
    cp, client, _ = wired
    client.create_queue(QueueRecord("q1", weight=2.0))
    assert client.get_queue("q1").weight == 2.0
    with pytest.raises(grpc.RpcError) as e:
        client.create_queue(QueueRecord("q1"))
    assert e.value.code() == grpc.StatusCode.ALREADY_EXISTS
    client.update_queue(QueueRecord("q1", weight=3.0))
    assert [q.name for q in client.list_queues()] == ["q1"]
    with pytest.raises(grpc.RpcError) as e:
        client.get_queue("ghost")
    assert e.value.code() == grpc.StatusCode.NOT_FOUND
    client.delete_queue("q1")
    assert client.list_queues() == []


def test_submit_validation_error_maps_to_invalid_argument(wired):
    cp, client, _ = wired
    client.create_queue(QueueRecord("q1"))
    with pytest.raises(grpc.RpcError) as e:
        client.submit_jobs("q1", "js", [JobSubmitItem(resources={})])
    assert e.value.code() == grpc.StatusCode.INVALID_ARGUMENT
    with pytest.raises(grpc.RpcError) as e:
        client.submit_jobs("ghost", "js", [item()])
    assert e.value.code() == grpc.StatusCode.INVALID_ARGUMENT


def test_full_lifecycle_over_wire_with_grpc_executor(wired, tmp_path):
    cp, client, port = wired
    client.create_queue(QueueRecord("acme"))

    # a fake executor whose api handle is the gRPC client
    factory = cp.config.resource_list_factory()
    from armada_tpu.core.types import NodeSpec

    nodes = [
        NodeSpec(
            id=f"wx-n{i}",
            pool="default",
            executor="wx",
            total_resources=factory.from_mapping({"cpu": "8", "memory": "32"}),
        )
        for i in range(2)
    ]
    cluster = FakeClusterContext(nodes, factory, runtime_of=lambda s: 3.0)
    api_client = ExecutorApiClient(f"127.0.0.1:{port}")
    agent = ExecutorService("wx", "default", cluster, api_client, factory, clock=cp.clock)

    ids = client.submit_jobs("acme", "run-1", [item(), item()])
    assert len(ids) == 2

    def done():
        states = cp.job_states()
        return len(states) == 2 and all(s == "succeeded" for s in states.values())

    for _ in range(30):
        cp.ingest()
        cp.scheduler.cycle()
        cp.ingest()
        cluster.tick(2.0)
        agent.run_once()
        cp.clock.advance(2.0)
        if done():
            break
    assert done()

    # observe via the wire event api
    kinds = [
        ev.WhichOneof("event")
        for e in client.get_jobset_events("acme", "run-1")
        for ev in e.sequence.events
    ]
    assert kinds.count("job_succeeded") == 2
    api_client.close()


def test_watch_streams_live_events(wired):
    cp, client, _ = wired
    client.create_queue(QueueRecord("q1"))
    seen = []

    def consume():
        for e in client.watch("q1", "live", idle_timeout_s=5.0):
            seen.append(e)
            if len(seen) >= 1:
                return

    t = threading.Thread(target=consume)
    t.start()
    client.submit_jobs("q1", "live", [item()])
    cp.ingest()
    t.join(timeout=10)
    assert seen and any(
        ev.WhichOneof("event") == "submit_job" for ev in seen[0].sequence.events
    )


def test_principal_metadata_reaches_authorizer(wired):
    cp, client, port = wired
    client.create_queue(QueueRecord("q1"))
    named = ArmadaClient(f"127.0.0.1:{port}", principal="alice", groups=("team",))
    named.submit_jobs("q1", "js", [item()])
    cp.ingest()
    # the published sequence carries the principal as user_id
    events = cp.event_api.get_jobset_events("q1", "js")
    assert events[0].sequence.user_id == "alice"
    named.close()


def test_snapshot_queue_usage_round_trips():
    """queue_usage must survive the executor->scheduler proto hop (the
    reference ships ResourceUsageByQueueAndPool in NodeInfo); name-keyed so
    axis order never matters."""
    from armada_tpu.core.config import default_scheduling_config
    from armada_tpu.core.types import NodeSpec
    from armada_tpu.rpc.convert import snapshot_from_proto, snapshot_to_proto
    from armada_tpu.scheduler.executors import ExecutorSnapshot

    factory = default_scheduling_config().resource_list_factory()
    cpu_i = factory.index_of("cpu")
    atoms = [0] * factory.num_resources
    atoms[cpu_i] = 4000
    snap = ExecutorSnapshot(
        id="ex1",
        pool="default",
        nodes=(
            NodeSpec(
                id="n1",
                pool="default",
                total_resources=factory.from_mapping({"cpu": "8", "memory": "32"}),
            ),
        ),
        last_update_ns=7,
        queue_usage={"qa": tuple(atoms)},
    )
    back = snapshot_from_proto(snapshot_to_proto(snap), factory)
    assert back.queue_usage["qa"][cpu_i] == 4000
    assert sum(back.queue_usage["qa"]) == 4000


def test_snapshot_queue_usage_custom_axis_without_nodes():
    """With a custom resource axis and an empty node list, the explicit
    factory must label queue_usage keys -- the node-payload inference would
    fall back to the default config's axis order and silently drop the
    custom resource (round-3 advisor finding)."""
    import dataclasses

    from armada_tpu.core.config import default_scheduling_config
    from armada_tpu.rpc.convert import snapshot_from_proto, snapshot_to_proto
    from armada_tpu.scheduler.executors import ExecutorSnapshot

    cfg = dataclasses.replace(
        default_scheduling_config(),
        supported_resource_types=(("tpu-chips", "1"),)
        + default_scheduling_config().supported_resource_types,
    )
    factory = cfg.resource_list_factory()
    chips_i = factory.index_of("tpu-chips")
    atoms = [0] * factory.num_resources
    atoms[chips_i] = 8
    snap = ExecutorSnapshot(
        id="ex1", pool="default", nodes=(), last_update_ns=7,
        queue_usage={"qa": tuple(atoms)},
    )
    back = snapshot_from_proto(snapshot_to_proto(snap, factory), factory)
    assert back.queue_usage["qa"][chips_i] == 8


def test_gateway_malformed_body_is_a_400():
    """Unparseable JSON must come back as HTTP 400, not a dropped socket."""
    import json
    import urllib.error
    import urllib.request

    from armada_tpu.server.gateway import RestGateway

    class _StubServer:
        pass

    gw = RestGateway(_StubServer(), _StubServer(), port=0)
    try:
        req = urllib.request.Request(
            f"http://127.0.0.1:{gw.port}/v1/job/submit",
            method="POST",
            data=b"not json at all",
            headers={"Content-Type": "application/json"},
        )
        try:
            urllib.request.urlopen(req)
            raise AssertionError("expected HTTPError")
        except urllib.error.HTTPError as e:
            assert e.code == 400
            assert json.loads(e.read())["code"] == 400
        # non-integer from_idx likewise
        try:
            urllib.request.urlopen(
                f"http://127.0.0.1:{gw.port}/v1/job-set/q/s?from_idx=abc"
            )
            raise AssertionError("expected HTTPError")
        except urllib.error.HTTPError as e:
            assert e.code == 400
    finally:
        gw.stop()


def test_gateway_jobs_query_bad_bodies_are_400():
    """POST /v1/jobs/list with valid-JSON non-object bodies (list, null,
    scalar) must answer 400, never drop the connection; without a lookout
    store the route is a clean 404."""
    import json
    import urllib.error
    import urllib.request

    from armada_tpu.lookout import LookoutDb, LookoutQueries
    from armada_tpu.server.gateway import RestGateway

    class _StubServer:
        pass

    db = LookoutDb(":memory:")
    gw = RestGateway(
        _StubServer(), _StubServer(), port=0,
        lookout_queries=LookoutQueries(db),
    )
    try:
        for body in (b"[]", b"null", b'"x"', b"42"):
            req = urllib.request.Request(
                f"http://127.0.0.1:{gw.port}/v1/jobs/list",
                method="POST",
                data=body,
                headers={"Content-Type": "application/json"},
            )
            try:
                urllib.request.urlopen(req)
                raise AssertionError(f"expected 400 for body {body!r}")
            except urllib.error.HTTPError as e:
                assert e.code == 400, (body, e.code)
        # a well-formed query against the empty store answers []
        req = urllib.request.Request(
            f"http://127.0.0.1:{gw.port}/v1/jobs/list",
            method="POST",
            data=b"{}",
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req) as resp:
            assert json.loads(resp.read()) == []
    finally:
        gw.stop()
        db.close()
    # no lookout store behind the gateway: 404, not a crash
    gw = RestGateway(_StubServer(), _StubServer(), port=0)
    try:
        req = urllib.request.Request(
            f"http://127.0.0.1:{gw.port}/v1/jobs/list",
            method="POST",
            data=b"{}",
            headers={"Content-Type": "application/json"},
        )
        try:
            urllib.request.urlopen(req)
            raise AssertionError("expected 404")
        except urllib.error.HTTPError as e:
            assert e.code == 404
    finally:
        gw.stop()


def test_minigen_fallback_compiles_both_protos():
    """The protoc-absent fallback (events/_minigen.py) must compile BOTH
    repo protos -- rpc.proto includes a message-valued map
    (map<string, ResourceAtoms>), which once crashed the regen branch at
    import.  Generated modules register descriptors in the default pool, so
    the round-trip runs in a fresh interpreter."""
    import os
    import subprocess
    import sys
    import textwrap

    import armada_tpu

    root = os.path.dirname(os.path.dirname(armada_tpu.__file__))
    script = textwrap.dedent(
        """
        import os, sys, tempfile
        sys.path.insert(0, %r)
        from armada_tpu.events import _minigen
        d = tempfile.mkdtemp()
        pkg = os.path.join(d, "mgtest")
        os.makedirs(pkg)
        open(os.path.join(pkg, "__init__.py"), "w").close()
        ev = os.path.join(%r, "armada_tpu", "events", "events.proto")
        rp = os.path.join(%r, "armada_tpu", "rpc", "rpc.proto")
        with open(os.path.join(pkg, "events_pb2.py"), "w") as f:
            f.write(_minigen.generate_pb2_source(ev, "events.proto", "events_pb2"))
        with open(os.path.join(pkg, "rpc_pb2.py"), "w") as f:
            f.write(_minigen.generate_pb2_source(
                rp, "rpc.proto", "rpc_pb2",
                import_lines="from mgtest import events_pb2 as events__pb2\\n"))
        sys.path.insert(0, d)
        from mgtest import rpc_pb2 as pb
        m = pb.ExecutorSnapshot()
        m.queue_usage["qa"].atoms["cpu"] = 5
        m.node_of_run["r1"] = "n1"
        m2 = pb.ExecutorSnapshot.FromString(m.SerializeToString())
        assert m2.queue_usage["qa"].atoms["cpu"] == 5
        assert m2.node_of_run["r1"] == "n1"
        """
    ) % (root, root, root)
    r = subprocess.run(
        [sys.executable, "-c", script], capture_output=True, text=True
    )
    assert r.returncode == 0, r.stderr[-2000:]


# --- transport hardening (VERDICT #6) ----------------------------------------


def test_large_lease_response_survives_wire():
    """A >4MB lease batch -- routine at reference scale -- must cross the
    wire: gRPC's stock 4MB receive cap would kill it on BOTH sides (server
    send and client receive), so make_server/clients raise the caps
    together (rpc.server.server_options)."""
    from armada_tpu.core.config import SchedulingConfig
    from armada_tpu.scheduler.api import JobRunLease, LeaseRequest, LeaseResponse
    from armada_tpu.scheduler.executors import ExecutorSnapshot

    big_spec = b"x" * 100_000  # 100KB spec payload per lease
    leases = tuple(
        JobRunLease(
            run_id=f"r{i}",
            job_id=f"j{i}",
            queue="q1",
            jobset="js",
            node_id="n0",
            node_name="n0",
            pool="default",
            scheduled_at_priority=None,
            spec=big_spec,
        )
        for i in range(60)  # ~6MB total
    )

    class StubApi:
        def lease_job_runs(self, request):
            return LeaseResponse(
                leases=leases, runs_to_cancel=(), runs_to_preempt=()
            )

        def report_events(self, sequences):
            pass

    factory = SchedulingConfig().resource_list_factory()
    server, port = make_server(executor_api=StubApi(), factory=factory)
    client = ExecutorApiClient(f"127.0.0.1:{port}", factory=factory)
    try:
        resp = client.lease_job_runs(
            LeaseRequest(
                snapshot=ExecutorSnapshot(
                    id="ex1", pool="default", nodes=(), last_update_ns=1
                )
            )
        )
        assert len(resp.leases) == 60
        assert resp.leases[0].spec == big_spec
        assert sum(len(l.spec) for l in resp.leases) > 4 * 1024 * 1024
    finally:
        client.close()
        server.stop(None)


def test_idle_long_lived_watch_survives_keepalive(tmp_path):
    """An event watch that sits IDLE longer than the keepalive period must
    stay open (data-less pings are permitted in both directions) and then
    deliver an event submitted after the idle stretch."""
    cp = ControlPlane.build(tmp_path, runtime_s=4.0)
    server, port = make_server(
        submit_server=cp.server,
        event_api=cp.event_api,
        factory=cp.config.resource_list_factory(),
        keepalive_time_s=1.0,  # aggressive: several pings during the idle
    )
    client = ArmadaClient(f"127.0.0.1:{port}")
    try:
        client.create_queue(QueueRecord("q1"))
        got = []
        errors = []

        def watch():
            try:
                for e in client.watch("q1", "idlewatch", idle_timeout_s=30.0):
                    got.append(e)
                    return
            except Exception as exc:  # noqa: BLE001
                errors.append(exc)

        t = threading.Thread(target=watch, daemon=True)
        t.start()
        # idle across multiple keepalive periods, then produce the event
        time.sleep(3.0)
        assert t.is_alive() and not errors, f"watch died while idle: {errors}"
        client.submit_jobs("q1", "idlewatch", [item()])
        deadline = time.monotonic() + 10.0
        while t.is_alive() and time.monotonic() < deadline:
            cp.ingest()  # the watch serves the event DB, fed by ingestion
            t.join(timeout=0.2)
        assert not errors, f"watch failed after idle: {errors}"
        assert got, "the post-idle event must reach the watcher"
    finally:
        client.close()
        server.stop(None)
        cp.close()
