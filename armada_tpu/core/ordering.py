"""The ONE scheduling-order key, shared by every component that sorts jobs.

Queue-internal scheduling order (reference jobdb/comparison.go
JobPriorityComparer): higher priority-class priority first, then lower job
priority value, then earlier submission, then id as the final tiebreak.  Both
the JobDb queued index and the scheduling-problem builder call this, so they
can never drift.

Callers must pass the job's CURRENT priority (reprioritisation updates
jobdb.Job.priority; a stale spec.priority would order differently).
"""

from __future__ import annotations


def scheduling_order_key(
    pc_priority: int, priority: int, submitted: "int | float", job_id: str
) -> tuple:
    return (-pc_priority, priority, submitted, job_id)
