"""Operator config loading for the serving stack (VERDICT round-2 missing
#4): `armadactl serve --config` parses scheduling:/auth:/serve: sections with
the reference's key names, applies the ARMADA_* env overlay
(internal/common/startup.go LoadConfig), and boots a control plane whose
transports enforce the configured auth chain."""

import base64

import grpc
import pytest

from armada_tpu.core.config import (
    apply_env_overlay,
    operator_config_from_yaml,
)

CONFIG_YAML = """
scheduling:
  maxQueueLookback: 1234
  maximumSchedulingBurst: 77
  defaultPriorityClassName: armada-default
  shapeBucket: 32
auth:
  basic:
    users:
      alice: {password: pw, groups: [team]}
serve:
  port: 0
  cycleInterval: 0.05
  scheduleInterval: 0.1
  restPort: 0
"""


def test_operator_config_parses_sections(tmp_path):
    p = tmp_path / "config.yaml"
    p.write_text(CONFIG_YAML)
    loaded = operator_config_from_yaml(p.as_posix(), env={})
    assert loaded["scheduling"].max_queue_lookback == 1234
    assert loaded["scheduling"].maximum_scheduling_burst == 77
    assert loaded["auth"]["basic"]["users"]["alice"]["password"] == "pw"
    assert loaded["serve"]["cycleInterval"] == 0.05


def test_env_overlay_reference_semantics(tmp_path):
    doc = {"scheduling": {"maxQueueLookback": 10}, "serve": {"port": 1}}
    out = apply_env_overlay(
        doc,
        {
            "ARMADA_SCHEDULING__MAXQUEUELOOKBACK": "99",
            "ARMADA_SCHEDULING__ENABLEASSERTIONS": "true",
            "ARMADA_SERVE__BINDHOST": "0.0.0.0",
            "ARMADA_BENCH_JOBS": "5",  # bench knobs are NOT config keys
            "OTHER_VAR": "x",
        },
    )
    assert out["scheduling"]["maxQueueLookback"] == 99  # case-insensitive match
    assert out["scheduling"]["enableassertions"] is True
    assert out["serve"]["bindhost"] == "0.0.0.0"
    assert "jobs" not in out and "ARMADA_BENCH_JOBS" not in out
    # the original is untouched
    assert doc["scheduling"]["maxQueueLookback"] == 10

    p = tmp_path / "config.yaml"
    p.write_text(CONFIG_YAML)
    loaded = operator_config_from_yaml(
        p.as_posix(), env={"ARMADA_SCHEDULING__MAXQUEUELOOKBACK": "55"}
    )
    assert loaded["scheduling"].max_queue_lookback == 55


def test_serve_flag_merge_respects_cli_precedence(tmp_path):
    from armada_tpu.cli.armadactl import build_parser, load_serve_config

    p = tmp_path / "config.yaml"
    p.write_text(CONFIG_YAML)
    parser = build_parser()
    args = parser.parse_args(
        ["serve", "--config", p.as_posix(), "--schedule-interval", "9.0"]
    )
    config, auth = load_serve_config(args)
    assert config.max_queue_lookback == 1234
    assert auth is not None
    assert args.cycle_interval == 0.05  # unset flag filled from file
    assert args.schedule_interval == 9.0  # explicit flag wins over file
    assert args.rest_port == 0
    assert args.port == 0  # unset flag filled from the file's serve: section
    assert args.data_dir == "./armada-tpu-data"  # absent everywhere -> fallback

    # a flag explicitly set to its DEFAULT value still beats the file
    # (round-3 review finding: sentinel defaults, not value comparison)
    p2 = tmp_path / "config2.yaml"
    p2.write_text("serve:\n  port: 60000\n  scheduleInterval: 0.1\n")
    args2 = parser.parse_args(
        ["serve", "--config", p2.as_posix(), "--port", "50051"]
    )
    load_serve_config(args2)
    assert args2.port == 50051
    assert args2.schedule_interval == 0.1

    # no --config: every unset flag resolves to its fallback
    args3 = parser.parse_args(["serve"])
    load_serve_config(args3)
    assert args3.port == 50051 and args3.data_dir == "./armada-tpu-data"
    assert args3.cycle_interval == 1.0 and args3.bind_host == "127.0.0.1"


def test_control_plane_boots_from_config_file(tmp_path):
    """End-to-end: the stack boots from the file and the configured strict
    auth chain holds on gRPC and REST."""
    import urllib.error
    import urllib.request

    from armada_tpu.cli.armadactl import build_parser, load_serve_config
    from armada_tpu.cli.serve import start_control_plane
    from armada_tpu.rpc.client import ArmadaClient

    p = tmp_path / "config.yaml"
    p.write_text(CONFIG_YAML)
    args = build_parser().parse_args(
        ["serve", "--config", p.as_posix(), "--data-dir", (tmp_path / "d").as_posix()]
    )
    config, auth = load_serve_config(args)
    plane = start_control_plane(
        data_dir=args.data_dir,
        port=args.port,
        config=config,
        authenticator=auth,
        cycle_interval_s=args.cycle_interval,
        schedule_interval_s=args.schedule_interval,
        rest_port=args.rest_port,
    )
    try:
        addr = f"127.0.0.1:{plane.port}"
        with pytest.raises(grpc.RpcError) as exc:
            ArmadaClient(addr, principal="admin").list_queues()
        assert exc.value.code() == grpc.StatusCode.UNAUTHENTICATED
        ok = ArmadaClient(addr, basic_auth=("alice", "pw"))
        assert ok.list_queues() == []

        url = f"http://127.0.0.1:{plane.rest_gateway.port}/v1/batched/queues"
        with pytest.raises(urllib.error.HTTPError) as herr:
            urllib.request.urlopen(urllib.request.Request(url), timeout=5)
        assert herr.value.code == 401
        req = urllib.request.Request(url)
        cred = base64.b64encode(b"alice:pw").decode()
        req.add_header("Authorization", f"Basic {cred}")
        with urllib.request.urlopen(req, timeout=5) as resp:
            assert resp.status == 200
    finally:
        plane.stop()
