// Partitioned append-only event log: the framework's durable event backbone.
//
// TPU-native equivalent of the reference's Apache Pulsar deployment as used by
// Armada (internal/common/pulsarutils, internal/scheduler/publisher.go:25-60):
// an ordered, partitioned, replayable log that is the source of truth, with
// materialized views (scheduler DB, lookout DB, event streams) hanging off it.
// The reference outsources this to a Pulsar cluster; here it is an embedded
// native store so a single process group owns its log (no external broker).
//
// Design:
//   * N partitions, each an append-only file `p<k>.log` in the log directory.
//   * A record is [u32 paylen][u32 keylen][key][payload][u32 crc32(key+payload)].
//   * A message offset is the byte position of its record start; offsets are
//     monotonic per partition (comparable to Pulsar's (ledger, entry) message
//     ids, which the reference totally orders per partition).
//   * Readers scan forward from any offset; `el_read` copies whole records into
//     a caller buffer and returns the next offset (consumer position = the
//     high-water mark each materialized view persists, SURVEY.md section 5
//     "checkpoint/resume").
//   * On open, each partition tail is scanned and torn trailing writes are
//     truncated (crash recovery).
//   * Writes take a per-partition mutex; `el_flush` fsyncs everything (the
//     publisher calls it at batch boundaries, like Pulsar producer flush).
//
// Built as a shared library; Python binds via ctypes (armada_tpu/eventlog/log.py).

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <mutex>
#include <string>
#include <vector>

#include <fcntl.h>
#include <sys/stat.h>
#include <sys/types.h>
#include <unistd.h>

namespace {

uint32_t crc32_table[256];
std::once_flag crc32_once;

void crc32_init() {
  for (uint32_t i = 0; i < 256; i++) {
    uint32_t c = i;
    for (int k = 0; k < 8; k++) c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
    crc32_table[i] = c;
  }
}

uint32_t crc32(const uint8_t* a, size_t an, const uint8_t* b, size_t bn) {
  std::call_once(crc32_once, crc32_init);
  uint32_t c = 0xFFFFFFFFu;
  for (size_t i = 0; i < an; i++) c = crc32_table[(c ^ a[i]) & 0xFF] ^ (c >> 8);
  for (size_t i = 0; i < bn; i++) c = crc32_table[(c ^ b[i]) & 0xFF] ^ (c >> 8);
  return c ^ 0xFFFFFFFFu;
}

struct Partition {
  int fd = -1;
  int64_t end = 0;  // next append offset (== file size after recovery)
  std::mutex mu;
};

struct Log {
  std::string dir;
  std::vector<Partition> parts;
};

constexpr size_t kHeader = 8;   // paylen + keylen
constexpr size_t kTrailer = 4;  // crc
// Size caps shared by append and read: a record violating them is corrupt.
constexpr uint32_t kMaxPayload = 1u << 30;
constexpr uint32_t kMaxKey = 1u << 20;

// Validate the record at `off` in fd of size `size`. Returns record total
// length, or a negative classification:
//   -1  TORN -- the record is incomplete at the end of the region (header
//       does not fit, the declared length runs past `size`, or a CRC-failed
//       record that ends EXACTLY at `size`).  A crash mid-append tears the
//       FINAL record only: pwrite lays down a contiguous prefix and the
//       unfsynced tail sectors may not all have landed, but everything it
//       tears sits at the end of the file.
//   -2  CORRUPT -- an invalid record with more data after it (a mid-log CRC
//       mismatch, or insane declared lengths whose claimed extent fits
//       inside the region).  This is disk damage, never crash residue.
// With verify_crc, the body is read and checksummed too (used by the
// open-time recovery scan and by el_read).
int64_t record_len_at(int fd, int64_t off, int64_t size, bool verify_crc) {
  if (off + (int64_t)(kHeader + kTrailer) > size) return -1;
  uint8_t hdr[kHeader];
  if (pread(fd, hdr, kHeader, off) != (ssize_t)kHeader) return -1;
  uint32_t paylen, keylen;
  memcpy(&paylen, hdr, 4);
  memcpy(&keylen, hdr + 4, 4);
  int64_t total = kHeader + keylen + paylen + kTrailer;
  if (paylen > kMaxPayload || keylen > kMaxKey) {
    // Insane lengths whose claimed extent still runs past the region end
    // look exactly like a torn partial header at the tail (arbitrary
    // bytes where a header never finished landing); a claimed extent
    // that FITS inside the region is damage.
    return off + total > size ? -1 : -2;
  }
  if (off + total > size) return -1;
  if (verify_crc) {
    std::vector<uint8_t> body(keylen + paylen + kTrailer);
    if (pread(fd, body.data(), body.size(), off + kHeader) !=
        (ssize_t)body.size())
      return -1;
    uint32_t stored;
    memcpy(&stored, body.data() + keylen + paylen, 4);
    if (crc32(body.data(), keylen, body.data() + keylen, paylen) != stored)
      return off + total == size ? -1 : -2;
  }
  return total;
}

}  // namespace

extern "C" {

void* el_open(const char* dir, int num_partitions) {
  Log* log = new Log();
  log->dir = dir;
  mkdir(dir, 0755);
  log->parts = std::vector<Partition>(num_partitions);
  for (int k = 0; k < num_partitions; k++) {
    std::string path = log->dir + "/p" + std::to_string(k) + ".log";
    int fd = open(path.c_str(), O_RDWR | O_CREAT, 0644);
    if (fd < 0) {
      for (int j = 0; j < k; j++) close(log->parts[j].fd);
      delete log;
      return nullptr;
    }
    struct stat st;
    fstat(fd, &st);
    // Crash recovery: walk records from 0, verifying checksums.  A TORN
    // final record (crash mid-append) is truncated away -- the publisher
    // never acked it, so dropping it loses nothing.  A CORRUPT record
    // with data after it is disk damage: acked records would silently
    // vanish if we truncated here, so the open FAILS loudly instead
    // (operator restores from a replica or checkpoint; docs/operations.md).
    int64_t off = 0;
    int64_t total = 0;
    while (off < st.st_size) {
      total = record_len_at(fd, off, st.st_size, /*verify_crc=*/true);
      if (total < 0) break;
      off += total;
    }
    if (total == -2) {
      fprintf(stderr,
              "eventlog: corrupt record (not a torn tail) in %s at offset "
              "%lld; refusing to open\n",
              path.c_str(), (long long)off);
      close(fd);
      for (int j = 0; j < k; j++) close(log->parts[j].fd);
      delete log;
      return nullptr;
    }
    if (off < st.st_size) {
      if (ftruncate(fd, off) != 0) { /* keep going; end still caps reads */
      }
    }
    log->parts[k].fd = fd;
    log->parts[k].end = off;
  }
  return log;
}

void el_close(void* h) {
  Log* log = (Log*)h;
  if (!log) return;
  for (auto& p : log->parts)
    if (p.fd >= 0) close(p.fd);
  delete log;
}

int el_num_partitions(void* h) { return (int)((Log*)h)->parts.size(); }

// Append one record; returns its offset, or -1 on error.
int64_t el_append(void* h, int part, const void* key, int keylen,
                  const void* payload, int paylen) {
  Log* log = (Log*)h;
  if (part < 0 || part >= (int)log->parts.size()) return -1;
  if (keylen < 0 || (uint32_t)keylen > kMaxKey || paylen < 0 ||
      (uint32_t)paylen > kMaxPayload)
    return -1;  // would be unreadable: reject at write time, not read time
  Partition& p = log->parts[part];
  std::lock_guard<std::mutex> lock(p.mu);
  uint32_t pl = (uint32_t)paylen, kl = (uint32_t)keylen;
  uint32_t crc = crc32((const uint8_t*)key, kl, (const uint8_t*)payload, pl);
  size_t total = kHeader + kl + pl + kTrailer;
  std::vector<uint8_t> buf(total);
  memcpy(buf.data(), &pl, 4);
  memcpy(buf.data() + 4, &kl, 4);
  memcpy(buf.data() + kHeader, key, kl);
  memcpy(buf.data() + kHeader + kl, payload, pl);
  memcpy(buf.data() + kHeader + kl + pl, &crc, 4);
  int64_t off = p.end;
  ssize_t n = pwrite(p.fd, buf.data(), total, off);
  if (n != (ssize_t)total) {
    // Undo a partial write so the tail stays clean.
    if (ftruncate(p.fd, off) != 0) { /* recovery scan will fix on reopen */
    }
    return -1;
  }
  p.end = off + total;
  return off;
}

int64_t el_end_offset(void* h, int part) {
  Log* log = (Log*)h;
  if (part < 0 || part >= (int)log->parts.size()) return -1;
  return log->parts[part].end;
}

// Copy whole records starting at `offset` into buf (framing preserved) until
// buf is full, max_msgs records are copied, or the partition end is reached.
// Returns bytes written; *next_offset is where the next read should start.
int64_t el_read(void* h, int part, int64_t offset, void* buf, int64_t max_bytes,
                int64_t max_msgs, int64_t* next_offset) {
  Log* log = (Log*)h;
  if (part < 0 || part >= (int)log->parts.size()) return -1;
  Partition& p = log->parts[part];
  int64_t end;
  {
    std::lock_guard<std::mutex> lock(p.mu);
    end = p.end;
  }
  int64_t written = 0, off = offset, msgs = 0;
  uint8_t* out = (uint8_t*)buf;
  while (off < end && msgs < max_msgs) {
    int64_t total = record_len_at(p.fd, off, end, /*verify_crc=*/false);
    if (total < 0) return -2;  // corruption below `end`: surface loudly
    if (written + total > max_bytes) {
      // Caller's buffer can't hold even one record: distinguish from
      // caught-up so the reader can retry with a bigger buffer instead of
      // silently treating the partition as drained.
      if (msgs == 0) return -3;
      break;
    }
    if (pread(p.fd, out + written, total, off) != (ssize_t)total) break;
    // Verify the checksum on the copied bytes (no second disk read).
    uint8_t* rec = out + written;
    uint32_t paylen, keylen, stored;
    memcpy(&paylen, rec, 4);
    memcpy(&keylen, rec + 4, 4);
    memcpy(&stored, rec + kHeader + keylen + paylen, 4);
    if (crc32(rec + kHeader, keylen, rec + kHeader + keylen, paylen) != stored)
      return -2;
    written += total;
    off += total;
    msgs++;
  }
  *next_offset = off;
  return written;
}

int el_flush(void* h) {
  Log* log = (Log*)h;
  int rc = 0;
  for (auto& p : log->parts) {
    std::lock_guard<std::mutex> lock(p.mu);
    if (p.fd >= 0 && fsync(p.fd) != 0) rc = -1;
  }
  return rc;
}

// Truncate one partition to `offset` (divergence recovery: a follower
// drops an unacked suffix back to the last common prefix with the leader).
// `offset` must be <= the current end; the caller is responsible for it
// being a record boundary (the open-time recovery scan would truncate a
// mid-record cut anyway, but the in-memory end would briefly disagree).
int el_truncate(void* h, int part, int64_t offset) {
  Log* log = (Log*)h;
  if (part < 0 || part >= (int)log->parts.size()) return -1;
  Partition& p = log->parts[part];
  std::lock_guard<std::mutex> lock(p.mu);
  if (offset < 0 || offset > p.end) return -1;
  if (ftruncate(p.fd, offset) != 0) return -1;
  if (fsync(p.fd) != 0) return -1;
  p.end = offset;
  return 0;
}

// Truncate every partition to zero (test helper / dev reset).
int el_reset(void* h) {
  Log* log = (Log*)h;
  for (auto& p : log->parts) {
    std::lock_guard<std::mutex> lock(p.mu);
    if (ftruncate(p.fd, 0) != 0) return -1;
    p.end = 0;
  }
  return 0;
}

}  // extern "C"
