"""Token-bucket rate limiters for scheduling throughput.

Equivalent of the reference's rate limiters (configuration
maximumSchedulingRate / maximumPerQueueSchedulingRate with bursts,
config/scheduler/config.yaml:103-107; consulted per gang in
queue_scheduler.go): tokens refill continuously at `rate`; each scheduled
job consumes one; a round's burst caps are clamped to the available tokens,
so sustained throughput converges to the configured rate while short bursts
up to the burst size pass immediately.
"""

from __future__ import annotations

import time
from typing import Callable, Optional


class TokenBucket:
    def __init__(
        self,
        rate_per_s: float,
        burst: int,
        clock: Callable[[], float] = time.time,
    ):
        """rate_per_s <= 0 or burst <= 0 disables limiting (unlimited)."""
        self.rate = rate_per_s
        self.burst = burst
        self._clock = clock
        self._tokens = float(burst)
        self._last = clock()

    @property
    def unlimited(self) -> bool:
        return self.rate <= 0 or self.burst <= 0

    def available(self) -> int:
        if self.unlimited:
            return 2**31 - 1
        now = self._clock()
        self._tokens = min(
            float(self.burst), self._tokens + (now - self._last) * self.rate
        )
        self._last = now
        return max(0, int(self._tokens))

    def consume(self, n: int) -> None:
        if not self.unlimited:
            self.available()  # refill first
            self._tokens = max(0.0, self._tokens - n)


class SchedulingRateLimiters:
    """The scheduler's global + per-queue buckets (lazily created)."""

    def __init__(
        self,
        rate_per_s: float,
        burst: int,
        per_queue_rate_per_s: float,
        per_queue_burst: int,
        clock: Callable[[], float] = time.time,
    ):
        self._clock = clock
        self.global_bucket = TokenBucket(rate_per_s, burst, clock)
        self._pq_rate = per_queue_rate_per_s
        self._pq_burst = per_queue_burst
        self._queues: dict[str, TokenBucket] = {}

    def queue_bucket(self, queue: str) -> TokenBucket:
        b = self._queues.get(queue)
        if b is None:
            b = TokenBucket(self._pq_rate, self._pq_burst, self._clock)
            self._queues[queue] = b
        return b

    def tokens(self, queues) -> tuple[Optional[int], Optional[dict]]:
        """(global_tokens, {queue: tokens}) for build_problem; None = unlimited."""
        g = None if self.global_bucket.unlimited else self.global_bucket.available()
        q = None
        if self._pq_rate > 0 and self._pq_burst > 0:
            q = {name: self.queue_bucket(name).available() for name in queues}
        return g, q

    def consume(self, scheduled_by_queue: dict) -> None:
        total = sum(scheduled_by_queue.values())
        self.global_bucket.consume(total)
        if self._pq_rate > 0 and self._pq_burst > 0:
            for queue, n in scheduled_by_queue.items():
                self.queue_bucket(queue).consume(n)
