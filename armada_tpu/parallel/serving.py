"""Mesh serving plane: process-global mesh state + the chip-loss ladder.

`parallel/mesh.py` proves the sharded round; this module makes the REAL
steady cycle run on it.  ``MeshServing`` is the process-wide answer to
"how many chips do rounds target right now", mirroring the watchdog's
DeviceSupervisor (core/watchdog.py) exactly one rung higher on the degrade
ladder::

    full mesh (serve --mesh N / ARMADA_MESH)
      -> smaller mesh      (chip loss: halve, re-shard, one slab re-upload)
      -> single device     (mesh exhausted: the plain single-chip path)
      -> XLA:CPU failover  (the watchdog's existing rung)

A degrade fires the SAME module-level reset hooks the watchdog uses
(core/watchdog.fire_reset_hooks): every feed replaces its device caches,
so the next cycle's apply() is one full slab upload sharded onto the
CURRENT mesh -- the generation/identity machinery that already makes
device->cpu flips race-safe (zombie watchdog workers only ever touch the
orphaned cache of their own round) covers mesh re-shards for free.

Divisibility is a BUILD-time property, never a serve-time error: the
incremental builders round their node-axis pad bucket to
``mesh_axis_multiple()`` (models/incremental._node_bucket) and the generic
``shard_problem`` pads inert lanes, so geometric slab growth can never
trip ``_check_divisible`` mid-serve.

Restore mirrors the watchdog re-probe: after a degrade, a background
subprocess probe (the only hang-safe way to ask the axon tunnel anything)
re-arms the FULL mesh after N consecutive healthy checks, riding one full
re-shard upload.  Knobs are shared with the watchdog:
``ARMADA_REPROBE_INTERVAL_S`` (0 disables -- tests/operators call
``restore()`` themselves), ``ARMADA_REPROBE_HEALTHY``,
``ARMADA_REPROBE_TIMEOUT_S``.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Optional

from armada_tpu.analysis.tsan import make_lock
from armada_tpu.core.logging import get_logger

_log = get_logger(__name__)


class MeshServing:
    """Process-wide mesh serving state (see module docstring)."""

    def __init__(self):
        self._lock = make_lock("parallel.mesh_serving")
        self._requested = 0  # serve --mesh N / ARMADA_MESH (0 = off)
        self._active = 0  # current ladder rung (devices rounds target)
        self._meshes: dict = {}  # active count -> constructed Mesh
        self.degrades = 0
        self.restores = 0
        self.last_degrade_reason: Optional[str] = None
        self.last_degrade_ts: Optional[float] = None
        self._restore_thread: Optional[threading.Thread] = None
        self._probe = None  # patchable in tests; default watchdog.probe_device

    # ------------------------------------------------------------ config ----

    def configure(self, n_devices: int) -> None:
        """Arm (n >= 2) or disarm (0/1) mesh serving.  Called by serve
        before the feed builds its device caches; idempotent."""
        n = max(0, int(n_devices))
        with self._lock:
            self._requested = n
            self._active = n
            self._meshes = {}

    def enabled(self) -> bool:
        """Mesh serving is armed (regardless of the current ladder rung or
        the watchdog backend) -- cheap, touches no jax state."""
        return self._requested >= 2

    def device_count(self) -> int:
        """Devices the current rung targets (0 when off/exhausted).  A
        display/trace number; `serving_mesh()` is the placement truth."""
        with self._lock:
            return self._active if self._requested >= 2 and self._active >= 2 else 0

    def axis_multiple(self) -> int:
        """The node-axis alignment every problem/slab axis must honour:
        the CONFIGURED mesh size (monotone over the whole ladder -- every
        smaller rung is reached by halving, so a multiple of the configured
        size divides every rung)."""
        return self._requested if self._requested >= 2 else 1

    # ------------------------------------------------------------- meshes ---

    def serving_mesh(self):
        """The Mesh rounds should run on right now, or None (mesh off,
        ladder exhausted, or fewer real devices than two).  First call per
        rung touches jax.devices() -- callers on the serving path do so
        inside the watchdog deadline (a tunnel hang here is a device loss
        like any other)."""
        with self._lock:
            n = self._active if self._requested >= 2 else 0
        return self._mesh_for(n)

    def _mesh_for(self, n: int):
        """Construct (or return the cached) Mesh for a SPECIFIC rung --
        callers that just set a rung pass it explicitly, so a concurrent
        restore() can never hand them a different (larger) mesh than the
        one their transition decided on."""
        if n < 2:
            return None
        mesh = self._meshes.get(n)
        if mesh is not None:
            return mesh
        import jax

        from armada_tpu.parallel.mesh import make_mesh

        avail = len(jax.devices())
        clamped = n
        while clamped > avail:
            clamped = clamped // 2 if clamped % 2 == 0 else 1
        if clamped != n:
            _log.warning(
                "mesh serving requested %d devices, %d visible: serving on %d",
                n, avail, clamped,
            )
            with self._lock:
                if self._active > clamped:
                    self._active = clamped
            if clamped < 2:
                return None
            n = clamped
            mesh = self._meshes.get(n)
            if mesh is not None:
                return mesh
        mesh = make_mesh(
            jax.devices()[:n], node_shards=n, job_shards=1
        )
        with self._lock:
            self._meshes[n] = mesh
        return mesh

    # -------------------------------------------------------- transitions ---

    def degrade(self, reason: str):
        """One rung down the ladder (chip loss): halve the mesh, fire the
        device-cache reset hooks, start the restore probe.  Returns the new
        (smaller) Mesh for the caller's immediate re-run, or None when the
        ladder is exhausted (single device next, then the watchdog's CPU
        failover)."""
        with self._lock:
            if self._requested < 2 or self._active < 2:
                return None
            self._active = (
                self._active // 2 if self._active % 2 == 0 else 1
            )
            self.degrades += 1
            self.last_degrade_reason = str(reason)[:300]
            self.last_degrade_ts = time.time()
            new_n = self._active
        _log.error(
            "mesh round failed (%s): degrading to %d devices (one full "
            "slab re-shard)", reason, new_n,
        )
        from armada_tpu.core.watchdog import fire_reset_hooks

        fire_reset_hooks()
        self._start_restore_probe()
        # The rung THIS transition decided on -- never re-read _active: a
        # fast concurrent restore() (drill-speed probes) would hand the
        # caller back the full mesh that just failed.
        return self._mesh_for(new_n)

    def restore(self) -> bool:
        """Back to the full configured mesh (probe-driven or operator);
        device caches re-shard on their next apply via the reset hooks.
        Returns False (staying on the smaller rung) while the watchdog
        promotion gate vetoes -- a quarantined chip must not rejoin the
        mesh until the operator clears it (scheduler/quarantine.py)."""
        from armada_tpu.core.watchdog import promotion_blocked

        blocked = promotion_blocked()
        if blocked:
            _log.warning(
                "mesh probes healthy but restore is blocked: %s", blocked
            )
            return False
        with self._lock:
            if self._requested < 2 or self._active >= self._requested:
                return True
            self._active = self._requested
            self.restores += 1
        _log.warning(
            "mesh healthy again: restoring the full %d-device mesh (next "
            "cycle pays one full slab re-upload)", self._requested,
        )
        from armada_tpu.core.watchdog import fire_reset_hooks

        fire_reset_hooks()
        return True

    # ------------------------------------------------------------ reprobe ---

    def _start_restore_probe(self) -> None:
        from armada_tpu.core.watchdog import supervisor

        if supervisor().reprobe_interval_s() <= 0:
            return  # operator/tests restore manually
        with self._lock:
            if self._restore_thread is not None and self._restore_thread.is_alive():
                return
            t = threading.Thread(
                target=self._restore_loop, daemon=True, name="mesh-restore"
            )
            self._restore_thread = t
        t.start()

    def _restore_loop(self) -> None:
        from armada_tpu.core.watchdog import probe_device, supervisor

        sup = supervisor()
        probe = self._probe or probe_device
        timeout = float(os.environ.get("ARMADA_REPROBE_TIMEOUT_S", "60"))
        healthy = 0
        need = sup.healthy_checks()
        while True:
            with self._lock:
                done = self._requested < 2 or self._active >= self._requested
            if done:
                break
            time.sleep(sup.reprobe_interval_s())
            ok, detail = probe(timeout)
            if ok:
                healthy += 1
                _log.info("mesh re-probe healthy (%s): %d/%d", detail, healthy, need)
                if healthy >= need and self.restore():
                    break
                # gate-blocked (quarantine): keep polling so an operator
                # clear restores on the next healthy pass
            else:
                healthy = 0
                _log.info("mesh re-probe still failing: %s", detail)
        with self._lock:
            self._restore_thread = None

    # ------------------------------------------------------------- export ---

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "requested": self._requested,
                # 0 = mesh off or ladder exhausted (single-device rounds)
                "devices": (
                    self._active
                    if self._requested >= 2 and self._active >= 2
                    else 0
                ),
                "degrades": self.degrades,
                "restores": self.restores,
                "last_degrade_reason": self.last_degrade_reason,
                "last_degrade_ts": self.last_degrade_ts,
            }


_MESH_SERVING = MeshServing()


def mesh_serving() -> MeshServing:
    return _MESH_SERVING


def reset_mesh_serving() -> MeshServing:
    """Fresh state (tests).  Like watchdog.reset_supervisor: an in-flight
    restore thread of the old instance exits on its next poll."""
    global _MESH_SERVING
    _MESH_SERVING = MeshServing()
    return _MESH_SERVING


def mesh_axis_multiple() -> int:
    """Alignment the problem builders apply to sharded axes (1 = off).
    Cheap (no jax): safe on every assemble."""
    return _MESH_SERVING.axis_multiple()


def dryrun_round(n_devices: int) -> int:
    """One sharded round on tiny shapes over an n-device (nodes x jobs)
    mesh -- the driver's multi-chip compile check (__graft_entry__.py
    delegates here; this is the ONE home of the dry-run's mesh dispatch).
    Returns the scheduled-member count (> 0 asserted)."""
    import jax

    from armada_tpu.models.synthetic import synthetic_problem
    from armada_tpu.parallel.mesh import make_mesh, sharded_schedule_round

    devices = jax.devices("cpu")[:n_devices]
    job_shards = 2 if n_devices % 2 == 0 and n_devices >= 4 else 1
    node_shards = n_devices // job_shards
    mesh = make_mesh(devices, node_shards=node_shards, job_shards=job_shards)

    pad = 2 * node_shards * job_shards
    problem, meta = synthetic_problem(
        num_nodes=max(16, pad),
        num_gangs=max(64, 4 * pad),
        num_queues=4,
        num_runs=max(8, pad),
        max_gang_cardinality=2,
        global_burst=16,
        perq_burst=8,
        node_pad_to=pad,
        gang_pad_to=pad,
    )
    result = sharded_schedule_round(
        problem,
        mesh,
        num_levels=meta["num_levels"],
        max_slots=meta["max_slots"],
        slot_width=meta["slot_width"],
    )
    jax.block_until_ready(result)  # lint: allow(fetch-not-barrier) -- dry-run on the virtual CPU mesh; the scalar fetch below is the real sync
    scheduled = int(result.scheduled_count)
    assert scheduled > 0, "dry run scheduled nothing"
    return scheduled
