"""Authentication (internal/common/auth parity): basic, OIDC bearer,
kubernetes token review, trusted headers, multi chains -- and the transport
contract that an unauthenticated or forged request is rejected on EVERY
gRPC service and the REST gateway (VERDICT round-2 missing #2)."""

import base64
import hashlib
import hmac
import json
import threading
import time

import grpc
import pytest

from armada_tpu.server.authn import (
    AnonymousAuthenticator,
    AuthenticationError,
    BasicAuthenticator,
    KubernetesTokenReviewAuthenticator,
    MultiAuthenticator,
    OidcAuthenticator,
    TrustedHeaderAuthenticator,
    authn_from_config,
)


def _b64u(b: bytes) -> str:
    return base64.urlsafe_b64encode(b).decode().rstrip("=")


def make_jwt(claims, secret=None, rsa_key=None, kid="k1", alg=None):
    alg = alg or ("HS256" if secret else "RS256")
    header = {"alg": alg, "kid": kid, "typ": "JWT"}
    signed = f"{_b64u(json.dumps(header).encode())}.{_b64u(json.dumps(claims).encode())}"
    if alg == "HS256":
        sig = hmac.new(secret.encode(), signed.encode(), hashlib.sha256).digest()
    else:
        from cryptography.hazmat.primitives import hashes
        from cryptography.hazmat.primitives.asymmetric import padding

        sig = rsa_key.sign(signed.encode(), padding.PKCS1v15(), hashes.SHA256())
    return f"{signed}.{_b64u(sig)}"


# --------------------------------------------------------------- unit -------


def test_basic_accepts_and_rejects():
    a = BasicAuthenticator({"alice": ("pw1", ("team",)), "bob": "pw2"})
    cred = base64.b64encode(b"alice:pw1").decode()
    p = a.authenticate({"authorization": f"Basic {cred}"})
    assert p.name == "alice" and p.groups == ("team",)
    bad = base64.b64encode(b"alice:wrong").decode()
    with pytest.raises(AuthenticationError):
        a.authenticate({"authorization": f"Basic {bad}"})
    unknown = base64.b64encode(b"eve:pw1").decode()
    with pytest.raises(AuthenticationError):
        a.authenticate({"authorization": f"Basic {unknown}"})
    assert a.authenticate({}) is None  # no credentials -> not handled


def test_malformed_credentials_reject_cleanly():
    """Attacker-shaped input must produce AuthenticationError, never an
    unhandled crash (round-3 review findings: non-ASCII basic passwords hit
    compare_digest's str TypeError; JSON-list JWT segments hit .get())."""
    a = BasicAuthenticator({"alice": "pw"})
    cred = base64.b64encode("alice:pässwörd".encode()).decode()
    with pytest.raises(AuthenticationError):
        a.authenticate({"authorization": f"Basic {cred}"})

    o = OidcAuthenticator("iss", "aud", {"": "hs256:s"})
    list_seg = _b64u(b"[]")
    for tok in (
        f"{list_seg}.{list_seg}.{list_seg}",
        "not-base64!.x.y",
    ):
        with pytest.raises(AuthenticationError):
            o.authenticate({"authorization": f"Bearer {tok}"})


def test_token_review_verdicts_are_cached():
    calls = []

    class _FakeReview(KubernetesTokenReviewAuthenticator):
        def __init__(self):
            super().__init__("http://unused", clock=lambda: now[0])

        def authenticate(self, metadata):  # route through the real cache
            return super().authenticate(metadata)

    now = [0.0]
    a = _FakeReview()

    def fake_urlopen(req, timeout=None, context=None):
        import io

        calls.append(1)
        body = json.dumps(
            {"status": {"authenticated": True, "user": {"username": "sa"}}}
        ).encode()

        class R(io.BytesIO):
            status = 201

            def __enter__(self):
                return self

            def __exit__(self, *a):
                return False

        return R(body)

    import urllib.request as ur

    orig = ur.urlopen
    ur.urlopen = fake_urlopen
    try:
        md = {"authorization": "Bearer tok"}
        assert a.authenticate(md).name == "sa"
        assert a.authenticate(md).name == "sa"
        assert len(calls) == 1  # second hit served from cache
        now[0] = 301.0  # TTL expired -> re-review
        assert a.authenticate(md).name == "sa"
        assert len(calls) == 2
    finally:
        ur.urlopen = orig


def test_oidc_hs256_claims():
    clock = lambda: 1000.0
    a = OidcAuthenticator(
        "https://issuer", "armada", {"k1": "hs256:sekrit"}, clock=clock
    )
    claims = {
        "iss": "https://issuer",
        "aud": "armada",
        "sub": "alice",
        "groups": ["team-a", "team-b"],
        "exp": 2000,
    }
    p = a.authenticate(
        {"authorization": "Bearer " + make_jwt(claims, secret="sekrit")}
    )
    assert p.name == "alice" and p.groups == ("team-a", "team-b")
    # tampered signature
    with pytest.raises(AuthenticationError):
        a.authenticate(
            {"authorization": "Bearer " + make_jwt(claims, secret="wrong")}
        )
    # expired
    with pytest.raises(AuthenticationError):
        a.authenticate(
            {
                "authorization": "Bearer "
                + make_jwt({**claims, "exp": 100}, secret="sekrit")
            }
        )
    # wrong issuer / audience
    for bad in ({"iss": "https://evil"}, {"aud": "other"}):
        with pytest.raises(AuthenticationError):
            a.authenticate(
                {
                    "authorization": "Bearer "
                    + make_jwt({**claims, **bad}, secret="sekrit")
                }
            )


def test_oidc_rs256_roundtrip():
    pytest.importorskip("cryptography")
    from cryptography.hazmat.primitives import serialization
    from cryptography.hazmat.primitives.asymmetric import rsa

    key = rsa.generate_private_key(public_exponent=65537, key_size=2048)
    pem = key.public_key().public_bytes(
        serialization.Encoding.PEM, serialization.PublicFormat.SubjectPublicKeyInfo
    ).decode()
    a = OidcAuthenticator("iss", "aud", {"k1": pem})
    claims = {"iss": "iss", "aud": ["aud", "other"], "sub": "svc",
              "exp": time.time() + 60}
    p = a.authenticate({"authorization": "Bearer " + make_jwt(claims, rsa_key=key)})
    assert p.name == "svc"
    other = rsa.generate_private_key(public_exponent=65537, key_size=2048)
    with pytest.raises(AuthenticationError):
        a.authenticate(
            {"authorization": "Bearer " + make_jwt(claims, rsa_key=other)}
        )


def test_token_review_against_fake_apiserver():
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    class H(BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def do_POST(self):
            body = json.loads(self.rfile.read(int(self.headers["Content-Length"])))
            tok = body["spec"]["token"]
            if tok == "good":
                out = {"status": {"authenticated": True,
                                  "user": {"username": "system:sa:ns:runner",
                                           "groups": ["system:serviceaccounts"]}}}
            else:
                out = {"status": {"authenticated": False}}
            data = json.dumps(out).encode()
            self.send_response(201)
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

    httpd = ThreadingHTTPServer(("127.0.0.1", 0), H)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    try:
        a = KubernetesTokenReviewAuthenticator(
            f"http://127.0.0.1:{httpd.server_address[1]}"
        )
        p = a.authenticate({"authorization": "Bearer good"})
        assert p.name == "system:sa:ns:runner"
        assert "system:serviceaccounts" in p.groups
        with pytest.raises(AuthenticationError):
            a.authenticate({"authorization": "Bearer bad"})
        assert a.authenticate({}) is None
    finally:
        httpd.shutdown()
        httpd.server_close()


def test_multi_chain_order_and_rejection():
    chain = MultiAuthenticator(
        [BasicAuthenticator({"alice": "pw"}), AnonymousAuthenticator()]
    )
    cred = base64.b64encode(b"alice:pw").decode()
    assert chain.authenticate({"authorization": f"Basic {cred}"}).name == "alice"
    assert chain.authenticate({}).name == "anonymous"
    strict = MultiAuthenticator([BasicAuthenticator({"alice": "pw"})])
    with pytest.raises(AuthenticationError):
        strict.authenticate({})  # no credentials, no anonymous fallback
    with pytest.raises(AuthenticationError):
        # forged trusted header means nothing to a strict chain
        strict.authenticate({"x-armada-principal": "admin"})


def test_authn_from_config():
    cfg = {
        "basic": {"users": {"alice": {"password": "pw", "groups": ["team"]}}},
        "oidc": {"issuer": "iss", "audience": "aud", "keys": {"": "hs256:s"}},
        "trusted_headers": True,
        "anonymous": True,
    }
    chain = authn_from_config(cfg)
    assert chain.authenticate({"x-armada-principal": "ops"}).name == "ops"
    assert chain.authenticate({}).name == "anonymous"
    # config WITHOUT anonymous/trusted: strict
    strict = authn_from_config({"basic": {"users": {"a": "p"}}})
    with pytest.raises(AuthenticationError):
        strict.authenticate({"x-armada-principal": "admin"})


# ------------------------------------------------- transport contract -------


class _StubSubmit:
    def list_queues(self):
        return []


class _StubEvents:
    def get_jobset_events(self, queue, jobset, idx):
        return []


class _StubQueries:
    def get_jobs(self, *a, **k):
        return []


class _StubReports:
    def pool_report(self, name):
        return {}


class _StubBinoculars:
    def logs(self, job_id="", run_id=""):
        return ""


class _StubExecApi:
    def report_events(self, seqs):
        pass


@pytest.fixture
def strict_server():
    from armada_tpu.core.config import default_scheduling_config
    from armada_tpu.rpc.server import make_server

    auth = MultiAuthenticator([BasicAuthenticator({"alice": ("pw", ("team",))})])
    server, port = make_server(
        submit_server=_StubSubmit(),
        event_api=_StubEvents(),
        lookout_queries=_StubQueries(),
        reports=_StubReports(),
        binoculars=_StubBinoculars(),
        executor_api=_StubExecApi(),
        factory=default_scheduling_config().resource_list_factory(),
        authenticator=auth,
    )
    yield port
    server.stop(None)


def test_every_grpc_service_rejects_unauthenticated(strict_server):
    from armada_tpu.rpc.client import (
        ArmadaClient,
        BinocularsClient,
        ExecutorApiClient,
    )

    addr = f"127.0.0.1:{strict_server}"
    # forged trusted header: the strict chain must NOT honour it
    calls = [
        lambda: ArmadaClient(addr, principal="admin").list_queues(),
        lambda: ArmadaClient(addr, principal="admin").get_jobset_events("q", "js"),
        lambda: ArmadaClient(addr, principal="admin").get_jobs(),
        lambda: ArmadaClient(addr, principal="admin").get_pool_report(),
        lambda: BinocularsClient(addr, principal="admin").logs(job_id="x"),
        lambda: ExecutorApiClient(addr, principal="admin").report_events([]),
    ]
    for call in calls:
        with pytest.raises(grpc.RpcError) as exc:
            call()
        assert exc.value.code() == grpc.StatusCode.UNAUTHENTICATED

    # valid credentials pass the same chain
    ok = ArmadaClient(addr, basic_auth=("alice", "pw"))
    assert ok.list_queues() == []
    bad = ArmadaClient(addr, basic_auth=("alice", "wrong"))
    with pytest.raises(grpc.RpcError) as exc:
        bad.list_queues()
    assert exc.value.code() == grpc.StatusCode.UNAUTHENTICATED


def test_gateway_rejects_unauthenticated():
    import urllib.error
    import urllib.request

    from armada_tpu.server.gateway import RestGateway

    auth = MultiAuthenticator([BasicAuthenticator({"alice": "pw"})])
    gw = RestGateway(_StubSubmit(), _StubEvents(), authenticator=auth)
    try:
        url = f"http://127.0.0.1:{gw.port}/v1/batched/queues"
        req = urllib.request.Request(url)
        req.add_header("x-armada-principal", "admin")  # forged
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(req, timeout=5)
        assert exc.value.code == 401
        ok = urllib.request.Request(url)
        cred = base64.b64encode(b"alice:pw").decode()
        ok.add_header("Authorization", f"Basic {cred}")
        with urllib.request.urlopen(ok, timeout=5) as resp:
            assert resp.status == 200
    finally:
        gw.stop()


# ---- kerberos / SPNEGO (configuration/types.go:42) ---------------------------


def _krb_chain(clock=None):
    """A KerberosAuthenticator with an injected validator: accepts tokens of
    the form b"krb:<principal>", rejects everything else -- the pluggable
    seam real deployments fill with python-gssapi."""
    from armada_tpu.server.authn import KerberosAuthenticator

    def validator(token: bytes) -> str:
        if not token.startswith(b"krb:"):
            raise ValueError("not a kerberos token")
        return token[4:].decode()

    kw = {"clock": clock} if clock else {}
    return KerberosAuthenticator(
        validator=validator,
        username_suffix="-svc",
        groups_of=lambda user: (f"{user}-team@grp",),
        group_name_suffix="@grp",
        **kw,
    )


def _negotiate(token: bytes) -> dict:
    import base64

    return {"authorization": "Negotiate " + base64.b64encode(token).decode()}


def test_kerberos_accepts_and_maps_principal():
    a = _krb_chain()
    p = a.authenticate(_negotiate(b"krb:alice-svc@EXAMPLE.COM"))
    # realm stripped, then the configured username suffix; groups via the
    # lookup hook with the group suffix stripped (LDAP analog)
    assert p.name == "alice"
    assert p.groups == ("alice-team",)


def test_kerberos_ignores_other_credentials():
    a = _krb_chain()
    assert a.authenticate({"authorization": "Bearer xyz"}) is None
    assert a.authenticate({}) is None


def test_kerberos_rejects_forged_token():
    from armada_tpu.server.authn import AuthenticationError

    a = _krb_chain()
    with pytest.raises(AuthenticationError, match="kerberos rejected"):
        a.authenticate(_negotiate(b"forged-bytes"))
    with pytest.raises(AuthenticationError, match="malformed"):
        a.authenticate({"authorization": "Negotiate !!!not-base64!!!"})


def test_kerberos_rejects_replayed_token():
    """AP-REQ tokens are single-use: the same Negotiate header presented
    twice is a replay (a captured header must not become a bearer token).
    After the TTL window the digest ages out."""
    from armada_tpu.server.authn import AuthenticationError

    now = [1000.0]
    a = _krb_chain(clock=lambda: now[0])
    header = _negotiate(b"krb:alice@X")
    assert a.authenticate(header).name == "alice"
    with pytest.raises(AuthenticationError, match="replayed"):
        a.authenticate(header)
    now[0] += 301  # past replay_ttl_s
    assert a.authenticate(header).name == "alice"


def test_kerberos_client_negotiate_header_round_trips():
    """rpc client -> header -> authenticator: the `negotiate` callable mints
    a fresh token per request (single-use semantics)."""
    from armada_tpu.rpc.client import _Base

    minted = []

    def mint():
        minted.append(len(minted))
        return f"krb:bot{len(minted)}@R".encode()

    client = _Base.__new__(_Base)
    client._static_meta = []
    client._negotiate = mint
    a = _krb_chain()
    for expect in ("bot1", "bot2"):
        meta = dict(client._meta)
        assert a.authenticate(meta).name == expect  # fresh token each call


def test_kerberos_config_requires_gssapi():
    """auth.kerberos without python-gssapi must fail LOUDLY at boot, never
    silently authenticate nothing."""
    from armada_tpu.server.authn import authn_from_config

    try:
        import gssapi  # noqa: F401

        pytest.skip("gssapi installed; the real backend is available")
    except ImportError:
        pass
    with pytest.raises(ValueError, match="gssapi"):
        authn_from_config({"kerberos": {"keytab": "/etc/krb5.keytab"}})


def test_kerberos_concurrent_replay_single_winner():
    """N parallel presentations of the SAME token: exactly one wins (the
    check-then-set is atomic; gRPC serves from a 16-thread pool)."""
    import threading

    from armada_tpu.server.authn import AuthenticationError

    a = _krb_chain()
    header = _negotiate(b"krb:alice@X")
    results = []

    def attempt():
        try:
            a.authenticate(header)
            results.append("ok")
        except AuthenticationError:
            results.append("replay")

    threads = [threading.Thread(target=attempt) for _ in range(12)]
    barrier_free = threads  # start together-ish
    for t in barrier_free:
        t.start()
    for t in barrier_free:
        t.join()
    assert results.count("ok") == 1 and results.count("replay") == 11


def test_kerberos_garbage_never_grows_replay_cache():
    """Unauthenticated garbage must not populate the cache (unbounded
    growth at request rate), and a transient validator failure must not
    burn a valid token."""
    from armada_tpu.server.authn import (
        AuthenticationError,
        KerberosAuthenticator,
    )

    flaky = [True]

    def validator(token: bytes) -> str:
        if not token.startswith(b"krb:"):
            raise ValueError("garbage")
        if flaky[0]:
            flaky[0] = False
            raise OSError("KDC unreachable")
        return token[4:].decode()

    a = KerberosAuthenticator(validator=validator)
    for i in range(50):
        with pytest.raises(AuthenticationError):
            a.authenticate(_negotiate(b"garbage-%d" % i))
    assert not a._seen  # nothing recorded for rejected tokens
    header = _negotiate(b"krb:alice@X")
    with pytest.raises(AuthenticationError, match="KDC unreachable"):
        a.authenticate(header)
    # the transient failure did not burn it: the retry succeeds
    assert a.authenticate(header).name == "alice"


def test_kerberos_scheme_is_case_insensitive():
    import base64

    a = _krb_chain()
    tok = base64.b64encode(b"krb:alice@X").decode()
    assert a.authenticate({"authorization": f"negotiate {tok}"}).name == "alice"
