"""ExecutorService: the agent's reconcile loops.

Equivalent of the reference's executor task loops (internal/executor/
application.go setupExecutorApiComponents + service/):
  * lease_cycle  = job_requester.go RequestJobsRuns + lease_requester.go
    LeaseJobRuns + cluster_allocation.go AllocateSpareClusterCapacity: report
    the cluster snapshot, receive new runs / runs-to-stop, submit/delete pods.
  * report_cycle = job_state_reporter.go: diff pod phases against what was
    already reported and publish the transitions as events.
  * cleanup      = resource_cleanup.go: forget reported terminal pods.

The api handle is anything with lease_job_runs/report_events -- the in-process
ExecutorApi or a gRPC client stub.
"""

from __future__ import annotations

import logging
import time
from typing import Callable, Optional

from armada_tpu.core.resources import ResourceListFactory
from armada_tpu.events import events_pb2 as pb
from armada_tpu.events.convert import job_spec_from_proto
from armada_tpu.executor.cluster import ClusterContext, PodPhase
from armada_tpu.scheduler.api import LeaseRequest, LeaseResponse
from armada_tpu.scheduler.executors import ExecutorSnapshot

# Phase -> the one event kind it is reported as.
_TERMINAL = (PodPhase.SUCCEEDED, PodPhase.FAILED)


class ExecutorService:
    def __init__(
        self,
        executor_id: str,
        pool: str,
        cluster: ClusterContext,
        api,
        factory: ResourceListFactory,
        clock: Callable[[], float] = time.time,
        pending_timeout_s: float = 600.0,
        pod_check_rules: tuple = (),
        failed_pod_checker=None,
        submit_brake: Optional[Callable[[], Optional[str]]] = None,
    ):
        """pending_timeout_s: pods stuck PENDING this long are returned for
        rescheduling (podchecks' stuck-pod detection,
        internal/executor/podchecks/pod_checks.go); <= 0 disables.
        pod_check_rules: regex rules over pending pods' diagnostics that can
        retry or fail-fast before the blanket timeout (executor/podchecks.py).
        submit_brake: () -> reason-or-None; a non-None reason pauses NEW pod
        submission for the cycle (cancels/preempts/reports still flow) -- the
        reference's etcd-health brake (common/etcdhealth/etcdhealth.go,
        executor/application.go:63-103 gates allocation on the soft health
        limit).  Wire executor.kubernetes.etcd_health_brake for real
        clusters.  Leases withheld while braked stay leased scheduler-side
        and are re-offered when the brake lifts; a prolonged pause ends in
        the scheduler's unacknowledged-lease expiry reclaiming them."""
        self.id = executor_id
        self.pool = pool
        self.cluster = cluster
        self.api = api
        self._factory = factory
        self._clock = clock
        self._pending_timeout = pending_timeout_s
        self._pod_check_rules = tuple(pod_check_rules)
        # Retryable failed-pod checks (podchecks/failedpodchecks/): None =
        # every pod failure is terminal.
        self._failed_pod_checker = failed_pod_checker
        self._pending_since: dict[str, float] = {}
        # run_id -> last phase reported to the scheduler
        self._reported: dict[str, PodPhase] = {}
        # runs leased to us that we could not start (reported as errors once)
        self._rejected: set[str] = set()
        # Terminal runs whose pods were cleaned up locally but whose terminal
        # event may not have reached the scheduler DB yet: they stay in
        # active_run_ids until the scheduler tells us they're dead
        # (runs_to_cancel), else a lagging ingester would re-lease them.
        self._awaiting_ack: set[str] = set()
        self._submit_brake = submit_brake
        # Last brake reason (None = flowing); exposed for metrics/logs.
        self.brake_reason: Optional[str] = None

    # --- snapshot -----------------------------------------------------------

    def snapshot(self) -> ExecutorSnapshot:
        node_of_run = {
            p.run_id: p.node_id
            for p in self.cluster.pod_states()
            if p.phase not in _TERMINAL
        }
        usage = (
            self.cluster.queue_usage()
            if hasattr(self.cluster, "queue_usage")
            else {}
        )
        return ExecutorSnapshot(
            id=self.id,
            pool=self.pool,
            nodes=tuple(self.cluster.node_specs()),
            node_of_run=node_of_run,
            last_update_ns=int(self._clock() * 1e9),
            queue_usage={q: tuple(v) for q, v in usage.items()},
        )

    # --- lease loop (lease_requester.go:51) ---------------------------------

    def lease_cycle(self) -> LeaseResponse:
        active = tuple(p.run_id for p in self.cluster.pod_states()) + tuple(
            self._awaiting_ack
        )
        reason = self._submit_brake() if self._submit_brake is not None else None
        if reason != self.brake_reason:
            logging.getLogger(__name__).warning(
                "executor %s submission brake %s%s",
                self.id,
                "ENGAGED" if reason else "released",
                f": {reason}" if reason else "",
            )
            self.brake_reason = reason
        request = LeaseRequest(
            snapshot=self.snapshot(),
            active_run_ids=active,
            pause_new_leases=reason is not None,
        )
        response = self.api.lease_job_runs(request)

        # Stop dead runs FIRST: a new lease may target the very capacity a
        # cancelled/preempted pod still holds (e.g. home jobs displacing away
        # guests in the same cycle) -- submitting before deleting would bounce
        # the new pod off a full node.
        for run_id in response.runs_to_cancel:
            self.cluster.delete_pod(run_id)
            self._reported.pop(run_id, None)
            # The scheduler knows this run is dead: stop advertising it.
            self._awaiting_ack.discard(run_id)

        preempted: list[pb.EventSequence] = []
        for run_id in response.runs_to_preempt:
            pod = self.cluster.get_pod(run_id)
            self.cluster.delete_pod(run_id)
            self._reported.pop(run_id, None)
            # Same re-lease race as cleanup(): keep advertising the run until
            # the scheduler has ingested the preemption and cancels it.
            self._awaiting_ack.add(run_id)
            if pod is not None:
                ev = pb.Event(
                    created_ns=int(self._clock() * 1e9),
                    job_run_preempted=pb.JobRunPreempted(
                        job_id=pod.job_id, run_id=run_id, reason="preemptRequested"
                    ),
                )
                preempted.append(
                    pb.EventSequence(
                        queue=pod.queue, jobset=pod.jobset, events=[ev]
                    )
                )

        errors: list[pb.EventSequence] = []
        for lease in response.leases:
            if lease.run_id in self._rejected:
                continue
            spec = job_spec_from_proto(
                lease.job_id,
                lease.queue,
                lease.jobset,
                pb.JobSpec.FromString(lease.spec),
                self._factory,
            )
            try:
                # Fault drill (core/faults): an injected pod-submit error
                # must ride the SAME rejection path as a real apiserver
                # refusal -- terminal run error event, suppression in
                # _rejected, no capacity leak.
                from armada_tpu.core import faults

                faults.check("executor_submit")
                self.cluster.submit_pod(
                    lease.run_id,
                    lease.job_id,
                    lease.queue,
                    lease.jobset,
                    spec,
                    lease.node_id,
                )
            except Exception as e:  # noqa: BLE001 - any rejection fails the run
                self._rejected.add(lease.run_id)
                errors.append(
                    _run_error_sequence(
                        lease.queue,
                        lease.jobset,
                        lease.job_id,
                        lease.run_id,
                        reason="podSubmissionRejected",
                        message=str(e),
                        now_ns=int(self._clock() * 1e9),
                    )
                )

        if errors or preempted:
            self.api.report_events(errors + preempted)
        # Rejections resolve once the scheduler stops offering the run -- but
        # a braked cycle withholds offers without the scheduler having
        # stopped, so it must not clear the suppression set (a cleared entry
        # would let a still-leased rejected run resubmit after release,
        # duplicating its terminal error event).
        if not request.pause_new_leases:
            self._rejected &= {l.run_id for l in response.leases}
        return response

    # --- state reporting (job_state_reporter.go) ----------------------------

    def report_cycle(self) -> int:
        """Report phase transitions; returns the number of events sent."""
        now_ns = int(self._clock() * 1e9)
        sequences: list[pb.EventSequence] = []
        for pod in self.cluster.pod_states():
            last = self._reported.get(pod.run_id)
            if pod.phase is last:
                continue
            ev = pb.Event(created_ns=now_ns)
            if pod.phase is PodPhase.PENDING:
                ev.job_run_assigned.job_id = pod.job_id
                ev.job_run_assigned.run_id = pod.run_id
            elif pod.phase is PodPhase.RUNNING:
                ev.job_run_running.job_id = pod.job_id
                ev.job_run_running.run_id = pod.run_id
                ev.job_run_running.node_id = pod.node_id
                # Exposed ports ride along once the pod runs (reference:
                # the executor's StandaloneIngressInfo event; lookout
                # surfaces the addresses).
                net = getattr(self.cluster, "pod_network", None)
                addresses = net(pod.run_id) if net is not None else {}
                if addresses:
                    info = pb.Event(
                        created_ns=now_ns,
                        ingress_info=pb.StandaloneIngressInfo(
                            job_id=pod.job_id,
                            run_id=pod.run_id,
                            addresses={
                                int(p): a for p, a in addresses.items()
                            },
                        ),
                    )
                    self._reported[pod.run_id] = pod.phase
                    sequences.append(
                        pb.EventSequence(
                            queue=pod.queue,
                            jobset=pod.jobset,
                            events=[ev, info],
                        )
                    )
                    continue
            elif pod.phase is PodPhase.SUCCEEDED:
                ev.job_run_succeeded.job_id = pod.job_id
                ev.job_run_succeeded.run_id = pod.run_id
            elif pod.phase is PodPhase.FAILED:
                retryable = (
                    self._failed_pod_checker is not None
                    and self._failed_pod_checker.is_retryable(pod.message)
                )
                sequences.append(
                    _run_error_sequence(
                        pod.queue,
                        pod.jobset,
                        pod.job_id,
                        pod.run_id,
                        reason="podFailedRetryable" if retryable else "podFailed",
                        message=pod.message or "pod failed",
                        now_ns=now_ns,
                        node=pod.node_id,
                        # Retryable infra deaths return the lease so the job
                        # reschedules (failedpodchecks/pod_checks.go).
                        terminal=not retryable,
                        lease_returned=retryable,
                    )
                )
                self._reported[pod.run_id] = pod.phase
                continue
            else:
                continue
            self._reported[pod.run_id] = pod.phase
            sequences.append(
                pb.EventSequence(queue=pod.queue, jobset=pod.jobset, events=[ev])
            )
        if sequences:
            self.api.report_events(sequences)
        return len(sequences)

    # --- cleanup (resource_cleanup.go) --------------------------------------

    def cleanup(self) -> int:
        """Delete pods whose terminal phase has been reported; returns count."""
        n = 0
        for pod in list(self.cluster.pod_states()):
            if (
                pod.phase in _TERMINAL
                and self._reported.get(pod.run_id) is pod.phase
            ):
                self.cluster.delete_pod(pod.run_id)
                self._reported.pop(pod.run_id, None)
                self._awaiting_ack.add(pod.run_id)
                n += 1
        return n

    # --- stuck-pod checks (podchecks/pod_checks.go) -------------------------

    def check_stuck_pods(self) -> int:
        """Apply the configured pending-pod checks, then the blanket stuck-
        PENDING timeout (podchecks/pod_checks.go: rule actions Fail/Retry,
        timeout = the catch-all ACTION_RETRY)."""
        if self._pending_timeout <= 0 and not self._pod_check_rules:
            return 0
        from armada_tpu.executor.podchecks import ACTION_FAIL, evaluate

        now = self._clock()
        acted = 0
        sequences: list[pb.EventSequence] = []
        current = {p.run_id for p in self.cluster.pod_states()}
        # pods deleted by other paths (cancel/preempt) must not leak entries
        self._pending_since = {
            k: v for k, v in self._pending_since.items() if k in current
        }
        for pod in list(self.cluster.pod_states()):
            if pod.phase is not PodPhase.PENDING:
                self._pending_since.pop(pod.run_id, None)
                continue
            since = self._pending_since.setdefault(pod.run_id, now)
            action = evaluate(self._pod_check_rules, pod.message, now - since)
            reason, message = "", ""
            if action is not None:
                reason = (
                    "podCheckFailed" if action == ACTION_FAIL else "podCheckRetry"
                )
                message = f"pod check matched: {pod.message or '(no diagnostics)'}"
            elif (
                self._pending_timeout > 0
                and now - since > self._pending_timeout
            ):
                action = "retry"
                reason = "podStuckPending"
                message = f"pod pending for more than {self._pending_timeout}s"
            if action is None:
                continue
            self.cluster.delete_pod(pod.run_id)
            self._reported.pop(pod.run_id, None)
            self._pending_since.pop(pod.run_id, None)
            self._awaiting_ack.add(pod.run_id)
            sequences.append(
                _run_error_sequence(
                    pod.queue,
                    pod.jobset,
                    pod.job_id,
                    pod.run_id,
                    reason=reason,
                    message=message,
                    now_ns=int(now * 1e9),
                    node=pod.node_id,
                    # Fail = terminal error; Retry = lease returned, the job
                    # reschedules elsewhere.
                    terminal=action == ACTION_FAIL,
                    lease_returned=action != ACTION_FAIL,
                )
            )
            acted += 1
        if sequences:
            self.api.report_events(sequences)
        return acted

    # Accumulators for runs that missed a sample (pod flapped to Unknown)
    # survive this long before being dropped -- the cumulative series must
    # not reset on a transient phase flap.
    _USAGE_RETENTION_S = 900.0

    def utilisation_cycle(self) -> int:
        """Publish per-run usage samples (armadaevents ResourceUtilisation;
        the reference's utilisation reporting task).  Everything comes from
        the cluster context's single pod listing (UsageSample); cumulative
        usage accumulates one sample per observation."""
        samples = (
            self.cluster.usage_samples()
            if hasattr(self.cluster, "usage_samples")
            else ()
        )
        if not hasattr(self, "_usage_cum"):
            # run_id -> [cum atoms list, last_seen wall-clock]
            self._usage_cum = {}
        now = self._clock()
        for run_id, entry in list(self._usage_cum.items()):
            if now - entry[1] > self._USAGE_RETENTION_S:
                self._usage_cum.pop(run_id, None)
        now_ns = int(now * 1e9)
        names = self._factory.names
        sequences = []
        for s in samples:
            if s.phase != "RUNNING":
                continue
            entry = self._usage_cum.setdefault(
                s.run_id, [[0] * len(s.atoms), now]
            )
            cum = entry[0]
            entry[1] = now
            for i, a in enumerate(s.atoms):
                cum[i] += a
            ev = pb.Event(created_ns=now_ns)
            ev.resource_utilisation.run_id = s.run_id
            ev.resource_utilisation.job_id = s.job_id
            ev.resource_utilisation.node_id = s.node_id
            for i, a in enumerate(s.atoms):
                if a:
                    ev.resource_utilisation.max_resources_for_period.milli[
                        names[i]
                    ] = int(a)
            for i, a in enumerate(cum):
                if a:
                    ev.resource_utilisation.total_cumulative_usage.milli[
                        names[i]
                    ] = int(a)
            sequences.append(
                pb.EventSequence(queue=s.queue, jobset=s.jobset, events=[ev])
            )
        if sequences:
            self.api.report_events(sequences)
        return len(sequences)

    def run_once(self) -> None:
        """One full agent iteration: lease, report, check, clean."""
        self.lease_cycle()
        self.report_cycle()
        self.utilisation_cycle()
        self.check_stuck_pods()
        self.cleanup()


def _run_error_sequence(
    queue: str,
    jobset: str,
    job_id: str,
    run_id: str,
    reason: str,
    message: str,
    now_ns: int,
    node: str = "",
    terminal: bool = True,
    lease_returned: bool = False,
) -> pb.EventSequence:
    return pb.EventSequence(
        queue=queue,
        jobset=jobset,
        events=[
            pb.Event(
                created_ns=now_ns,
                job_run_errors=pb.JobRunErrors(
                    job_id=job_id,
                    run_id=run_id,
                    errors=[
                        pb.Error(
                            reason=reason,
                            message=message,
                            terminal=terminal,
                            lease_returned=lease_returned,
                            node=node,
                        )
                    ],
                ),
            )
        ],
    )
