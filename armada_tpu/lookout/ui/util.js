// Shared helpers + boot config (colors/state order injected by the server).
export const BOOT = JSON.parse(document.getElementById("boot").textContent);
export const COLORS = BOOT.colors;
export const ORDER = BOOT.order;

export const $ = (id) => document.getElementById(id);
export const fmtT = (ns) => ns ? new Date(ns / 1e6).toLocaleString() : "—";
export const esc = (s) => String(s ?? "").replace(/[&<>"]/g,
  (c) => ({"&":"&amp;","<":"&lt;",">":"&gt;",'"':"&quot;"}[c]));

export const dark = () => document.documentElement.dataset.theme === "dark" ||
  (!document.documentElement.dataset.theme &&
   matchMedia("(prefers-color-scheme: dark)").matches);
export const color = (s) => COLORS[dark() ? "dark" : "light"][s] || "#999";

export function meterHTML(states, total) {
  if (!total) return "";
  return ORDER.filter((s) => states[s])
    .map((s) => `<span style="flex:${states[s]};background:${color(s)}"
      title="${s}: ${states[s]}"></span>`).join("");
}
export function chipsHTML(states) {
  return ORDER.filter((s) => states[s]).map((s) =>
    `<span class="chip"><span class="dot" style="background:${color(s)}"></span>` +
    `${s.toLowerCase()} <b>${states[s]}</b></span>`).join("") ||
    '<span class="chip">no jobs yet</span>';
}
export function stateCell(s) {
  return `<span class="dot" style="background:${color(s)}"></span>${s.toLowerCase()}`;
}
