"""Lookout tests: ingestion state machine, filter/group/order queries,
pruning, and the wire surface via armadactl jobs.

Modeled on the reference's lookout repository tests
(internal/lookout/repository/getjobs_test.go, groupjobs_test.go) and
lookoutingester instruction tests.
"""

import pytest

from armada_tpu.ingest.pipeline import IngestionPipeline
from armada_tpu.lookout import (
    JobFilter,
    JobOrder,
    LookoutDb,
    LookoutQueries,
    lookout_converter,
)
from armada_tpu.server import JobSubmitItem, QueueRecord
from tests.control_plane import ControlPlane


@pytest.fixture
def cp(tmp_path):
    plane = ControlPlane.build(tmp_path)
    plane.server.create_queue(QueueRecord("qa", weight=2.0))
    plane.server.create_queue(QueueRecord("qb"))
    # attach a lookout pipeline to the plane's log
    plane.lookoutdb = LookoutDb(":memory:")
    plane.lookout_pipeline = IngestionPipeline(
        plane.log, plane.lookoutdb, lookout_converter, consumer_name="lookout"
    )
    plane.queries = LookoutQueries(plane.lookoutdb)
    yield plane
    plane.lookoutdb.close()
    plane.close()


def lk(cp):
    cp.lookout_pipeline.run_until_caught_up()
    return cp.queries


def item(cpu="2", **kw):
    return JobSubmitItem(resources={"cpu": cpu, "memory": "2"}, **kw)


def test_lifecycle_states_materialize(cp):
    ids = cp.server.submit_jobs(
        "qa", "js1", [item(annotations={"team": "ml", "run": "7"})]
    )
    q = lk(cp)
    (row,) = q.get_jobs()
    assert row["state"] == "QUEUED"
    assert row["annotations"] == {"team": "ml", "run": "7"}
    assert row["cpu_milli"] == 2000

    cp.run_until(lambda: cp.job_states().get(ids[0]) == "succeeded", tick_s=3.0)
    q = lk(cp)
    (row,) = q.get_jobs()
    assert row["state"] == "SUCCEEDED"
    assert row["node"] != ""

    details = q.get_job_details(ids[0])
    assert details is not None
    (run,) = details["runs"]
    assert run["state"] == "SUCCEEDED"
    assert run["leased_ns"] <= run["started_ns"] <= run["finished_ns"]


def test_cancel_and_failure_states(cp):
    ids = cp.server.submit_jobs("qa", "js2", [item(), item(cpu="999")])
    cp.run_until(lambda: cp.job_states().get(ids[0]) == "leased")
    cp.server.cancel_jobs("qa", "js2", [ids[0]])
    cp.run_until(lambda: cp.job_states().get(ids[0]) == "cancelled")
    q = lk(cp)
    by_id = {j["job_id"]: j for j in q.get_jobs()}
    assert by_id[ids[0]]["state"] == "CANCELLED"
    # unschedulably large: rejected by the submit checker with a reason
    assert by_id[ids[1]]["state"] == "FAILED"
    assert "unschedulable" in by_id[ids[1]]["error"]


def test_filters_order_pagination(cp):
    cp.server.submit_jobs("qa", "alpha", [item() for _ in range(3)])
    cp.server.submit_jobs("qb", "beta", [item() for _ in range(2)])
    q = lk(cp)

    assert q.count_jobs() == 5
    assert q.count_jobs([JobFilter("queue", "qa")]) == 3
    assert q.count_jobs([JobFilter("jobset", "bet", match="startsWith")]) == 2
    assert q.count_jobs([JobFilter("queue", ["qa", "qb"], match="in")]) == 5
    assert q.count_jobs([JobFilter("queue", "qa", match="notEqual")]) == 2

    page1 = q.get_jobs(order=JobOrder("job_id"), take=3)
    page2 = q.get_jobs(order=JobOrder("job_id"), skip=3, take=3)
    assert len(page1) == 3 and len(page2) == 2
    all_ids = [j["job_id"] for j in page1 + page2]
    assert all_ids == sorted(all_ids)

    desc = q.get_jobs(order=JobOrder("job_id", "DESC"), take=5)
    assert [j["job_id"] for j in desc] == sorted(all_ids, reverse=True)

    with pytest.raises(ValueError):
        q.get_jobs([JobFilter("password", "x")])


def test_group_jobs(cp):
    cp.server.submit_jobs("qa", "g1", [item() for _ in range(3)])
    cp.server.submit_jobs("qb", "g2", [item() for _ in range(1)])
    q = lk(cp)
    groups = q.group_jobs("queue")
    assert groups[0]["group"] == "qa" and groups[0]["count"] == 3
    assert groups[0]["states"]["QUEUED"] == 3
    groups = q.group_jobs("state")
    assert groups[0]["group"] == "QUEUED" and groups[0]["count"] == 4


def test_annotation_filter(cp):
    cp.server.submit_jobs("qa", "ann", [item(annotations={"team": "ml"})])
    cp.server.submit_jobs("qa", "ann", [item(annotations={"team": "infra"})])
    q = lk(cp)
    rows = q.get_jobs([JobFilter("annotation", "ml", annotation_key="team")])
    assert len(rows) == 1 and rows[0]["annotations"]["team"] == "ml"


def test_prune_terminal_jobs(cp):
    ids = cp.server.submit_jobs("qa", "old", [item()])
    cp.run_until(lambda: cp.job_states().get(ids[0]) == "succeeded", tick_s=3.0)
    q = lk(cp)
    (row,) = q.get_jobs()
    now_ns = row["last_transition_ns"]
    assert cp.lookoutdb.prune(now_ns + int(10e9), keep_terminal_s=60.0) == 0
    assert cp.lookoutdb.prune(now_ns + int(120e9), keep_terminal_s=60.0) == 1
    assert q.get_jobs() == []
    assert q.get_job_details(ids[0]) is None


def test_jobs_cli_over_wire(cp, capsys):
    from armada_tpu.cli.armadactl import main
    from armada_tpu.rpc.server import make_server

    ids = cp.server.submit_jobs("qa", "cli", [item(), item()])
    lk(cp)
    server, port = make_server(lookout_queries=cp.queries)
    try:
        assert main(["--url", f"127.0.0.1:{port}", "jobs", "--queue", "qa"]) == 0
        out = capsys.readouterr().out
        assert ids[0] in out and "QUEUED" in out
        assert main(["--url", f"127.0.0.1:{port}", "jobs", "--group-by", "state"]) == 0
        out = capsys.readouterr().out
        assert "QUEUED" in out and "2" in out
        assert main(["--url", f"127.0.0.1:{port}", "describe-job", ids[0]]) == 0
        out = capsys.readouterr().out
        assert "state: QUEUED" in out
    finally:
        server.stop(None)


def test_annotation_match_modes(cp):
    """Annotation filters carry the full match-mode set
    (querybuilder.go:320-346: exact / startsWith / contains / exists)."""
    cp.server.submit_jobs("qa", "ann2", [item(annotations={"stage": "training-7"})])
    cp.server.submit_jobs("qa", "ann2", [item(annotations={"stage": "eval-7"})])
    cp.server.submit_jobs("qa", "ann2", [item(annotations={"other": "x"})])
    q = lk(cp)
    ann = lambda v, m: JobFilter("annotation", v, m, annotation_key="stage")
    assert len(q.get_jobs([ann("training-7", "exact")])) == 1
    assert len(q.get_jobs([ann("training", "startsWith")])) == 1
    assert len(q.get_jobs([ann("-7", "contains")])) == 2
    assert len(q.get_jobs([ann(None, "exists")])) == 2
    assert len(q.get_jobs([ann(["training-7", "eval-7"], "in")])) == 2
    # exists is annotation-only
    with pytest.raises(ValueError):
        q.get_jobs([JobFilter("queue", None, "exists")])


def test_group_by_annotation(cp):
    """Grouping by an annotation key implies an exists filter so jobs
    without the key never form a null group (querybuilder.go:206-213)."""
    cp.server.submit_jobs("qa", "g3", [item(annotations={"team": "ml"})] * 2)
    cp.server.submit_jobs("qa", "g3", [item(annotations={"team": "infra"})])
    cp.server.submit_jobs("qa", "g3", [item()])  # no team annotation
    q = lk(cp)
    groups = q.group_jobs("annotation", annotation_key="team")
    assert [(g["group"], g["count"]) for g in groups] == [("ml", 2), ("infra", 1)]


def test_group_aggregates(cp):
    """Requestable aggregates (tables.go:110-114 groupAggregates: min
    submitted, avg lastTransitionTime, state counts) plus per-group resource
    sums."""
    cp.server.submit_jobs("qa", "g4", [item(cpu="2"), item(cpu="3")])
    cp.server.submit_jobs("qb", "g4", [item(cpu="1")])
    q = lk(cp)
    groups = q.group_jobs(
        "queue", aggregates=("state", "submitted", "cpu_milli", "memory")
    )
    by_q = {g["group"]: g for g in groups}
    assert by_q["qa"]["count"] == 2
    assert by_q["qa"]["cpu_milli"] == 5000.0
    # memory rides the same milli-unit encoding the ingester stores
    assert by_q["qa"]["memory"] == 4000.0
    assert by_q["qb"]["cpu_milli"] == 1000.0
    assert by_q["qa"]["submitted"] > 0
    assert by_q["qa"]["states"]["QUEUED"] == 2
    with pytest.raises(ValueError):
        q.group_jobs("queue", aggregates=("bogus",))


def test_group_aggregates_over_wire_and_webui(cp):
    """The new group options ride the gRPC Lookout surface and the webui
    query params."""
    import json
    import urllib.request

    from armada_tpu.lookout.webui import LookoutWebUI

    cp.server.submit_jobs("qa", "g5", [item(annotations={"team": "ml"})])
    cp.server.submit_jobs("qa", "g5", [item(annotations={"team": "ml"})])
    q = lk(cp)
    ui = LookoutWebUI(q, port=0)
    try:
        url = (
            f"http://127.0.0.1:{ui.port}/api/groups?by=annotation&key=team"
            "&aggs=state,cpu_milli&take=10"
        )
        with urllib.request.urlopen(url) as resp:
            data = json.loads(resp.read())
        assert data["groups"][0]["group"] == "ml"
        assert data["groups"][0]["count"] == 2
        assert data["groups"][0]["cpu_milli"] == 4000.0
        # annotation filter on the jobs listing
        url2 = (
            f"http://127.0.0.1:{ui.port}/api/jobs?ann.team=ml&take=10"
        )
        with urllib.request.urlopen(url2) as resp:
            data2 = json.loads(resp.read())
        assert data2["total"] == 2
        url3 = f"http://127.0.0.1:{ui.port}/api/jobs?ann.team=*&take=10"
        with urllib.request.urlopen(url3) as resp:
            data3 = json.loads(resp.read())
        assert data3["total"] == 2
    finally:
        ui.stop()


def test_run_usage_flows_to_lookout(cp):
    """Executors publish ResourceUtilisation samples (armadaevents oneof 17)
    and lookout surfaces them on the run row."""
    import json as _json

    ids = cp.server.submit_jobs("qa", "usage", [item(cpu="2")])
    cp.run_until(
        lambda: cp.job_states().get(ids[0]) in ("running", "succeeded")
    )
    # one more executor pass publishes a utilisation sample if the pod is
    # still running; run a few ticks to be safe
    for _ in range(3):
        for ex in cp.executors:
            ex.run_once()
    q = lk(cp)
    details = q.get_job_details(ids[0])
    assert details is not None and details["runs"]
    usages = [r.get("usage_json") for r in details["runs"] if r.get("usage_json")]
    if usages:  # the pod may have finished before a sample landed
        u = _json.loads(usages[0])
        assert u["max"].get("cpu", 0) > 0
        assert u["cumulative"].get("cpu", 0) >= u["max"].get("cpu", 0)
    else:
        # deterministic path: force a sample while running
        ids2 = cp.server.submit_jobs("qa", "usage", [item(cpu="1")])
        cp.run_until(lambda: cp.job_states().get(ids2[0]) == "running")
        for ex in cp.executors:
            ex.run_once()
        q2 = lk(cp)
        details2 = q2.get_job_details(ids2[0])
        usages2 = [
            r.get("usage_json") for r in details2["runs"] if r.get("usage_json")
        ]
        assert usages2, "no utilisation sample reached lookout"
