"""TestRunner: submit a spec, watch events, assert per-job sequences.

Equivalent of the reference's internal/testsuite engine: the submitter posts
the spec's jobs under a fresh jobset, the eventwatcher consumes the jobset
stream, and each job must exhibit the expected event kinds as an ordered
subsequence before the timeout (eventwatcher.go); per-event latency
percentiles come from the eventbenchmark package.
"""

from __future__ import annotations

import dataclasses
import time
import uuid

from armada_tpu.testsuite.spec import EVENT_NAMES, TestSpec


@dataclasses.dataclass
class TestResult:
    spec: TestSpec
    passed: bool
    duration_s: float
    jobset: str
    failures: list  # [str] human-readable reasons
    events_by_job: dict  # job_id -> [(kind, created_ns)]
    latency_by_event: dict  # expected-event name -> seconds from submit (max)

    def summary(self) -> str:
        status = "PASS" if self.passed else "FAIL"
        lines = [
            f"{status} {self.spec.name} ({len(self.events_by_job)} jobs, "
            f"{self.duration_s:.1f}s)"
        ]
        for name, latency in self.latency_by_event.items():
            lines.append(f"  {name:<12} last at +{latency:.2f}s")
        lines.extend(f"  !! {f}" for f in self.failures)
        return "\n".join(lines)


class TestRunner:
    """Runs TestSpecs against any client with submit/cancel/watch (the gRPC
    ArmadaClient or the in-process SubmitServer+EventApi pair via a shim)."""

    def __init__(self, client, clock=time.time):
        self._client = client
        self._clock = clock

    def run(self, spec: TestSpec) -> TestResult:
        jobset = f"testsuite-{uuid.uuid4().hex[:10]}"
        start = self._clock()

        if self._client.get_queue_or_none(spec.queue) is None:
            self._client.create_queue(spec.queue, spec.queue_weight)

        job_ids = self._client.submit_jobs(spec.queue, jobset, list(spec.jobs))
        if spec.cancel == "byId":
            self._client.cancel_jobs(spec.queue, jobset, job_ids)
        elif spec.cancel == "bySet":
            self._client.cancel_jobset(spec.queue, jobset)

        expected_kinds = [EVENT_NAMES[e] for e in spec.expected_events]
        events_by_job: dict = {jid: [] for jid in job_ids}
        pending = set(job_ids)
        submit_ns: dict = {}
        latency: dict = {}
        deadline = start + spec.timeout_s

        # Keep (re-)watching from the cursor until everything is seen or the
        # deadline passes: a single stream may idle out during a long run.
        next_idx = 0
        while pending and self._clock() < deadline:
            for item in self._client.watch_events(
                spec.queue, jobset, from_idx=next_idx
            ):
                next_idx = item.idx + 1
                for ev in item.sequence.events:
                    kind = ev.WhichOneof("event")
                    body = getattr(ev, kind)
                    job_id = getattr(body, "job_id", "")
                    if job_id not in events_by_job:
                        continue
                    if kind == "job_errors" and not any(
                        e.terminal for e in body.errors
                    ):
                        continue  # non-terminal error noise
                    events_by_job[job_id].append((kind, ev.created_ns))
                    if kind == "submit_job":
                        submit_ns[job_id] = ev.created_ns
                for jid in list(pending):
                    if _is_subsequence(
                        expected_kinds, [k for k, _ in events_by_job[jid]]
                    ):
                        pending.discard(jid)
                        for name, k in zip(spec.expected_events, expected_kinds):
                            t = next(
                                (ns for kk, ns in events_by_job[jid] if kk == k),
                                None,
                            )
                            if t is not None and jid in submit_ns:
                                dt = (t - submit_ns[jid]) / 1e9
                                latency[name] = max(latency.get(name, 0.0), dt)
                if not pending or self._clock() > deadline:
                    break

        failures = []
        for jid in sorted(pending):
            seen = [k for k, _ in events_by_job[jid]]
            failures.append(
                f"job {jid}: expected {expected_kinds}, saw {seen} "
                f"within {spec.timeout_s}s"
            )
        return TestResult(
            spec=spec,
            passed=not failures,
            duration_s=self._clock() - start,
            jobset=jobset,
            failures=failures,
            events_by_job=events_by_job,
            latency_by_event=latency,
        )


def _is_subsequence(needle: list, haystack: list) -> bool:
    it = iter(haystack)
    return all(k in it for k in needle)


class GrpcSuiteClient:
    """Adapter giving TestRunner its minimal surface over ArmadaClient."""

    def __init__(self, client):
        self._c = client

    def get_queue_or_none(self, name):
        import grpc

        try:
            return self._c.get_queue(name)
        except grpc.RpcError as e:
            if e.code() == grpc.StatusCode.NOT_FOUND:
                return None
            raise

    def create_queue(self, name, weight):
        from armada_tpu.server.queues import QueueRecord

        self._c.create_queue(QueueRecord(name, weight=weight))

    def submit_jobs(self, queue, jobset, items):
        return self._c.submit_jobs(queue, jobset, items)

    def cancel_jobs(self, queue, jobset, job_ids):
        self._c.cancel_jobs(queue, jobset, job_ids)

    def cancel_jobset(self, queue, jobset):
        self._c.cancel_jobset(queue, jobset)

    def watch_events(self, queue, jobset, from_idx=0):
        return self._c.watch(queue, jobset, from_idx=from_idx, idle_timeout_s=2.0)
