# Fixture for rule `fixed-sleep-retry`.
import time

from armada_tpu.core.backoff import Backoff


def reconnect(connect, poll_interval_s):
    while True:
        try:
            return connect()
        except ConnectionError:
            time.sleep(0.5)  # TP


def reconnect_jittered(connect):
    # near-miss: the prescribed fix -- jittered delay from core/backoff
    backoff = Backoff()
    while True:
        try:
            return connect()
        except ConnectionError:
            time.sleep(backoff.next_delay())


def poll(done, poll_interval_s):
    # near-miss: a poll loop (no try/except) may sleep a fixed interval
    while not done():
        time.sleep(poll_interval_s)
