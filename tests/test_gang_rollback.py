"""Gang-txn rollback semantics for split (heterogeneous) gangs.

The reference schedules a gang as one NodeDb transaction
(nodedb.go:347 ScheduleManyWithTxn): if any member fails, the whole txn --
including evictions earlier members caused -- rolls back.  Our kernel splits a
heterogeneous gang into per-key-class sub-gangs, so the equivalents are:

  1. statically impossible gangs (per class OR jointly across classes) are
     pre-killed before the round (build_problem `dead` + `_joint_capacity_ok`,
     gang_scheduler.go:152-227);
  2. runtime-contention failures unwind placed siblings at decode AND re-run
     the round without the doomed gang, so evictions the unwound placement
     caused do not stand (run_scheduling_round rollback loop).
"""

import dataclasses

import numpy as np

from armada_tpu.core.config import PriorityClass, SchedulingConfig
from armada_tpu.core.types import JobSpec, NodeSpec, Queue, RunningJob
from armada_tpu.models import build_problem, run_scheduling_round

CFG = SchedulingConfig(
    shape_bucket=32,
    indexed_node_labels=("rack",),
    priority_classes={
        "low": PriorityClass("low", priority=100, preemptible=True),
        "high": PriorityClass("high", priority=1000, preemptible=False),
    },
    default_priority_class="high",
    # Keep every queue protected: the rollback scenario must exercise
    # urgency preemption by the gang placement, not phase-A fair-share
    # eviction.
    protected_fraction_of_fair_share=10.0,
)
F = CFG.resource_list_factory()


def rnode(nid, rack, cpu="8"):
    return NodeSpec(
        id=nid,
        pool="default",
        labels={"rack": rack},
        total_resources=F.from_mapping({"cpu": cpu, "memory": "32"}),
    )


def job(jid, cpu="8", queue="q", submit_time=0.0, pc="high", **kw):
    return JobSpec(
        id=jid,
        queue=queue,
        priority_class=pc,
        submit_time=submit_time,
        resources=F.from_mapping({"cpu": cpu, "memory": "1"}),
        **kw,
    )


def gang_member(jid, cpu="8", submit_time=1.0, selector=None):
    return job(
        jid,
        cpu=cpu,
        submit_time=submit_time,
        gang_id="g1",
        gang_cardinality=2,
        node_selector=selector or {},
    )


def test_jointly_infeasible_gang_is_prekilled():
    """Two classes individually feasible but jointly infeasible: each wants
    the single node's full capacity (gang_scheduler.go:152-227 discovers
    this by attempting placement; here the Hall-condition check kills it
    before the kernel)."""
    nodes = [rnode("a1", "a", cpu="8")]
    members = [
        gang_member("m1", cpu="8"),
        gang_member("m2", cpu="8", selector={"rack": "a"}),
    ]
    problem, ctx = build_problem(
        CFG, pool="default", nodes=nodes, queues=[Queue("q")], queued_jobs=members
    )
    sub_gangs = [gi for gi in range(ctx.num_real_gangs) if ctx.gang_members[gi]]
    assert len(sub_gangs) == 2, "selector difference must split the gang"
    assert not np.asarray(problem.g_valid)[sub_gangs].any(), (
        "jointly infeasible gang must be dead before the round"
    )
    out = run_scheduling_round(
        CFG, pool="default", nodes=nodes, queues=[Queue("q")], queued_jobs=members
    )
    assert out.scheduled == {}
    assert set(out.failed) == {"m1", "m2"}


def test_jointly_feasible_gang_survives_the_joint_check():
    """Same shape, enough capacity: the joint check must not over-kill."""
    nodes = [rnode("a1", "a", cpu="16")]
    members = [
        gang_member("m1", cpu="8"),
        gang_member("m2", cpu="8", selector={"rack": "a"}),
    ]
    out = run_scheduling_round(
        CFG, pool="default", nodes=nodes, queues=[Queue("q")], queued_jobs=members
    )
    assert set(out.scheduled) == {"m1", "m2"}


def test_joint_check_across_disjoint_node_sets():
    """Classes on disjoint racks don't compete: jointly feasible."""
    nodes = [rnode("a1", "a"), rnode("b1", "b")]
    members = [
        gang_member("m1", cpu="8", selector={"rack": "a"}),
        gang_member("m2", cpu="8", selector={"rack": "b"}),
    ]
    out = run_scheduling_round(
        CFG, pool="default", nodes=nodes, queues=[Queue("q")], queued_jobs=members
    )
    assert set(out.scheduled) == {"m1", "m2"}


def test_unwound_sibling_evictions_roll_back():
    """A split gang fails at RUNTIME contention (statically feasible): the
    placed sibling urgency-preempted a third-party running job.  The unwind
    must roll that eviction back -- no third-party job may be preempted by a
    gang that did not lease (nodedb.go:347: gang = one txn).

    Setup: X (earlier submit, same queue) takes n2 first; m1 places on n1 by
    evicting victim V; m2 then finds n2 full of non-preemptible X and fails.
    """
    nodes = [rnode("n1", "a"), rnode("n2", "b")]
    victim = RunningJob(
        job=job("victim", cpu="8", queue="qv", pc="low"),
        node_id="n1",
        priority=100,
    )
    x = job("x", cpu="8", submit_time=0.0, node_selector={"rack": "b"})
    members = [
        gang_member("m1", submit_time=1.0, selector={"rack": "a"}),
        gang_member("m2", submit_time=2.0, selector={"rack": "b"}),
    ]
    # Sanity: the gang is NOT statically dead (n1 fits m1, n2 fits m2).
    problem, ctx = build_problem(
        CFG,
        pool="default",
        nodes=nodes,
        queues=[Queue("q"), Queue("qv")],
        queued_jobs=[x] + members,
        running=[victim],
    )
    sub_gangs = [
        gi
        for gi in range(ctx.num_real_gangs)
        if any(m.startswith("m") for m in ctx.gang_members[gi])
    ]
    assert len(sub_gangs) == 2
    assert np.asarray(problem.g_valid)[sub_gangs].all()

    out = run_scheduling_round(
        CFG,
        pool="default",
        nodes=nodes,
        queues=[Queue("q"), Queue("qv")],
        queued_jobs=[x] + members,
        running=[victim],
    )
    assert out.scheduled == {"x": "n2"}
    assert set(out.failed) >= {"m1", "m2"}
    assert out.preempted == [], (
        "eviction caused by the unwound sibling must be rolled back"
    )
    assert not out.unwound_groups, "final outcome must be rollback-clean"


def test_half_running_gang_requeue_keeps_eviction_rollback():
    """The rollback loop terminates and keeps scheduling everything else:
    a queue full of singles around the doomed gang still schedules."""
    nodes = [rnode("n1", "a"), rnode("n2", "b", cpu="32")]
    victim = RunningJob(
        job=job("victim", cpu="8", queue="qv", pc="low"),
        node_id="n1",
        priority=100,
    )
    singles = [
        job(f"s{i}", cpu="4", submit_time=0.0) for i in range(4)
    ]
    x = job("x", cpu="16", submit_time=0.5, node_selector={"rack": "b"})
    members = [
        gang_member("m1", submit_time=1.0, selector={"rack": "a"}),
        gang_member("m2", submit_time=2.0, selector={"rack": "b"}),
    ]
    out = run_scheduling_round(
        CFG,
        pool="default",
        nodes=nodes,
        queues=[Queue("q"), Queue("qv")],
        queued_jobs=singles + [x] + members,
        running=[victim],
    )
    # n2 (32 cpu): 4 singles (16) + x (16) fill it; m2 has no room; m1's
    # eviction of victim rolls back.
    assert set(out.scheduled) == {"s0", "s1", "s2", "s3", "x"}
    assert out.preempted == []
