"""Pool-parallel serving (round 17): parallel/stacked cycle == serial loop.

The non-negotiable contract: arming ARMADA_POOL_PARALLEL changes NOTHING
about decisions, event order, or mirror state -- the dispatch/fetch split
only reorders asynchronous device enqueues, and stacked launches are
jax.vmap lanes whose while_loop batching is bit-exact per lane.  Pinned
here:

1. *Multi-pool churn equality*: the same seeded submit/cancel/reprioritise
   /gang/preemption stream driven through P in {2, 4, 8} pool-restricted
   tenants yields identical per-cycle decisions, apply order (the event
   order), and final JobDb state with pool-parallel armed vs the serial
   loop -- both assemble modes, with verify armed, commit_k in {1, 8}.
2. *Certification fallback*: a cycle that cannot certify pool
   independence (a multi-pool job queued, binding rate-limiter tokens)
   runs the serial order -- and stays bit-equal (the ledger shows the
   fallback, scheduler/pool_serving.py).
3. *Verification blast radius*: a RoundVerificationError in ONE pool's
   round walks the failover ladder for that pool alone -- its re-run is
   bit-equal, the other pools' decisions are untouched, exactly one
   fallback is recorded, and the quarantine scoreboard gets the strike.
"""

from __future__ import annotations

import dataclasses
import os

import numpy as np
import pytest

from armada_tpu.core import faults
from armada_tpu.core import watchdog
from armada_tpu.core.config import PoolConfig, PriorityClass, SchedulingConfig
from armada_tpu.core.types import JobSpec, NodeSpec, Queue
from armada_tpu.jobdb.job import Job
from armada_tpu.jobdb.jobdb import JobDb
from armada_tpu.models.verify import reset_verify_state
from armada_tpu.scheduler.algo import FairSchedulingAlgo
from armada_tpu.scheduler.executors import ExecutorSnapshot
from armada_tpu.scheduler.incremental_algo import IncrementalProblemFeed
from armada_tpu.scheduler.pool_serving import (
    pool_serving_stats,
    reset_pool_serving_stats,
)
from armada_tpu.scheduler.quarantine import reset_device_quarantine

NOW_NS = 1_000_000_000_000


@pytest.fixture(autouse=True)
def _fresh_state(monkeypatch):
    monkeypatch.delenv("ARMADA_POOL_PARALLEL", raising=False)
    monkeypatch.delenv("ARMADA_FAULT", raising=False)
    faults.reset_counters()
    reset_verify_state()
    reset_device_quarantine()
    reset_pool_serving_stats()
    watchdog.reset_supervisor()
    yield
    faults.reset_counters()
    reset_verify_state()
    reset_device_quarantine()
    reset_pool_serving_stats()
    watchdog.reset_supervisor()


def make_config(npools: int, incremental: bool, unlimited: bool = True):
    kw = {}
    if unlimited:
        # unlimited buckets: the frozen test clock never refills, so armed
        # defaults would drain mid-scenario and turn the equality run into
        # a nothing-schedules run (the certification-fallback test keeps
        # them armed deliberately)
        kw.update(
            maximum_scheduling_rate=0.0,
            maximum_per_queue_scheduling_rate=0.0,
        )
    return SchedulingConfig(
        shape_bucket=32,
        priority_classes={
            "low": PriorityClass("low", priority=100, preemptible=True),
            "high": PriorityClass("high", priority=1000, preemptible=False),
        },
        default_priority_class="high",
        maximum_scheduling_burst=1_000,
        incremental_problem_build=incremental,
        pools=tuple(PoolConfig(f"p{i}") for i in range(npools)),
        **kw,
    )


class MultiPoolWorld:
    """JobDb + feed + algo over P pool-restricted tenants, driven by a
    seeded churn script (submits with gangs, cancels, reprioritises; the
    capacity squeeze makes later high-priority submits preempt low ones)."""

    def __init__(self, npools: int, incremental: bool, seed: int,
                 unlimited: bool = True, multi_pool_job: bool = False):
        self.cfg = make_config(npools, incremental, unlimited)
        self.F = self.cfg.resource_list_factory()
        self.npools = npools
        self.jdb = JobDb(self.cfg)
        self.feed = None
        if incremental:
            self.feed = IncrementalProblemFeed(self.cfg)
            self.feed.attach(self.jdb)
        self.rng = np.random.default_rng(seed)
        self.seq = 0
        self.live: list = []
        self.multi_pool_job = multi_pool_job
        self.executors = [
            ExecutorSnapshot(
                id=f"ex{p}",
                pool=f"p{p}",
                last_update_ns=NOW_NS,
                nodes=tuple(
                    NodeSpec(
                        id=f"n{p}-{k}",
                        pool=f"p{p}",
                        total_resources=self.F.from_mapping(
                            {"cpu": "8", "memory": "32"}
                        ),
                    )
                    for k in range(3)
                ),
            )
            for p in range(npools)
        ]
        self.algo = FairSchedulingAlgo(
            self.cfg,
            queues=lambda: [Queue(f"q{i}", 1.0 + i) for i in range(3)],
            clock_ns=lambda: NOW_NS,
            feed=self.feed,
        )

    def _submit(self, txn, n: int, pc: str, gang_every: int = 0):
        for _ in range(n):
            i = self.seq
            self.seq += 1
            pool = f"p{i % self.npools}"
            pools = (pool,)
            if self.multi_pool_job and i == 7 and self.npools >= 2:
                pools = ("p0", "p1")  # breaks the independence certification
            gang_id = ""
            card = 0
            if gang_every and i % gang_every == 0:
                gang_id = f"g{i}"
                card = 2
            spec = JobSpec(
                id=f"j{i:05d}",
                queue=f"q{int(self.rng.integers(0, 3))}",
                priority_class=pc,
                submit_time=float(i),
                pools=pools,
                gang_id=gang_id,
                gang_cardinality=card,
                resources=self.F.from_mapping(
                    {
                        "cpu": str(1 + int(self.rng.integers(0, 3))),
                        "memory": "1",
                    }
                ),
            )
            txn.upsert(Job(spec=spec, queued=True, validated=True, pools=pools))
            self.live.append(spec.id)
            if card:
                # gang sibling, same pool/queue
                sib = dataclasses.replace(spec, id=f"{spec.id}s")
                txn.upsert(
                    Job(spec=sib, queued=True, validated=True, pools=pools)
                )
                self.live.append(sib.id)

    def run(self, cycles: int = 4):
        """Seeded churn; returns (per-cycle ordered decisions, final state)."""
        out = []
        for c in range(cycles):
            txn = self.jdb.write_txn()
            # churn: fill with preemptible work first, then high-priority
            # arrivals that must preempt; sprinkle cancels/reprioritises
            self._submit(
                txn,
                14 if c == 0 else 6,
                "low" if c < 2 else "high",
                gang_every=5,
            )
            if c >= 1 and len(self.live) > 4:
                for jid in self.live[2:4]:
                    job = txn.get(jid)
                    if job is not None and job.queued:
                        txn.upsert(dataclasses.replace(job, cancelled=True))
                jid = self.live[4]
                job = txn.get(jid)
                if job is not None and job.queued and not job.in_terminal_state():
                    txn.upsert(dataclasses.replace(job, priority=5000 + c))
            result = self.algo.schedule(txn, self.executors, NOW_NS)
            # event order == apply order: the per-pool sequence of
            # PoolStats AND the per-pool ordered decision lists
            out.append(
                (
                    [
                        (
                            ps.pool,
                            sorted(ps.outcome.scheduled.items()),
                            sorted(ps.outcome.preempted),
                        )
                        for ps in result.pools
                    ],
                    [(job.id, run.node_id) for job, run in result.scheduled],
                    sorted(job.id for job, _ in result.preempted),
                )
            )
            txn.commit()
        final = sorted(
            (
                j.id,
                j.queued,
                j.in_terminal_state(),
                None if j.latest_run is None else j.latest_run.node_id,
            )
            for j in self.jdb.read_txn().all_jobs()
        )
        return out, final


def run_scenario(parallel, *, npools=4, incremental=True, seed=0,
                 verify=False, unlimited=True, multi_pool_job=False,
                 monkeypatch=None):
    monkeypatch.setenv("ARMADA_POOL_PARALLEL", "1" if parallel else "0")
    monkeypatch.setenv("ARMADA_VERIFY", "1" if verify else "0")
    world = MultiPoolWorld(
        npools, incremental, seed, unlimited=unlimited,
        multi_pool_job=multi_pool_job,
    )
    return world.run()


# --- 1. multi-pool churn equality -------------------------------------------


@pytest.mark.parametrize("npools", [2, 4, 8])
@pytest.mark.parametrize("seed", [0, 1, 2])
def test_pool_parallel_bit_equal_over_churn(monkeypatch, npools, seed):
    a = run_scenario(False, npools=npools, seed=seed, monkeypatch=monkeypatch)
    reset_pool_serving_stats()
    b = run_scenario(True, npools=npools, seed=seed, monkeypatch=monkeypatch)
    assert a == b, f"P={npools} seed={seed}: decisions/event order diverged"
    assert any(sched for _pools, sched, _pre in a[0]), "scenario must schedule"
    snap = pool_serving_stats().snapshot()
    assert snap["parallel_cycles"] > 0, "the parallel path never engaged"


def test_pool_parallel_bit_equal_with_verify_and_stacking(monkeypatch):
    """Verify armed end to end: every pool's round is certified (the
    stacked verify pass included) and decisions stay bit-equal; stacked
    launches actually happen."""
    a = run_scenario(False, npools=4, seed=3, verify=True,
                     monkeypatch=monkeypatch)
    reset_pool_serving_stats()
    b = run_scenario(True, npools=4, seed=3, verify=True,
                     monkeypatch=monkeypatch)
    assert a == b
    snap = pool_serving_stats().snapshot()
    assert snap["parallel_cycles"] > 0
    assert snap["stacked_launches"] > 0, "shape-matched pools must stack"
    from armada_tpu.models.verify import verify_state

    assert verify_state().rounds > 0 and verify_state().failures == 0


@pytest.mark.parametrize("commit_k", [1, 8])
def test_pool_parallel_bit_equal_with_commit_k(monkeypatch, commit_k):
    monkeypatch.setenv("ARMADA_COMMIT_K", str(commit_k))
    a = run_scenario(False, npools=4, seed=1, monkeypatch=monkeypatch)
    b = run_scenario(True, npools=4, seed=1, monkeypatch=monkeypatch)
    assert a == b


def test_pool_parallel_legacy_assemble_mode_equal(monkeypatch):
    """Non-incremental (legacy per-cycle build): pool-parallel has no
    incremental feed to certify against -- the flag must degrade to the
    serial order and change nothing."""
    a = run_scenario(False, npools=3, seed=0, incremental=False,
                     monkeypatch=monkeypatch)
    b = run_scenario(True, npools=3, seed=0, incremental=False,
                     monkeypatch=monkeypatch)
    assert a == b
    assert pool_serving_stats().snapshot()["parallel_cycles"] == 0


# --- 2. certification fallback ----------------------------------------------


def test_multi_pool_job_forces_serial_fallback(monkeypatch):
    """One queued job listing two pools makes the cycle order-dependent:
    the certification must fail, the cycle runs serially, decisions equal
    the serial loop exactly."""
    a = run_scenario(False, npools=3, seed=2, multi_pool_job=True,
                     monkeypatch=monkeypatch)
    reset_pool_serving_stats()
    b = run_scenario(True, npools=3, seed=2, multi_pool_job=True,
                     monkeypatch=monkeypatch)
    assert a == b
    snap = pool_serving_stats().snapshot()
    # the cycle with the multi-pool job queued fell back; once it leases,
    # independence is restored and LATER cycles may parallelize again
    assert snap["serial_fallback_cycles"] > 0


def test_binding_rate_limits_force_serial_fallback(monkeypatch):
    """Armed token buckets against the frozen test clock drain and become
    BINDING: the per-window token certification must refuse to overlap,
    and the fallback path hands every pool the exact post-consumption
    tokens the serial loop would have (the re-read after flush)."""
    a = run_scenario(False, npools=3, seed=0, unlimited=False,
                     monkeypatch=monkeypatch)
    reset_pool_serving_stats()
    b = run_scenario(True, npools=3, seed=0, unlimited=False,
                     monkeypatch=monkeypatch)
    assert a == b


def test_feed_independence_tracking():
    """pools_independent() follows the queued-job lifecycle: unrestricted
    and multi-pool jobs break it; leasing/terminating them restores it."""
    cfg = make_config(2, True)
    F = cfg.resource_list_factory()
    jdb = JobDb(cfg)
    feed = IncrementalProblemFeed(cfg)
    feed.attach(jdb)

    def upsert(job):
        txn = jdb.write_txn()
        txn.upsert(job)
        txn.commit()

    spec = JobSpec(
        id="a", queue="q0", priority_class="high", submit_time=0.0,
        pools=("p0",),
        resources=F.from_mapping({"cpu": "1", "memory": "1"}),
    )
    upsert(Job(spec=spec, queued=True, validated=True, pools=("p0",)))
    assert feed.pools_independent()
    # unrestricted job: sits in every builder
    free = dataclasses.replace(spec, id="b", pools=())
    upsert(Job(spec=free, queued=True, validated=True))
    assert not feed.pools_independent()
    upsert(Job(spec=free, queued=True, validated=True, cancelled=True))
    assert feed.pools_independent()
    # multi-pool job: sits in two builders
    both = dataclasses.replace(spec, id="c", pools=("p0", "p1"))
    upsert(Job(spec=both, queued=True, validated=True, pools=("p0", "p1")))
    assert not feed.pools_independent()
    upsert(Job(spec=both, queued=True, validated=True, pools=("p0", "p1"),
               cancelled=True))
    assert feed.pools_independent()


# --- 3. verification blast radius -------------------------------------------


def test_verify_failure_in_one_pool_walks_ladder_alone(monkeypatch):
    """round_corrupt drill against the pool-parallel cycle: the one-shot
    header corruption lands in exactly ONE pool's dispatched round; its
    finish raises RoundVerificationError and re-runs on the CPU rung
    bit-equal, the OTHER pools' decisions commit untouched, exactly one
    fallback is recorded, and the device gets a quarantine strike."""
    from armada_tpu.scheduler.quarantine import device_quarantine

    a = run_scenario(False, npools=4, seed=5, verify=True,
                     monkeypatch=monkeypatch)

    faults.reset_counters()
    reset_pool_serving_stats()
    watchdog.reset_supervisor()
    monkeypatch.setenv("ARMADA_POOL_PARALLEL", "1")
    monkeypatch.setenv("ARMADA_VERIFY", "1")
    monkeypatch.setenv("ARMADA_FAULT", "round_corrupt:header")
    world = MultiPoolWorld(4, True, 5)
    b = world.run()
    monkeypatch.delenv("ARMADA_FAULT")

    assert a == b, "the failed pool's ladder re-run must be bit-equal"
    from armada_tpu.models.verify import verify_state

    assert verify_state().failures == 1
    sup = watchdog.supervisor().snapshot()
    assert sup["fallbacks"] == 1, "exactly the corrupted pool fails over"
    assert sum(
        device_quarantine().snapshot()["strike_totals"].values()
    ) >= 1
