# Fixture for rule `inloop-scatter-gathered-key` (linted under
# armada_tpu/models/).  The twin scatter is syntactically IDENTICAL to
# the true positive; its index is a REDUCED pick (argmin: a fresh scalar,
# not a gathered row) and its base is loop carry state -- the sanctioned
# commit pattern.
import jax
import jax.numpy as jnp


def run(ban_mask, cand_tab, scores, carry0):
    def body(c):
        i, acc, done = c
        cand = cand_tab[i]
        banned = ban_mask.at[cand].set(True)  # TP
        slot = jnp.argmin(scores * acc)
        acc2 = acc.at[slot].set(True)  # twin
        return (i + 1, acc2, done | banned[0])

    return jax.lax.while_loop(lambda c: ~c[2], body, carry0)
