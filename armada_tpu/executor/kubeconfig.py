"""Minimal kubeconfig loader for out-of-cluster executors.

The analog of client-go's clientcmd for the two auth modes the executor's
REST plumbing speaks: bearer tokens and mTLS client certificates (what
kind / admin kubeconfigs ship, ref:e2e/setup/kind.yaml dev flow).  Reads
the current-context's cluster + user, materializing inline base64 data
(certificate-authority-data etc.) into temp files, and returns the kwargs
for KubernetesClusterContext.
"""

from __future__ import annotations

import atexit
import base64
import os
import tempfile
from typing import Optional


def _data_file(b64: str, suffix: str) -> str:
    # delete=False so the ssl/urllib machinery can reopen by path, but the
    # materialized credential (possibly a private key) must not outlive the
    # process -- unlink at exit.
    f = tempfile.NamedTemporaryFile(
        prefix="armada-kubeconfig-", suffix=suffix, delete=False
    )
    f.write(base64.b64decode(b64))
    f.close()
    atexit.register(_unlink_quiet, f.name)
    return f.name


def _unlink_quiet(path: str) -> None:
    try:
        os.unlink(path)
    except OSError:
        pass


def load_kubeconfig(path: Optional[str] = None, context: Optional[str] = None) -> dict:
    """Returns {base_url, token?, ca_file?, client_cert_file?,
    client_key_file?, insecure?} for KubernetesClusterContext(**kw minus
    base_url/factory)."""
    import yaml

    path = path or os.environ.get(
        "KUBECONFIG", os.path.expanduser("~/.kube/config")
    )
    with open(path) as f:
        doc = yaml.safe_load(f) or {}
    ctx_name = context or doc.get("current-context")
    contexts = {e["name"]: e.get("context", {}) for e in doc.get("contexts", ())}
    clusters = {e["name"]: e.get("cluster", {}) for e in doc.get("clusters", ())}
    users = {e["name"]: e.get("user", {}) for e in doc.get("users", ())}
    if ctx_name not in contexts:
        raise ValueError(f"kubeconfig {path}: no context {ctx_name!r}")
    ctx = contexts[ctx_name]
    cluster = clusters.get(ctx.get("cluster"), {})
    user = users.get(ctx.get("user"), {})

    out: dict = {"base_url": cluster.get("server", "")}
    if cluster.get("insecure-skip-tls-verify"):
        out["insecure"] = True
    if cluster.get("certificate-authority"):
        out["ca_file"] = cluster["certificate-authority"]
    elif cluster.get("certificate-authority-data"):
        out["ca_file"] = _data_file(
            cluster["certificate-authority-data"], ".crt"
        )
    if user.get("token"):
        out["token"] = user["token"]
    elif user.get("tokenFile"):
        with open(user["tokenFile"]) as f:
            out["token"] = f.read().strip()
    if user.get("client-certificate"):
        out["client_cert_file"] = user["client-certificate"]
    elif user.get("client-certificate-data"):
        out["client_cert_file"] = _data_file(
            user["client-certificate-data"], ".crt"
        )
    if user.get("client-key"):
        out["client_key_file"] = user["client-key"]
    elif user.get("client-key-data"):
        out["client_key_file"] = _data_file(user["client-key-data"], ".key")
    return out
