"""External gRPC provider services: market bid prices + priority overrides.

The reference's scheduler can consume two OPTIONAL external gRPC services --
bid prices per (queue, price band) for market-driven pools
(internal/scheduler/pricing/bid_price.go + client.go; pkg/bidstore protos)
and per-(pool, queue) fair-share weight overrides
(internal/scheduler/priorityoverride/service_provider.go;
pkg/priorityoverride).  Both follow the same shape: poll the service on an
interval, cache the last good answer atomically, and keep scheduling from
the cache when the API is down (ServiceProvider.Run / fetchOverrides).

This module provides BOTH halves:

  * polling clients implementing the in-process provider protocols
    (scheduler/providers.py BidPriceProvider / PriorityOverrideProvider),
    drop-in for FairSchedulingAlgo's `bid_prices=` / `priority_overrides=`;
  * a host for provider processes (`serve_providers`) so an operator --
    or a test -- can run a price/override source the plane polls.

Wire messages: rpc.proto BidPricesResponse / PriorityOverridesResponse.
"""

from __future__ import annotations

import threading
from typing import Callable, Mapping, Optional

import grpc

from armada_tpu.rpc import rpc_pb2 as pb
from armada_tpu.scheduler.providers import most_specific_bid

_BID_METHOD = "/armada_tpu.api.BidPriceService/GetBidPrices"
_OVERRIDE_METHOD = "/armada_tpu.api.PriorityOverrideService/GetPriorityOverrides"


class ProviderNotReady(Exception):
    """No successful fetch yet (ServiceProvider.Ready() == false).

    Raised by refresh_or_raise() for callers that want startup to block on a
    live provider; the read paths (price()/override()) never raise -- a
    never-answered provider serves "no data" (0 bids / no overrides), so a
    down optional service cannot crash the scheduling cycle."""


class _PollingClient:
    """Poll `fetch` every interval; keep the last good snapshot atomically.

    A fetch failure logs-and-keeps-serving the stale cache, exactly the
    reference's "cache the overrides in memory so that we can continue
    scheduling even if the API is unavailable"."""

    def __init__(
        self,
        address: str,
        method: str,
        response_cls,
        poll_interval_s: float = 30.0,
        channel: Optional[grpc.Channel] = None,
        timeout_s: float = 10.0,
    ):
        if channel is None:
            # Shared transport hardening (rpc/transport.py): caps/keepalive
            # must match the serving side or >4MB responses still break.
            from armada_tpu.rpc.transport import channel_options

            channel = grpc.insecure_channel(address, options=channel_options())
        self._channel = channel
        self._call = self._channel.unary_unary(
            method,
            request_serializer=lambda m: m.SerializeToString(),
            response_deserializer=response_cls.FromString,
        )
        self._interval = poll_interval_s
        self._timeout = timeout_s
        self._snapshot = None  # immutable dict, swapped atomically
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.last_error: Optional[str] = None

    def _decode(self, resp) -> Mapping:
        raise NotImplementedError

    def _request(self):
        raise NotImplementedError

    def refresh(self) -> bool:
        """One fetch; returns True on success.  Called by the poll loop and
        available to tests/cycle hooks for deterministic refreshes."""
        try:
            resp = self._call(self._request(), timeout=self._timeout)
        except grpc.RpcError as e:
            self.last_error = f"{e.code().name}: {e.details()}"
            return False
        self._snapshot = self._decode(resp)
        self.last_error = None
        return True

    def start(self) -> "_PollingClient":
        """Fetch once now, then poll in the background."""
        self.refresh()
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.wait(self._interval):
            self.refresh()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
        self._channel.close()

    def ready(self) -> bool:
        return self._snapshot is not None

    def refresh_or_raise(self) -> None:
        """One fetch, erroring if the provider has still never answered --
        for deployments that want startup to block on provider readiness."""
        if not self.refresh() and self._snapshot is None:
            raise ProviderNotReady(self.last_error or "provider unreachable")


class BidPriceServiceClient(_PollingClient):
    """BidPriceProvider backed by a remote BidPriceService
    (pricing/bid_price.go BidPriceProvider + client.go)."""

    def __init__(self, address: str, **kw):
        super().__init__(address, _BID_METHOD, pb.BidPricesResponse, **kw)

    def _request(self):
        return pb.BidPricesRequest()

    def _decode(self, resp) -> Mapping:
        prices = {}
        for q in resp.queues:
            for b in q.bids:
                prices[(q.queue, b.band, b.pool)] = float(b.price)
        return prices

    def price(self, queue: str, band: str, pool: str = "") -> float:
        """Most specific match wins: (queue, band, pool) > (queue, band, any
        pool) > (queue, default band).  0 = no bid (never scheduled by a
        market pool, market_iterator.go); a never-answered provider bids 0
        for everyone rather than crashing the cycle."""
        snap = self._snapshot
        if snap is None:
            return 0.0
        return most_specific_bid(snap, queue, band, pool)


class PriorityOverrideServiceClient(_PollingClient):
    """PriorityOverrideProvider backed by a remote PriorityOverrideService
    (priorityoverride/service_provider.go)."""

    def __init__(self, address: str, **kw):
        super().__init__(
            address, _OVERRIDE_METHOD, pb.PriorityOverridesResponse, **kw
        )

    def _request(self):
        return pb.PriorityOverridesRequest()

    def _decode(self, resp) -> Mapping:
        return {
            (o.pool, o.queue): float(o.priority) for o in resp.overrides
        }

    def override(self, pool: str, queue: str) -> Optional[float]:
        """None = no override.  A never-answered provider overrides nothing
        (the reference's Override() errors when unready, but its scheduler
        only consumes overrides once Ready(); here the read path is simply
        empty until the first successful fetch -- a down optional service
        must not fail cycles)."""
        snap = self._snapshot
        if snap is None:
            return None
        return snap.get((pool, queue))


# ------------------------------------------------------------- the host ----


def serve_providers(
    bid_prices: Optional[Callable[[], Mapping]] = None,
    priority_overrides: Optional[Callable[[], Mapping]] = None,
    address: str = "127.0.0.1:0",
) -> tuple[grpc.Server, int]:
    """Host BidPriceService / PriorityOverrideService from live sources.

    bid_prices() -> {(queue, band, pool) | (queue, band): price}
    priority_overrides() -> {(pool, queue): weight}

    Sources are called per request, so a mutable dict the operator updates
    becomes visible to the scheduler on its next poll -- which is what the
    e2e test exercises (prices change mid-run, the next cycle reorders).
    """
    from concurrent import futures

    from armada_tpu.rpc.server import server_options

    server = grpc.server(
        futures.ThreadPoolExecutor(max_workers=4), options=server_options()
    )
    handlers = []
    if bid_prices is not None:

        def get_bids(request, context):
            by_queue: dict[str, list] = {}
            for k, price in bid_prices().items():
                queue, band, pool = (k if len(k) == 3 else (*k, ""))
                by_queue.setdefault(queue, []).append(
                    pb.PriceBandBid(band=band, pool=pool, price=float(price))
                )
            return pb.BidPricesResponse(
                queues=[
                    pb.QueueBids(queue=q, bids=bids)
                    for q, bids in sorted(by_queue.items())
                ]
            )

        handlers.append(
            grpc.method_handlers_generic_handler(
                "armada_tpu.api.BidPriceService",
                {
                    "GetBidPrices": grpc.unary_unary_rpc_method_handler(
                        get_bids,
                        request_deserializer=pb.BidPricesRequest.FromString,
                        response_serializer=lambda m: m.SerializeToString(),
                    )
                },
            )
        )
    if priority_overrides is not None:

        def get_overrides(request, context):
            return pb.PriorityOverridesResponse(
                overrides=[
                    pb.PriorityOverride(pool=pool, queue=queue, priority=float(w))
                    for (pool, queue), w in sorted(priority_overrides().items())
                ]
            )

        handlers.append(
            grpc.method_handlers_generic_handler(
                "armada_tpu.api.PriorityOverrideService",
                {
                    "GetPriorityOverrides": grpc.unary_unary_rpc_method_handler(
                        get_overrides,
                        request_deserializer=pb.PriorityOverridesRequest.FromString,
                        response_serializer=lambda m: m.SerializeToString(),
                    )
                },
            )
        )
    server.add_generic_rpc_handlers(tuple(handlers))
    port = server.add_insecure_port(address)
    server.start()
    return server, port
