"""Kubernetes Lease leader election (leader.go:112-186 parity): acquisition,
renewal, failover on expiry, token fencing across takeovers, and optimistic-
concurrency races through the resourceVersion precondition."""

import pytest

from armada_tpu.scheduler.kube_leader import KubernetesLeaseLeaderController
from tests.fake_kube_api import FakeKubeApi


class Clock:
    def __init__(self, t=1_000_000.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


@pytest.fixture
def kube():
    api = FakeKubeApi()
    yield api
    api.stop()


def ctrl(kube, holder, clock, duration=15.0):
    return KubernetesLeaseLeaderController(
        kube.url, holder, lease_duration_s=duration, clock=clock
    )


def test_acquire_renew_and_follow(kube):
    clock = Clock()
    a = ctrl(kube, "replica-a", clock)
    b = ctrl(kube, "replica-b", clock)

    ta = a.get_token()
    assert ta.leader and ta.generation == 1
    assert a.validate_token(ta)

    tb = b.get_token()
    assert not tb.leader
    assert not b.validate_token(tb)

    # a renews within the lease window; generation is stable
    clock.advance(5)
    ta2 = a.get_token()
    assert ta2.leader and ta2.generation == 1


def test_failover_bumps_generation_and_fences_old_leader(kube):
    clock = Clock()
    a = ctrl(kube, "replica-a", clock)
    b = ctrl(kube, "replica-b", clock)
    ta = a.get_token()
    assert ta.leader
    # b observes a's record; expiry is measured from this LOCAL observation
    # (client-go observedTime), so a skewed remote renewTime alone can never
    # trigger takeover
    assert not b.get_token().leader

    # a goes silent past the lease duration; b takes over
    clock.advance(20)
    tb = b.get_token()
    assert tb.leader and tb.generation == 2

    # the old leader's token no longer validates (scheduler.go:263 fencing)
    assert not a.validate_token(ta)
    # and when a comes back it is a follower
    ta2 = a.get_token()
    assert not ta2.leader


def test_takeover_race_has_one_winner(kube):
    """Two replicas observing the same expired lease race the PUT; the
    resourceVersion precondition lets exactly one through (the 409 loser
    stays follower) -- the client-go optimistic-concurrency fence."""
    clock = Clock()
    a = ctrl(kube, "replica-a", clock)
    b = ctrl(kube, "replica-b", clock)
    c = ctrl(kube, "replica-c", clock)
    ta = a.get_token()
    assert ta.leader
    # both challengers observe a's record, then a goes silent past duration
    assert not b.get_token().leader and not c.get_token().leader
    clock.advance(20)

    # simulate the race: both see the stale lease, then both try to update.
    # The fake apiserver serializes; the second one's PUT must lose on
    # resourceVersion/409 and report follower.
    tb = b.get_token()
    tc = c.get_token()
    assert tb.leader ^ tc.leader  # exactly one winner
    winner_gen = (tb if tb.leader else tc).generation
    assert winner_gen == 2


def test_clock_skew_does_not_flap_leadership(kube):
    """A leader whose clock runs 1000s behind writes renewTime stamps that
    look long-expired to a skewed follower; takeover must still only happen
    after the record goes UNCHANGED for a full duration on the follower's
    own clock (round-3 advisor finding)."""
    slow, fast = Clock(1_000_000.0), Clock(1_001_000.0)
    a = ctrl(kube, "replica-a", slow)
    b = ctrl(kube, "replica-b", fast)
    assert a.get_token().leader
    # b sees renewTime 1000s in the past -- renewTime vs local clock would
    # take over immediately; observed-time must not
    assert not b.get_token().leader
    # a keeps renewing: b keeps following indefinitely
    for _ in range(4):
        slow.advance(5)
        fast.advance(5)
        assert a.get_token().leader
        assert not b.get_token().leader
    # a actually dies: b takes over one duration after its last observation
    fast.advance(20)
    assert b.get_token().leader


def test_apiserver_outage_fails_safe_as_follower(kube):
    clock = Clock()
    a = ctrl(kube, "replica-a", clock)
    ta = a.get_token()
    assert ta.leader
    kube.stop()
    # unreachable apiserver: cannot renew, must not claim leadership
    t2 = a.get_token()
    assert not t2.leader
    assert not a.validate_token(ta)


def test_scheduler_runs_on_kube_lease_controller(kube, tmp_path):
    """The controller satisfies the same LeaderController protocol the
    scheduler service consumes: follower replicas sync but do not publish
    (mirrors test_scheduler_service.test_follower_syncs_but_does_not_publish,
    here over the kube Lease)."""
    from tests.test_scheduler_service import World

    clock = Clock()
    leader_ctrl = ctrl(kube, "replica-a", clock)
    follower_ctrl = ctrl(kube, "replica-b", clock)
    # replica-a claims the lease first
    assert leader_ctrl.get_token().leader

    w = World(tmp_path, leader=follower_ctrl)
    try:
        w.submit("job-1")
        w.ingest()
        w.add_executor()
        res = w.scheduler.cycle()
        assert not res.leader and not res.published

        # replica-a dies; replica-b takes over and schedules
        clock.advance(30)
        res2 = w.scheduler.cycle()
        assert res2.leader
    finally:
        w.close()


def test_leader_address_rides_the_lease_annotation(kube):
    """Followers discover the leader's advertised gRPC address from the
    Lease annotation (reports proxying, leader_client.go analog) -- served
    from the election state WITHOUT an apiserver round trip per query."""
    clock = Clock()
    a = KubernetesLeaseLeaderController(
        kube.url, "replica-a", clock=clock, advertised_address="hostA:50051"
    )
    b = KubernetesLeaseLeaderController(
        kube.url, "replica-b", clock=clock, advertised_address="hostB:50052"
    )
    assert b.leader_address() == ""  # no election state observed yet
    assert a.get_token().leader
    # the holder answers None IMMEDIATELY after acquiring (serve locally)
    assert a.leader_address() is None
    assert b.get_token().leader is False
    assert b.leader_address() == "hostA:50051"
    # cached peek: an apiserver outage must not flip answers mid-lease
    kube.stop()
    assert b.leader_address() == "hostA:50051"
    assert a.leader_address() is None


def test_leader_address_follows_takeover(kube):
    clock = Clock()
    a = KubernetesLeaseLeaderController(
        kube.url, "replica-a", clock=clock, advertised_address="hostA:1",
        lease_duration_s=15.0,
    )
    b = KubernetesLeaseLeaderController(
        kube.url, "replica-b", clock=clock, advertised_address="hostB:2",
        lease_duration_s=15.0,
    )
    assert a.get_token().leader
    assert not b.get_token().leader
    # a dies; b (which already observed a's record at its first follow)
    # sees it unrenewed for a full duration and takes over
    clock.advance(16)
    assert b.get_token().leader
    assert b.leader_address() is None
    assert a.get_token().leader is False
    assert a.leader_address() == "hostB:2"
