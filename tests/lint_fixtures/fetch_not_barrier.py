# Fixture for rule `fetch-not-barrier` (linted under armada_tpu/).
import numpy as np


def wait_for_round(result, jax):
    jax.block_until_ready(result)  # TP
    # near-miss: a real device->host scalar fetch is the reliable barrier
    sentinel = np.asarray(result.termination)
    return sentinel
