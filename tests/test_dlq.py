"""Poison-record isolation (round 21): bounded retries, bisecting
dead-letter quarantine, wedge-proof ingest (ingest/dlq.py).

Pins the ISSUE-19 contracts:

- bounded Backoff (max_attempts / deadline_s) is what escalates a retry
  loop to isolation;
- bisection quarantines EXACTLY the deterministic poison record (stage
  tagged decode/convert/render) while every environmental shape
  (all-records-fail, store-down) keeps retry-forever;
- the DLQ insert and the cursor advance share one store transaction: an
  ingest_ack crash between quarantine and ack neither loses the record
  nor double-dead-letters it;
- a poison '$control-plane' record is NEVER auto-skipped -- the consumer
  halts loudly until the operator verdict (discard approves the skip);
- the serving pipelines (serial AND sharded) drain PAST a poison record,
  and `dlq replay` + a suffix drain restores bit-equality with a
  never-poisoned run.

The first two tests are the cheap fast-tier representatives.
"""

from __future__ import annotations

import os
import time

import pytest

from armada_tpu.core.backoff import Backoff
from armada_tpu.eventlog.log import EventLog
from armada_tpu.eventlog.publisher import Publisher
from armada_tpu.events import events_pb2 as pb
from armada_tpu.ingest import dlq
from armada_tpu.ingest.converter import convert_sequences
from armada_tpu.ingest.schedulerdb import SNAPSHOT_TABLES, SchedulerDb

CONSUMER = "scheduler"


@pytest.fixture(autouse=True)
def _clean_dlq_state():
    saved = {
        k: os.environ.get(k)
        for k in ("ARMADA_FAULT", "ARMADA_INGEST_RETRIES")
    }
    dlq.reset_poison()
    dlq.reset_registry()
    yield
    dlq.reset_poison()
    dlq.reset_registry()
    for k, v in saved.items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v


def _seq(jid: str, queue: str = "q1", jobset: str = "js1") -> pb.EventSequence:
    return pb.EventSequence(
        queue=queue,
        jobset=jobset,
        events=[
            pb.Event(
                created_ns=1,
                submit_job=pb.SubmitJob(job_id=jid, spec=pb.JobSpec()),
            )
        ],
    )


def _publish(log, n: int, prefix: str = "job") -> list[str]:
    pub = Publisher(log)
    ids = [f"{prefix}-{i:04d}" for i in range(n)]
    for i, jid in enumerate(ids):
        pub.publish([_seq(jid, queue=f"q{i % 3}", jobset=f"js{i % 2}")])
    return ids


def _poisoning_converter(bad_ids):
    """convert_sequences, but deterministically refuses specific job ids
    (the poison signature: a pure function of the payload bytes)."""

    def conv(seqs):
        for s in seqs:
            for ev in s.events:
                if ev.HasField("submit_job") and ev.submit_job.job_id in bad_ids:
                    raise ValueError(f"poison {ev.submit_job.job_id}")
        return convert_sequences(seqs)

    return conv


def _isolate(log, sink, converter, *, renderer=None, stop_at_control=False):
    positions = {p: 0 for p in range(log.num_partitions)}
    positions.update(sink.positions(CONSUMER))
    return dlq.isolate_batch(
        log_=log,
        sink=sink,
        converter=converter,
        consumer=CONSUMER,
        partitions=list(range(log.num_partitions)),
        positions=positions,
        renderer=renderer,
        stop_at_control=stop_at_control,
    )


def _job_ids(db) -> set:
    return {r[0] for r in db.export_snapshot().get("jobs", [])}


def _caught_up(db, log) -> bool:
    pos = db.positions(CONSUMER)
    return all(
        pos.get(p, 0) >= log.end_offset(p) for p in range(log.num_partitions)
    )


# ---------------------------------------------------------------------------
# fast-tier representatives: the bounded-retry schedule


def test_backoff_max_attempts_bounds_and_reset():
    b = Backoff(base_s=0.01, cap_s=0.02, floor_s=0.001, max_attempts=3)
    assert not b.exhausted()
    for _ in range(3):
        assert not b.exhausted()
        d = b.next_delay()
        assert 0.0 < d <= 0.02
    assert b.exhausted()
    # exhausted is a report, not a gate: further draws stay legal
    b.next_delay()
    assert b.exhausted()
    b.reset()
    assert not b.exhausted()
    # the unbounded default (every pre-existing call site) never exhausts
    u = Backoff(base_s=0.001, cap_s=0.001, floor_s=0.0001)
    for _ in range(50):
        u.next_delay()
    assert not u.exhausted()


def test_backoff_deadline_measured_from_first_draw():
    b = Backoff(base_s=0.001, cap_s=0.001, floor_s=0.0001, deadline_s=3600.0)
    assert not b.exhausted()
    b.next_delay()
    assert not b.exhausted()  # the hour has not elapsed
    d = Backoff(base_s=0.001, cap_s=0.001, floor_s=0.0001, deadline_s=0.0)
    assert not d.exhausted()  # clock starts at the FIRST post-reset draw
    d.next_delay()
    assert d.exhausted()
    d.reset()
    assert not d.exhausted()


# ---------------------------------------------------------------------------
# classification: poison vs environmental, stage attribution


def test_bisection_quarantines_exactly_the_poison_record(tmp_path):
    log = EventLog(str(tmp_path / "log"), num_partitions=2)
    ids = _publish(log, 8)
    db = SchedulerDb(str(tmp_path / "db.sqlite"))
    out = _isolate(log, db, _poisoning_converter({ids[3]}))
    assert not out.environmental and not out.halted
    assert out.dead == 1
    assert out.applied_sequences == 7
    assert _caught_up(db, log)  # the cursor is PAST the poison record
    assert _job_ids(db) == set(ids) - {ids[3]}
    rows = db.list_dead_letters(consumer=CONSUMER, status="dead")
    assert len(rows) == 1
    assert rows[0]["stage"] == "convert"
    full = db.get_dead_letter(
        CONSUMER, rows[0]["partition"], rows[0]["record_offset"]
    )
    assert ids[3].encode() in full["payload"]  # raw bytes preserved
    log.close()


def test_decode_stage_garbage_payload(tmp_path):
    log = EventLog(str(tmp_path / "log"), num_partitions=1)
    ids = _publish(log, 2)
    log.append(0, b"k", b"\xff\xfenot-a-proto")
    log.flush()
    ids += _publish(log, 2, prefix="tail")
    db = SchedulerDb(str(tmp_path / "db.sqlite"))
    out = _isolate(log, db, convert_sequences)
    assert out.dead == 1 and out.applied_sequences == 4
    rows = db.list_dead_letters(consumer=CONSUMER)
    assert rows[0]["stage"] == "decode"
    assert _caught_up(db, log)
    log.close()


def test_render_stage_poison_with_fake_sink(tmp_path):
    log = EventLog(str(tmp_path / "log"), num_partitions=1)
    ids = _publish(log, 4)

    class FakeSink:
        def __init__(self):
            self.stored: list = []
            self.dead: list = []
            self.pos: dict = {}

        def store(self, ops, consumer=None, next_positions=None):
            self.stored.extend(ops)
            self.pos.update(next_positions or {})

        def store_dead_letters(self, rows, consumer=None, next_positions=None):
            self.dead.extend(rows)
            self.pos.update(next_positions or {})

        def positions(self, consumer=None):
            return dict(self.pos)

    def renderer(seqs):
        for s in seqs:
            for ev in s.events:
                if ev.submit_job.job_id == ids[2]:
                    raise RuntimeError("render chokes")

    sink = FakeSink()
    out = dlq.isolate_batch(
        log_=log,
        sink=sink,
        converter=lambda seqs: seqs,  # identity: the renderer probes seqs
        consumer=CONSUMER,
        partitions=[0],
        positions={0: 0},
        renderer=renderer,
    )
    assert out.dead == 1
    assert sink.dead[0].stage == "render"
    assert len(sink.stored) == 3
    log.close()


def test_all_records_failing_is_environmental(tmp_path):
    """A broken converter build fails everything: nothing quarantined,
    retry-forever preserved."""
    log = EventLog(str(tmp_path / "log"), num_partitions=2)
    _publish(log, 6)
    db = SchedulerDb(str(tmp_path / "db.sqlite"))

    def broken(seqs):
        raise RuntimeError("bad build")

    out = _isolate(log, db, broken)
    assert out.environmental
    assert out.dead == 0 and out.applied_sequences == 0
    assert not out.new_positions
    assert db.list_dead_letters(consumer=CONSUMER) == []
    log.close()


def test_single_record_batch_poison_has_no_contrast(tmp_path):
    """total == 1: a deterministic pure-stage failure IS the poison
    signature (there is nothing to contrast against)."""
    log = EventLog(str(tmp_path / "log"), num_partitions=1)
    ids = _publish(log, 1)
    db = SchedulerDb(str(tmp_path / "db.sqlite"))
    out = _isolate(log, db, _poisoning_converter(set(ids)))
    assert out.dead == 1 and not out.environmental
    assert _caught_up(db, log)
    log.close()


def test_store_down_is_environmental(tmp_path):
    """A store refusing even an empty transaction is environmental: abort
    the walk, quarantine nothing, keep retrying."""
    log = EventLog(str(tmp_path / "log"), num_partitions=1)
    _publish(log, 4)
    db = SchedulerDb(str(tmp_path / "db.sqlite"))

    class DownSink:
        def store(self, ops, consumer=None, next_positions=None):
            raise ConnectionError("db down")

        def store_dead_letters(self, rows, consumer=None, next_positions=None):
            raise ConnectionError("db down")

        def positions(self, consumer=None):
            return {}

    out = dlq.isolate_batch(
        log_=log,
        sink=DownSink(),
        converter=convert_sequences,
        consumer=CONSUMER,
        partitions=[0],
        positions={0: 0},
    )
    assert out.environmental
    assert out.dead == 0
    log.close()


# ---------------------------------------------------------------------------
# the same-transaction contract (r11/r19 cursor-fence discipline)


def test_ingest_ack_crash_no_double_dead_letter_no_lost_record(tmp_path):
    """A crash between the quarantine txn and the in-memory ack replays
    the walk: INSERT OR IGNORE + the idempotent cursor upsert make the
    replay a no-op -- exactly one DLQ row, no record lost or re-applied."""
    from armada_tpu.core.faults import FaultInjected

    log = EventLog(str(tmp_path / "log"), num_partitions=1)
    ids = _publish(log, 6)
    db = SchedulerDb(str(tmp_path / "db.sqlite"))
    # after_n=1: the first ingest_ack check fires after the good-prefix
    # run commits; the SECOND lands exactly between the quarantine txn
    # and the in-memory ack -- the crash window under test
    os.environ["ARMADA_FAULT"] = "ingest_ack:raise:1"
    with pytest.raises(FaultInjected):
        _isolate(log, db, _poisoning_converter({ids[2]}))
    os.environ.pop("ARMADA_FAULT", None)
    # the quarantine COMMITTED before the crash: row and cursor are fenced
    rows = db.list_dead_letters(consumer=CONSUMER, status="dead")
    assert len(rows) == 1
    # the retry loop re-runs isolation from committed positions
    out = _isolate(log, db, _poisoning_converter({ids[2]}))
    assert not out.environmental
    assert _caught_up(db, log)
    rows = db.list_dead_letters(consumer=CONSUMER, status="dead")
    assert len(rows) == 1, "double dead-letter after crash replay"
    assert _job_ids(db) == set(ids) - {ids[2]}, "lost or duplicated record"
    log.close()


# ---------------------------------------------------------------------------
# control-plane records: never auto-skipped


def test_poison_control_record_halts_until_operator_verdict(tmp_path):
    from armada_tpu.ingest.shards import _CONTROL_KEY

    log = EventLog(str(tmp_path / "log"), num_partitions=1)
    ids = _publish(log, 2)
    log.append(0, _CONTROL_KEY, b"\xff\xfegarbage-control")
    log.flush()
    tail = _publish(log, 2, prefix="tail")
    db = SchedulerDb(str(tmp_path / "db.sqlite"))

    out = _isolate(log, db, convert_sequences)
    assert out.halted and not out.environmental
    assert out.dead == 0, "a control record must NEVER be auto-skipped"
    assert out.applied_sequences == 2  # the prefix before the halt commits
    halts = dlq.registry().control_halts()
    assert CONSUMER in halts
    part, off = halts[CONSUMER]["partition"], halts[CONSUMER]["record_offset"]
    # the cursor parks BEFORE the poison control record
    assert db.positions(CONSUMER)[part] <= off

    # re-running without a verdict stays halted (loud, no progress)
    out2 = _isolate(log, db, convert_sequences)
    assert out2.halted and out2.dead == 0

    # the operator verdict: discard approves the skip, the record
    # quarantines on the next pass and the consumer drains to the end
    admin = dlq.DlqAdmin(log, {CONSUMER: db})
    verdict = admin.discard(f"{CONSUMER}:{part}:{off}")
    assert verdict.get("control_skip_approved")
    out3 = _isolate(log, db, convert_sequences)
    assert out3.dead == 1 and not out3.halted
    assert _caught_up(db, log)
    assert dlq.registry().control_halts() == {}
    assert _job_ids(db) == set(ids) | set(tail)
    log.close()


def test_healthy_control_record_parks_sharded_walk(tmp_path):
    """stop_at_control=True (the sharded mode): a HEALTHY control record
    ends isolation so the barrier path keeps its ordering."""
    from armada_tpu.ingest.shards import _CONTROL_KEY

    log = EventLog(str(tmp_path / "log"), num_partitions=1)
    ids = _publish(log, 2)
    log.append(0, _CONTROL_KEY, _seq("ctl-0000").SerializeToString())
    log.flush()
    _publish(log, 2, prefix="tail")
    db = SchedulerDb(str(tmp_path / "db.sqlite"))
    out = _isolate(log, db, convert_sequences, stop_at_control=True)
    assert not out.halted
    assert out.applied_sequences == 2  # parked at the control record
    assert _job_ids(db) == set(ids)
    log.close()


# ---------------------------------------------------------------------------
# the serving pipelines: wedge-proof drain + operator replay round-trip


def _materialized(db) -> dict:
    """Bit-equality surface: dead_letters excluded (the poisoned arm
    carries 'replayed' rows), consumer_positions excluded (replay appends
    the raw record, so the cursor ends further), serials scrubbed."""
    snap = db.export_snapshot()
    out = {}
    for table, cols in SNAPSHOT_TABLES.items():
        if table in ("serials", "dead_letters", "consumer_positions"):
            continue
        rows = snap.get(table, [])
        if "serial" in cols:
            i = cols.index("serial")
            rows = [r[:i] + r[i + 1 :] for r in rows]
        out[table] = sorted(rows)
    return out


def _drain_with_poison_then_replay(tmp_path, sharded: bool):
    from armada_tpu.core import faults

    log = EventLog(str(tmp_path / "log"), num_partitions=4)
    _publish(log, 24)

    clean = SchedulerDb(str(tmp_path / "clean.sqlite"))
    from armada_tpu.ingest.pipeline import IngestionPipeline

    IngestionPipeline(log, clean, convert_sequences, CONSUMER).run_until_caught_up()
    want = _materialized(clean)

    db = SchedulerDb(str(tmp_path / "poisoned.sqlite"))
    os.environ["ARMADA_INGEST_RETRIES"] = "2"
    os.environ["ARMADA_FAULT"] = "convert_record:raise"
    faults.reset_counters()
    if sharded:
        from armada_tpu.ingest.shards import PartitionedIngestionPipeline

        pipe = PartitionedIngestionPipeline(
            log, db, convert_sequences, CONSUMER,
            num_shards=4, convert_mode="inline", poll_interval=0.02,
        )
    else:
        pipe = IngestionPipeline(
            log, db, convert_sequences, CONSUMER, poll_interval=0.02
        )
    pipe.start()
    try:
        deadline = time.monotonic() + 60.0
        while time.monotonic() < deadline and not _caught_up(db, log):
            time.sleep(0.02)
        # wedge-proof: bounded retries escalated to bisection and the
        # pipeline drained PAST the poison record
        assert _caught_up(db, log), "pipeline wedged on the poison record"
        dead = db.list_dead_letters(consumer=CONSUMER, status="dead")
        assert len(dead) >= 1

        # operator fix: disarm, clear the latch, replay the raw bytes
        os.environ.pop("ARMADA_FAULT", None)
        dlq.reset_poison()
        rep = dlq.DlqAdmin(log, {CONSUMER: db}).replay(CONSUMER)
        assert rep["replayed"] >= 1
        deadline = time.monotonic() + 60.0
        while time.monotonic() < deadline and not _caught_up(db, log):
            time.sleep(0.02)
        assert _caught_up(db, log)
    finally:
        pipe.stop()
    assert _materialized(db) == want
    assert all(
        r["status"] == "replayed"
        for r in db.list_dead_letters(consumer=CONSUMER)
    )
    log.close()


@pytest.mark.fast  # explicit: the fast tier must always carry one full
# poison drill (wedge-proof drain + replay round-trip), not just the
# backoff representatives -- the chaos_cycle --poison leg's in-process twin
def test_serial_pipeline_drains_past_poison_and_replay_restores(tmp_path):
    _drain_with_poison_then_replay(tmp_path, sharded=False)


def test_sharded_pipeline_drains_past_poison_and_replay_restores(tmp_path):
    _drain_with_poison_then_replay(tmp_path, sharded=True)


# ---------------------------------------------------------------------------
# operator + observability surfaces


def test_dlq_admin_verbs_via_control_plane(tmp_path):
    """The armadactl verbs ride ControlPlaneServer hooks (plane-local,
    like checkpoints); an unwired plane answers with a typed error."""
    from armada_tpu.server.controlplane import ControlPlaneServer, SubmitError

    cp = ControlPlaneServer(publisher=None)
    with pytest.raises(SubmitError):
        cp.dlq_status()

    log = EventLog(str(tmp_path / "log"), num_partitions=1)
    ids = _publish(log, 4)
    db = SchedulerDb(str(tmp_path / "db.sqlite"))
    _isolate(log, db, _poisoning_converter({ids[1]}))
    cp.dlq_admin = dlq.DlqAdmin(log, {CONSUMER: db})

    status = cp.dlq_status()
    assert status["dead_letters_total"] == 1
    assert status["stores"][CONSUMER]["dead"] == 1
    listing = cp.dlq_list(CONSUMER)
    assert len(listing) == 1
    part, off = listing[0]["partition"], listing[0]["record_offset"]
    import base64

    shown = cp.dlq_show(f"{CONSUMER}:{part}:{off}")
    assert ids[1].encode() in base64.b64decode(shown["payload"])
    rep = cp.dlq_replay(f"{CONSUMER}:{part}:{off}")
    assert rep["replayed"] == 1
    # replay re-published the raw bytes; a drain recovers the job
    from armada_tpu.ingest.pipeline import IngestionPipeline

    IngestionPipeline(
        log, db, convert_sequences, CONSUMER,
        start_positions=db.positions(CONSUMER),
    ).run_until_caught_up()
    assert _job_ids(db) == set(ids)
    log.close()


def test_registry_snapshot_and_metrics_gauges():
    reg = dlq.registry()
    reg.note_dead_letter("scheduler", 2)
    reg.note_dead_letter("scheduler", 2)
    reg.note_dead_letter("lookout", 0)
    reg.note_batch_retry("scheduler")
    snap = reg.snapshot()
    assert snap["dead_letters_total"] == 3
    assert snap["dead_letters_by_partition"]["scheduler"]["2"] == 2
    assert snap["batch_retries"]["scheduler"] == 1

    import prometheus_client

    from armada_tpu.scheduler.metrics import SchedulerMetrics

    preg = prometheus_client.CollectorRegistry()
    m = SchedulerMetrics(registry=preg)
    m.observe_dlq(snap)
    assert (
        preg.get_sample_value(
            "armada_ingest_dead_letters_total",
            {"consumer": "scheduler", "partition": "2"},
        )
        == 2.0
    )
    assert (
        preg.get_sample_value(
            "armada_ingest_batch_retries_total", {"consumer": "scheduler"}
        )
        == 1.0
    )
    # stale-label removal: a reset registry drops the series
    m.observe_dlq(dlq.DlqRegistry().snapshot())
    assert (
        preg.get_sample_value(
            "armada_ingest_dead_letters_total",
            {"consumer": "scheduler", "partition": "2"},
        )
        is None
    )
