"""Control-plane actions: operator commands on executors and queues, routed
through the EVENT LOG so every replica and materialized view converges by
replay (reference: internal/server/executor/executor.go publishing
pkg/controlplaneevents onto the control-plane Pulsar topic).

Cordon state is therefore rebuildable from the log -- a fresh replica that
replays the "$control-plane" stream reaches the same executor_settings table
as the one that served the original armadactl call (VERDICT r3 missing #4:
direct DB writes were the one asymmetry in the event-sourced design).

Verbs (pkg/api/executor.proto):
  * upsert/delete executor settings (cordon with reason, by user)
  * preempt/cancel all matching jobs on an executor
  * preempt/cancel all matching jobs of a queue
"""

from __future__ import annotations

import time
from typing import Callable, Optional, Sequence

from armada_tpu.eventlog.publisher import Publisher
from armada_tpu.events import events_pb2 as pb
from armada_tpu.server.auth import ActionAuthorizer, Permission, Principal
from armada_tpu.server.submit import SubmitError

# The reserved stream: EventSequences keyed (queue="", jobset=CONTROL_PLANE)
# hash to a fixed partition and are consumed by every scheduler ingester.
# No real jobset can collide: queue names are validated non-empty.
CONTROL_PLANE_JOBSET = "$control-plane"


class ControlPlaneServer:
    def __init__(
        self,
        publisher: Publisher,
        authorizer: Optional[ActionAuthorizer] = None,
        clock: Callable[[], float] = time.time,
    ):
        self._publisher = publisher
        self._auth = authorizer or ActionAuthorizer()
        self._clock = clock
        # Checkpoint verbs (armadactl checkpoint): plane-LOCAL hooks wired
        # by serve -- a snapshot is one replica's recovery artifact, so
        # these are the single exception to "every verb publishes an
        # event".  None = this plane has no checkpoint surface.
        self.checkpoint_trigger: Optional[Callable[[], dict]] = None
        self.checkpoint_status: Optional[Callable[[], dict]] = None
        # Dead-letter verbs (armadactl dlq): plane-LOCAL like checkpoints --
        # a quarantined record is one replica's artifact (its store's DLQ
        # table); replay re-publishes through the shared log, and
        # idempotent re-application makes that safe.  serve wires an
        # ingest/dlq.DlqAdmin here; None = no dead-letter surface.
        self.dlq_admin: Optional[object] = None

    def _publish(self, event: pb.Event, user: str) -> None:
        event.created_ns = int(self._clock() * 1e9)
        self._publisher.publish(
            [
                pb.EventSequence(
                    queue="",
                    jobset=CONTROL_PLANE_JOBSET,
                    user_id=user,
                    events=[event],
                )
            ]
        )

    # --- executor settings (executor.go UpsertExecutorSettings) -------------

    def upsert_executor_settings(
        self,
        name: str,
        cordoned: bool,
        cordon_reason: str = "",
        principal: Principal = Principal(),
    ) -> None:
        self._auth.authorize_action(
            principal, Permission.UPDATE_EXECUTOR_SETTINGS
        )
        if not name:
            raise SubmitError("executor name must be non-empty")
        if cordoned and not cordon_reason:
            # the reference makes the reason mandatory when cordoning:
            # forensics later need to know WHY capacity left the fleet
            raise SubmitError("cordon reason must be specified if cordoning")
        self._publish(
            pb.Event(
                executor_settings_upsert=pb.ExecutorSettingsUpsert(
                    name=name,
                    cordoned=cordoned,
                    cordon_reason=cordon_reason,
                    set_by_user=principal.name,
                )
            ),
            principal.name,
        )

    def delete_executor_settings(
        self, name: str, principal: Principal = Principal()
    ) -> None:
        self._auth.authorize_action(
            principal, Permission.UPDATE_EXECUTOR_SETTINGS
        )
        if not name:
            raise SubmitError("executor name must be non-empty")
        self._publish(
            pb.Event(
                executor_settings_delete=pb.ExecutorSettingsDelete(name=name)
            ),
            principal.name,
        )

    # --- checkpoints (scheduler/checkpoint.py; plane-local) -----------------

    def trigger_checkpoint(self, principal: Principal = Principal()) -> dict:
        """Snapshot the plane's materialized state now; returns the written
        checkpoint's identity.  Operator-gated like the cordon verbs."""
        self._auth.authorize_action(
            principal, Permission.UPDATE_EXECUTOR_SETTINGS
        )
        if self.checkpoint_trigger is None:
            raise SubmitError("this plane has no checkpoint surface")
        return self.checkpoint_trigger()

    def get_checkpoint_status(
        self, principal: Principal = Principal()
    ) -> dict:
        self._auth.authorize_action(
            principal, Permission.UPDATE_EXECUTOR_SETTINGS
        )
        if self.checkpoint_status is None:
            raise SubmitError("this plane has no checkpoint surface")
        return self.checkpoint_status()

    # --- dead letters (ingest/dlq.py; plane-local like checkpoints) ---------

    def _dlq(self):
        if self.dlq_admin is None:
            raise SubmitError("this plane has no dead-letter surface")
        return self.dlq_admin

    def dlq_status(self, principal: Principal = Principal()) -> dict:
        """Quarantine census + pending control-plane halts (the /healthz
        ``dlq`` block plus per-store row counts)."""
        self._auth.authorize_action(
            principal, Permission.UPDATE_EXECUTOR_SETTINGS
        )
        return self._dlq().status()

    def dlq_list(
        self, selector: str = "", principal: Principal = Principal()
    ) -> list:
        self._auth.authorize_action(
            principal, Permission.UPDATE_EXECUTOR_SETTINGS
        )
        return self._dlq().list(selector)

    def dlq_show(self, selector: str, principal: Principal = Principal()) -> dict:
        self._auth.authorize_action(
            principal, Permission.UPDATE_EXECUTOR_SETTINGS
        )
        return self._dlq().show(selector)

    def dlq_replay(
        self, selector: str = "", principal: Principal = Principal()
    ) -> dict:
        """Re-publish matching dead rows' raw bytes to their original
        partitions (once per original record) and mark them replayed.
        Event-sourcing idempotency makes re-application safe; run only
        after fixing whatever made the record poison."""
        self._auth.authorize_action(
            principal, Permission.UPDATE_EXECUTOR_SETTINGS
        )
        return self._dlq().replay(selector)

    def dlq_discard(
        self, selector: str, principal: Principal = Principal()
    ) -> dict:
        """Approve a pending control-plane skip (the halt verdict) or mark
        quarantined rows discarded -- the operator's explicit give-up."""
        self._auth.authorize_action(
            principal, Permission.UPDATE_EXECUTOR_SETTINGS
        )
        return self._dlq().discard(selector)

    # --- cycle traces (ops/trace.py; plane-local like checkpoints) ----------

    def dump_trace(self, principal: Principal = Principal()) -> dict:
        """The last N cycles' span trees in offset form (armadactl trace
        converts to Chrome trace JSON client-side).  Plane-LOCAL like the
        checkpoint verbs: a trace is one replica's own timeline.  Gated on
        the operator permission -- span args carry queue/pool names."""
        self._auth.authorize_action(
            principal, Permission.UPDATE_EXECUTOR_SETTINGS
        )
        from armada_tpu.ops.trace import recorder

        return recorder().dump()

    # --- device quarantine (scheduler/quarantine.py; plane-local) -----------

    def quarantine_status(self, principal: Principal = Principal()) -> dict:
        """The round-verification ledger + device quarantine scoreboard
        (the same block /healthz embeds).  Plane-LOCAL like the checkpoint
        verbs: a quarantine is one replica's view of its own accelerator."""
        self._auth.authorize_action(
            principal, Permission.UPDATE_EXECUTOR_SETTINGS
        )
        from armada_tpu.models.verify import healthz_block

        return healthz_block()

    def quarantine_clear(
        self, device: str = "", principal: Principal = Principal()
    ) -> dict:
        """Operator clear: forget quarantine + strike windows for `device`
        (or every device when empty), so the next healthy re-probe may
        promote back to the accelerator.  The ONE way out of a
        verification quarantine -- a chip that corrupts results does not
        heal by waiting (docs/operations.md runbook)."""
        self._auth.authorize_action(
            principal, Permission.UPDATE_EXECUTOR_SETTINGS
        )
        from armada_tpu.scheduler.quarantine import device_quarantine

        cleared = device_quarantine().clear(device)
        return {"cleared": cleared}

    # --- mass actions (executor.go PreemptOnExecutor / CancelOnExecutor) ----

    def preempt_on_executor(
        self,
        name: str,
        queues: Sequence[str] = (),
        priority_classes: Sequence[str] = (),
        principal: Principal = Principal(),
    ) -> None:
        self._auth.authorize_action(principal, Permission.PREEMPT_ANY_JOBS)
        if not name:
            raise SubmitError("executor name must be non-empty")
        self._publish(
            pb.Event(
                preempt_on_executor=pb.PreemptOnExecutor(
                    name=name,
                    queues=list(queues),
                    priority_classes=list(priority_classes),
                )
            ),
            principal.name,
        )

    def cancel_on_executor(
        self,
        name: str,
        queues: Sequence[str] = (),
        priority_classes: Sequence[str] = (),
        principal: Principal = Principal(),
    ) -> None:
        self._auth.authorize_action(principal, Permission.CANCEL_ANY_JOBS)
        if not name:
            raise SubmitError("executor name must be non-empty")
        self._publish(
            pb.Event(
                cancel_on_executor=pb.CancelOnExecutor(
                    name=name,
                    queues=list(queues),
                    priority_classes=list(priority_classes),
                )
            ),
            principal.name,
        )

    def preempt_on_queue(
        self,
        name: str,
        priority_classes: Sequence[str] = (),
        principal: Principal = Principal(),
    ) -> None:
        self._auth.authorize_action(principal, Permission.PREEMPT_ANY_JOBS)
        if not name:
            raise SubmitError("queue name must be non-empty")
        self._publish(
            pb.Event(
                preempt_on_queue=pb.PreemptOnQueue(
                    name=name, priority_classes=list(priority_classes)
                )
            ),
            principal.name,
        )

    def cancel_on_queue(
        self,
        name: str,
        priority_classes: Sequence[str] = (),
        job_states: Sequence[str] = (),
        principal: Principal = Principal(),
    ) -> None:
        self._auth.authorize_action(principal, Permission.CANCEL_ANY_JOBS)
        if not name:
            raise SubmitError("queue name must be non-empty")
        for state in job_states:
            if state not in ("queued", "leased"):
                raise SubmitError(
                    f"invalid job state {state!r} (want queued|leased)"
                )
        self._publish(
            pb.Event(
                cancel_on_queue=pb.CancelOnQueue(
                    name=name,
                    priority_classes=list(priority_classes),
                    job_states=list(job_states),
                )
            ),
            principal.name,
        )
