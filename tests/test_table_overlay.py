"""Gap-buffer/LSM overlay stress: _SortedTable's O(delta) maintenance must be
state-identical to the pre-overlay direct-sorted construction.

The overlay rework (incremental.py round 6) keeps recent inserts in a
key-sorted OVERLAY region behind the sorted base and folds them in with one
vectorized merge when the overlay passes its threshold — instead of a
full-column np.insert copy per batch.  These tests pin:

1. *State equality*: after any interleaving of insert/remove batches (with
   organic merges and compactions), the live rows — order, keys, requests,
   extra columns — equal a fresh table bulk-loaded from the same logical
   state in one sorted batch (the n==0 fast path IS the pre-overlay direct
   construction).
2. *Builder equality, both assemble modes*: driving heavy per-cycle churn
   through IncrementalBuilder keeps its jobs/runs tables equal to a
   from-scratch builder's, and rounds produce identical outcomes via both
   assemble() (dense/table-position) and assemble_delta() (stable slots).
3. *O(delta) cost*: `copied_rows` (full-width rows copied by merge/compact/
   growth) stays amortized O(delta) at a 100k-row table — a timing-free
   guard (the CI host is 1-CPU and load-sensitive) that the old per-batch
   O(table) memcpy cannot pass.
"""

import random

import numpy as np

from armada_tpu.core.types import RunningJob
from armada_tpu.models.incremental import _SortedTable

from test_incremental import (
    _incremental,
    _job,
    _outcomes_equal,
    _random_world,
    _round,
)


def _key_at(t, r):
    return tuple(
        t.ids[r] if c == "ids" else getattr(t, c)[r].item()
        for c in t.sort_cols
    )


def _table_state(t):
    """Live-order snapshot of everything load-bearing: full sort keys, the
    request matrix, extra columns, raw atoms."""
    rows = t.live_rows()
    state = {c: getattr(t, c)[rows].copy() for c in t.sort_cols + t._extra}
    state["req"] = t.req[rows].copy()
    if t.atoms is not None:
        state["atoms"] = t.atoms[rows].copy()
    return state


def _assert_states_equal(a, b, ctx=""):
    assert a.keys() == b.keys()
    for c in a:
        assert np.array_equal(a[c], b[c]), f"column {c} diverged {ctx}"


def _direct_sorted(t, model):
    """The pre-overlay construction: one bulk insert of the whole logical
    state into a fresh table (the n==0 path sorts the batch directly)."""
    fresh = _SortedTable(
        t.R,
        {c: getattr(t, c).dtype for c in t._extra},
        cap=max(len(model), 1),
        sort_cols=t.sort_cols,
        with_atoms=t.atoms is not None,
    )
    vals = list(model.values())
    fresh.insert_batch(
        [dict(r) for r, _, _ in vals],
        [req for _, req, _ in vals],
        atoms=[at for _, _, at in vals] if t.atoms is not None else None,
    )
    return fresh


def _run_table_stress(seed, with_atoms):
    rng = random.Random(seed)
    t = _SortedTable(
        3, {"level": np.int32, "slot": np.int64}, cap=8, with_atoms=with_atoms
    )
    # id -> (row dict, req, atoms): the logical state the table must mirror
    model = {}
    next_id = 0
    saw_overlay = saw_merge = False
    for cycle in range(40):
        # interleaved submit batch; occasional bursts push the overlay past
        # its 2048-row merge threshold organically
        k = (
            1200 + rng.randrange(400)
            if rng.random() < 0.18
            else rng.randrange(1, 400)
        )
        batch, reqs, atoms = [], [], []
        for _ in range(k):
            jid = f"job{next_id:07d}".encode()
            next_id += 1
            row = {
                "ids": jid,
                "qi": rng.randrange(4),
                "npc": -rng.choice([100, 1000, 5000]),
                "prio": rng.randrange(3),
                "sub": round(rng.random(), 6),
                "level": rng.randrange(5),
                "slot": next_id,
            }
            req = np.array(
                [rng.randrange(1, 9) for _ in range(3)], np.float32
            )
            at = (req * 1000).astype(np.int64)
            batch.append(row)
            reqs.append(req)
            atoms.append(at)
            model[jid] = (row, req, at)
        had_rows = t.n > 0
        t.insert_batch(batch, reqs, atoms=atoms if with_atoms else None)
        if t.n > t.sorted_n:
            saw_overlay = True
        elif had_rows and k:
            saw_merge = True  # non-bulk insert ended fully sorted
        # interleaved remove batch (lease/cancel/terminate feedback)
        if model and rng.random() < 0.8:
            victims = rng.sample(
                sorted(model), min(len(model), rng.randrange(1, 260))
            )
            out = t.remove_many(victims)
            assert all(o is not None for o in out)
            for jid in victims:
                model.pop(jid)
        if cycle % 11 == 5:
            t.compact()  # explicit compaction interleave
            assert t.n == t.sorted_n == len(model) and t.dead == 0
        # per-cycle invariants: sortedness, membership, locate
        rows = t.live_rows()
        assert len(rows) == len(model)
        keys = [_key_at(t, r) for r in rows]
        assert keys == sorted(keys), f"order broken at cycle {cycle}"
        for jid in rng.sample(sorted(model), min(20, len(model))):
            assert t._locate(jid) is not None
        # full state equality vs the direct-sorted construction
        _assert_states_equal(
            _table_state(t),
            _table_state(_direct_sorted(t, model)),
            ctx=f"(seed {seed}, cycle {cycle})",
        )
    assert saw_overlay and saw_merge


def test_overlay_stress_matches_direct_sorted():
    for seed in (0, 1, 2):
        _run_table_stress(seed, with_atoms=False)


def test_overlay_stress_matches_direct_sorted_with_atoms():
    _run_table_stress(3, with_atoms=True)


# ---------------------------------------------------------------------------
# Builder-level: heavy churn cycles, both assemble modes
# ---------------------------------------------------------------------------


def _builder_tables_equal(a, b):
    """Jobs/runs table state (sort keys + requests, live order) must match
    between two builders holding the same logical state.  Slot-assignment
    extras are intentionally excluded: slots are an allocation order, not
    state."""
    for name in ("jobs", "runs"):
        ta, tb = getattr(a, name), getattr(b, name)
        ra, rb = ta.live_rows(), tb.live_rows()
        assert len(ra) == len(rb), f"{name} live count diverged"
        for c in ta.sort_cols:
            assert np.array_equal(
                getattr(ta, c)[ra], getattr(tb, c)[rb]
            ), f"{name}.{c} diverged"
        assert np.array_equal(ta.req[ra], tb.req[rb]), f"{name}.req diverged"


def _churn_cycles(mode, seed):
    rng = random.Random(seed)
    nodes, queues, jobs, running = _random_world(seed, num_jobs=150)
    builder = _incremental(nodes, queues, jobs, running)
    jobs_by_id = {j.id: j for j in jobs}
    running = list(running)
    next_id = [0]

    def outcome(b):
        if mode == "slot":
            bundle, ctx = b.assemble_delta()
            return _round(bundle.materialize(), ctx)
        return _round(*b.assemble())

    for cycle in range(6):
        incr = outcome(builder)
        fresh_builder = _incremental(
            nodes, queues, list(jobs_by_id.values()), running
        )
        _builder_tables_equal(fresh_builder, builder)
        _outcomes_equal(outcome(fresh_builder), incr)

        # heavy delta churn: lease feedback + batch cancels + batch submits
        for jid, nid in incr.scheduled.items():
            spec = jobs_by_id.pop(jid, None)
            if spec is None:
                continue
            builder.remove(jid)
            r = RunningJob(job=spec, node_id=nid)
            running.append(r)
            builder.lease(r)
            if spec.gang_id:
                builder.note_running_gang(spec.queue, spec.gang_id, spec.id)
        for jid in incr.preempted:
            running = [r for r in running if r.job.id != jid]
            builder.unlease(jid)
        cancels = rng.sample(sorted(jobs_by_id), min(len(jobs_by_id), 25))
        for jid in cancels:
            jobs_by_id.pop(jid)
            builder.remove(jid)
        submits = []
        for _ in range(60):
            i = next_id[0]
            next_id[0] += 1
            spec = _job(
                f"new{i:04d}",
                rng.choice(["qa", "qb", "qc"]),
                rng.choice([1, 2, 4]),
                pc=rng.choice(["low", "high"]),
                prio=rng.randrange(3),
                sub=10.0 + cycle + rng.random(),
            )
            jobs_by_id[spec.id] = spec
            submits.append(spec)
        builder.submit_many(submits)


def test_builder_churn_cycles_dense_mode():
    _churn_cycles("dense", seed=11)


def test_builder_churn_cycles_slot_mode():
    _churn_cycles("slot", seed=12)


# ---------------------------------------------------------------------------
# O(delta) microbench guard (timing-free: counts copied rows, not seconds)
# ---------------------------------------------------------------------------


def test_insert_remove_cost_is_o_delta_at_100k():
    """20 cycles of 1k-in/1k-out against a 100k-row base.  The old path
    copied the full table per insert_batch (~2M full-width rows over this
    run); the overlay must stay within the amortized merge bound (~16x
    delta) and most cycles must copy nothing at all."""
    rng = random.Random(99)
    n0, cycles, delta = 100_000, 20, 1_000
    t = _SortedTable(2, {"level": np.int32}, cap=n0 + cycles * delta + 1024)
    rows, reqs = [], []
    for i in range(n0):
        rows.append(
            {
                "ids": f"base{i:07d}".encode(),
                "qi": rng.randrange(32),
                "npc": -rng.choice([100, 1000]),
                "prio": rng.randrange(3),
                "sub": round(rng.random(), 6),
                "level": 2,
            }
        )
        reqs.append(np.ones(2, np.float32))
    t.insert_batch(rows, reqs)
    assert t.n == t.sorted_n == n0
    live_ids = [r["ids"] for r in rows]
    t.copied_rows = 0

    free_cycles = 0
    next_id = 0
    for cycle in range(cycles):
        before = t.copied_rows
        batch, breqs = [], []
        for _ in range(delta):
            jid = f"fresh{next_id:07d}".encode()
            next_id += 1
            batch.append(
                {
                    "ids": jid,
                    "qi": rng.randrange(32),
                    "npc": -1000,
                    "prio": 0,
                    "sub": 100.0 + cycle,
                    "level": 2,
                }
            )
            breqs.append(np.ones(2, np.float32))
            live_ids.append(jid)
        t.insert_batch(batch, breqs)
        # tombstone removal must never copy (20k dead never passes the
        # n//4 compaction threshold at this scale)
        victims = [
            live_ids.pop(rng.randrange(len(live_ids))) for _ in range(delta)
        ]
        pre_remove = t.copied_rows
        assert all(o is not None for o in t.remove_many(victims))
        assert t.copied_rows == pre_remove, "remove_many copied the table"
        if t.copied_rows == before:
            free_cycles += 1

    total_delta = cycles * delta
    # Amortized bound: the overlay folds at ~sorted_n//16, i.e. ~16 copied
    # rows per inserted row; 2x headroom for threshold crossings.  The
    # pre-overlay path copied n0 rows per cycle -- 2M total, two orders
    # past this bound.
    assert t.copied_rows <= 32 * total_delta, (
        f"copied {t.copied_rows} rows for {total_delta} delta rows: "
        f"O(table) maintenance is back"
    )
    # most cycles ride the overlay without touching the base at all
    assert free_cycles >= cycles // 2, (
        f"only {free_cycles}/{cycles} cycles were copy-free"
    )
    # the table still answers exactly
    assert len(t.live_rows()) == len(live_ids)
    for jid in rng.sample(live_ids, 50):
        assert t._locate(jid) is not None
