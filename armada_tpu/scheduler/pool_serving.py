"""Pool-parallel serving ledger: what the multi-pool cycle actually did.

One process-global scoreboard (the watchdog/SLO-recorder discipline) fed by
FairSchedulingAlgo.schedule each cycle: whether the pool-parallel path ran
or fell back to the serial per-pool order (and why it fell back matters --
certification failure is a WORKLOAD property, not a bug), how many stacked
kernel launches covered how many pools, per-pool round seconds, and the
cycle's overlap ratio (sum of per-pool round time over the pool section's
wall clock -- ~1.0 serial, > 1.0 when dispatches overlapped fetches).

Readers: /healthz ``pools`` block (cli/serve.py), bench ``pools_*`` keys,
tools/chaos_cycle.py --pools.  Decisions never depend on this module.
"""

from __future__ import annotations

from typing import Optional

from armada_tpu.analysis.tsan import make_lock


class PoolServingStats:
    def __init__(self):
        self._lock = make_lock("scheduler.pool_serving")
        self.cycles = 0  # multi-pool cycles observed (>= 1 pool round ran)
        self.parallel_cycles = 0  # cycles that ran the dispatch/fetch split
        # pool-parallel armed but the cycle stayed serial: shared queued
        # candidates (feed.pools_independent() false), armed rate limiters,
        # or a single-pool cycle (nothing to overlap).
        self.serial_fallback_cycles = 0
        self.stacked_launches = 0  # cumulative stacked kernel launches
        self.stacked_pools = 0  # cumulative pools covered by stacks
        self.last_overlap_ratio: Optional[float] = None
        self.last_round_s: dict = {}  # pool -> seconds, last cycle each ran

    def record_cycle(
        self,
        *,
        parallel: bool,
        armed: bool,
        pool_round_s: dict,
        stacked_launches: int = 0,
        stacked_pools: int = 0,
        overlap_ratio: Optional[float] = None,
    ) -> None:
        with self._lock:
            self.cycles += 1
            if parallel:
                self.parallel_cycles += 1
            elif armed:
                self.serial_fallback_cycles += 1
            self.stacked_launches += stacked_launches
            self.stacked_pools += stacked_pools
            if overlap_ratio is not None:
                self.last_overlap_ratio = round(float(overlap_ratio), 3)
            self.last_round_s.update(
                {p: round(float(s), 6) for p, s in pool_round_s.items()}
            )
            if len(self.last_round_s) > 512:
                # pool-churn bound (the SLORecorder.pool_cap discipline):
                # late-discovered pools come and go; past the cap keep only
                # the pools this cycle actually served
                self.last_round_s = {
                    p: round(float(s), 6) for p, s in pool_round_s.items()
                }

    def snapshot(self) -> dict:
        from armada_tpu.core.pipeline import pool_parallel_enabled

        with self._lock:
            return {
                "enabled": pool_parallel_enabled(),
                "cycles": self.cycles,
                "parallel_cycles": self.parallel_cycles,
                "serial_fallback_cycles": self.serial_fallback_cycles,
                "stacked_launches": self.stacked_launches,
                "stacked_pools": self.stacked_pools,
                "last_overlap_ratio": self.last_overlap_ratio,
                "last_round_s": dict(self.last_round_s),
            }


_STATS = PoolServingStats()


def pool_serving_stats() -> PoolServingStats:
    return _STATS


def reset_pool_serving_stats() -> PoolServingStats:
    """Fresh scoreboard (tests/bench)."""
    global _STATS
    _STATS = PoolServingStats()
    return _STATS
