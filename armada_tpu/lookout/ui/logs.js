// Live pod-log viewer: fetch + 3s tail-follow per run box (binoculars
// logs.go behind /api/logs).
import { $ } from "./util.js";
import { raw } from "./api.js";

const logTimers = new Map();  // run id -> live-tail interval (one per box)

export function stopLogTimer(runId) {
  if (logTimers.has(runId)) { clearInterval(logTimers.get(runId)); logTimers.delete(runId); }
}
export function stopAllLogTimers() { for (const id of [...logTimers.keys()]) stopLogTimer(id); }

async function fetchLogs(jobId, runId, boxId) {
  const box = $(boxId);
  if (!box) { stopLogTimer(runId); return; }
  const r = await raw(`/api/logs?job=${encodeURIComponent(jobId)}&run=${encodeURIComponent(runId)}`);
  const d = await r.json();
  const pre = box.querySelector("pre");
  if (!pre) return;
  const atEnd = pre.scrollTop + pre.clientHeight >= pre.scrollHeight - 4;
  pre.textContent = r.ok ? (d.log || "(empty)") : `⚠ ${d.error}`;
  if (atEnd) pre.scrollTop = pre.scrollHeight;  // follow the tail
}

export function openLogs(jobId, runId, live) {
  const boxId = "log-" + runId;
  const box = $(boxId);
  if (!box) return;
  if (box.innerHTML) {  // toggle off
    box.innerHTML = "";
    stopLogTimer(runId);
    return;
  }
  box.innerHTML = "<pre>loading…</pre>";
  fetchLogs(jobId, runId, boxId);
  stopLogTimer(runId);
  if (live) logTimers.set(runId, setInterval(() => fetchLogs(jobId, runId, boxId), 3000));
}
