"""Shared gRPC channel options for servers AND clients (VERDICT #6).

A standalone module so pure-client processes (executors, armadactl, the
sidecar's callers) never import the server module graph just to build a
channel.  The two sides must agree on the message cap -- raising only the
server's send limit still breaks a >4MB lease batch on the client's
receive side -- so both read the same knobs:

* ``ARMADA_GRPC_MAX_MSG_MB`` (default 64): max send/receive message size.
  gRPC's stock 4MB receive cap rejects a large lease batch at reference
  scale.
* ``ARMADA_GRPC_KEEPALIVE_S`` (default 300): keepalive ping period for
  long-lived idle streams (an event watch, the replication tail) crossing
  NATs/proxies that silently drop idle TCP flows.
"""

from __future__ import annotations

import os
from typing import Optional


def _max_message_bytes(max_message_mb: Optional[int]) -> int:
    if max_message_mb is None:
        try:
            max_message_mb = int(os.environ.get("ARMADA_GRPC_MAX_MSG_MB", 64))
        except ValueError:
            max_message_mb = 64
    return int(max_message_mb) * 1024 * 1024


def _keepalive_ms(keepalive_time_s: Optional[float]) -> int:
    if keepalive_time_s is None:
        try:
            keepalive_time_s = float(
                os.environ.get("ARMADA_GRPC_KEEPALIVE_S", 300.0)
            )
        except ValueError:
            keepalive_time_s = 300.0
    return int(keepalive_time_s * 1000)


def channel_options(
    max_message_mb: Optional[int] = None,
    keepalive_time_s: Optional[float] = None,
    keepalive_timeout_s: float = 20.0,
) -> list:
    """Options valid on EITHER side: message caps + keepalive pings."""
    max_bytes = _max_message_bytes(max_message_mb)
    return [
        ("grpc.max_send_message_length", max_bytes),
        ("grpc.max_receive_message_length", max_bytes),
        ("grpc.keepalive_time_ms", _keepalive_ms(keepalive_time_s)),
        ("grpc.keepalive_timeout_ms", int(keepalive_timeout_s * 1000)),
        ("grpc.keepalive_permit_without_calls", 1),
        # Streams sit idle for minutes between events: data-less pings are
        # legitimate, not abuse.
        ("grpc.http2.max_pings_without_data", 0),
    ]
