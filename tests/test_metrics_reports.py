"""Scheduler metrics + scheduling reports tests.

Modeled on the reference's cycle metrics tests (internal/scheduler/metrics/
cycle_metrics_test.go) and reports repository tests (internal/scheduler/
reports): gauge names match the reference's so existing dashboards carry over.
"""

import pytest
from prometheus_client import CollectorRegistry

from armada_tpu.scheduler.metrics import SchedulerMetrics
from armada_tpu.scheduler.reports import SchedulingReportsRepository
from armada_tpu.server import JobSubmitItem, QueueRecord
from tests.control_plane import ControlPlane


@pytest.fixture
def cp(tmp_path):
    plane = ControlPlane.build(tmp_path)
    plane.registry = CollectorRegistry()
    plane.scheduler.metrics = SchedulerMetrics(registry=plane.registry)
    plane.scheduler.reports = SchedulingReportsRepository(max_job_reports=100)
    plane.server.create_queue(QueueRecord("heavy", weight=3.0))
    plane.server.create_queue(QueueRecord("light", weight=1.0))
    yield plane
    plane.close()


def item(cpu="2"):
    return JobSubmitItem(resources={"cpu": cpu, "memory": "2"})


def sample(cp, name, labels=None):
    return cp.registry.get_sample_value(name, labels or {})


def test_cycle_metrics_exported(cp):
    cp.server.submit_jobs("heavy", "m", [item() for _ in range(8)])
    cp.server.submit_jobs("light", "m", [item() for _ in range(8)])
    for ex in cp.executors:
        ex.run_once()
    cp.ingest()
    cp.scheduler.cycle()

    # share gauges, reference names
    heavy = {"pool": "default", "queue": "heavy"}
    light = {"pool": "default", "queue": "light"}
    assert sample(cp, "armada_scheduler_queue_weight", heavy) == 3.0
    fs_heavy = sample(cp, "armada_scheduler_fair_share", heavy)
    fs_light = sample(cp, "armada_scheduler_fair_share", light)
    assert fs_heavy == pytest.approx(0.75) and fs_light == pytest.approx(0.25)
    assert sample(cp, "armada_scheduler_actual_share", heavy) > sample(
        cp, "armada_scheduler_actual_share", light
    )
    assert sample(cp, "armada_scheduler_demand", heavy) > 0
    assert sample(cp, "armada_scheduler_fairness_error", {"pool": "default"}) >= 0

    # decision counters
    total_scheduled = sample(
        cp, "armada_scheduler_scheduled_jobs_total", heavy
    ) + sample(cp, "armada_scheduler_scheduled_jobs_total", light)
    assert total_scheduled == 8  # 2 nodes x 8 cpu / 2 cpu

    # cycle time histogram recorded one scheduling cycle
    assert sample(cp, "armada_scheduler_schedule_cycle_times_count") == 1

    # state transition counters from published events
    assert sample(
        cp,
        "armada_scheduler_job_state_counter_by_queue_total",
        {"queue": "heavy", "state": "leased"},
    ) > 0


def test_reports_record_rounds_and_jobs(cp):
    ids = cp.server.submit_jobs("heavy", "r", [item()])
    impossible = cp.server.submit_jobs(
        "heavy", "r", [JobSubmitItem(resources={"cpu": "6", "memory": "500"})]
    )
    for ex in cp.executors:
        ex.run_once()
    cp.ingest()
    cp.scheduler.cycle()
    reports = cp.scheduler.reports

    jr = reports.job_report(ids[0])
    assert jr is not None and jr["outcome"] == "scheduled"
    assert jr["node"].startswith("ex1-n")

    pool = reports.pool_report("default")["default"]
    assert pool["scheduled"] == 1
    assert pool["num_nodes"] == 2
    assert pool["termination"] in ("exhausted", "global_burst")

    qr = reports.queue_report("heavy")
    assert qr and 0 <= qr[0]["actual_share"] <= 1


def test_reports_over_wire_and_cli(cp, capsys):
    from armada_tpu.cli.armadactl import main
    from armada_tpu.rpc.server import make_server

    ids = cp.server.submit_jobs("heavy", "w", [item()])
    for ex in cp.executors:
        ex.run_once()
    cp.ingest()
    cp.scheduler.cycle()

    server, port = make_server(reports=cp.scheduler.reports)
    try:
        assert main(["--url", f"127.0.0.1:{port}", "scheduling-report"]) == 0
        out = capsys.readouterr().out
        assert "default:" in out and "scheduled=1" in out
        assert main(
            ["--url", f"127.0.0.1:{port}", "scheduling-report", "--queue", "heavy"]
        ) == 0
        out = capsys.readouterr().out
        assert "actual=" in out
        assert main(
            ["--url", f"127.0.0.1:{port}", "scheduling-report", "--job-id", ids[0]]
        ) == 0
        out = capsys.readouterr().out
        assert "outcome: scheduled" in out
        # unknown job -> clean error, nonzero exit, no traceback
        assert main(
            ["--url", f"127.0.0.1:{port}", "scheduling-report", "--job-id", "nope"]
        ) == 1
        err = capsys.readouterr().err
        assert "NOT_FOUND" in err
    finally:
        server.stop(None)


def test_job_report_lru_bound():
    from armada_tpu.scheduler.algo import PoolStats, SchedulerResult
    from armada_tpu.models import RoundOutcome

    reports = SchedulingReportsRepository(max_job_reports=5)
    for i in range(20):
        outcome = RoundOutcome(
            scheduled={}, preempted=[], failed=[f"j{i}"], num_iterations=1,
            termination="exhausted",
        )
        result = SchedulerResult(
            pools=[PoolStats("default", outcome, 1, 1, 0)]
        )
        reports.record_cycle(result, now=float(i))
    assert reports.job_report("j0") is None
    assert reports.job_report("j19") is not None
    assert len(reports._job_reports) == 5


def test_indicative_share_gauge_end_to_end(tmp_path):
    from armada_tpu.core.config import SchedulingConfig

    cfg = SchedulingConfig(
        shape_bucket=32,
        enable_assertions=True,
        indicative_share_base_priorities=(1, 2),
    )
    plane = ControlPlane.build(tmp_path, config=cfg)
    plane.registry = CollectorRegistry()
    plane.scheduler.metrics = SchedulerMetrics(registry=plane.registry)
    plane.server.create_queue(QueueRecord("q"))
    plane.server.submit_jobs("q", "m", [item("8") for _ in range(4)])
    for ex in plane.executors:
        ex.run_once()
    plane.ingest()
    plane.scheduler.cycle()
    s1 = sample(
        plane, "armada_scheduler_indicative_share",
        {"pool": "default", "priority": "1"},
    )
    s2 = sample(
        plane, "armada_scheduler_indicative_share",
        {"pool": "default", "priority": "2"},
    )
    # one fully-demanding queue + phantom: 1/2 at priority 1, 1/3 at 2
    assert s1 == pytest.approx(0.5, abs=1e-3)
    assert s2 == pytest.approx(1 / 3, abs=1e-2)
    plane.close()


def test_base_priorities_must_be_positive():
    from armada_tpu.core.config import scheduling_config_from_dict

    with pytest.raises(ValueError, match="must be positive"):
        scheduling_config_from_dict(
            {"experimentalIndicativeShare": {"basePriorities": [0]}}
        )


def test_job_state_counters_reset_on_interval(tmp_path):
    """jobStateMetricsResetInterval (config.yaml:12; state_metrics.go:157):
    the state counter vector clears once the interval lapses, bounding
    label-series churn."""
    from armada_tpu.core.config import scheduling_config_from_dict
    import dataclasses as _dc

    cfg = scheduling_config_from_dict({"jobStateMetricsResetInterval": "12h"})
    assert cfg.job_state_metrics_reset_interval_s == 12 * 3600.0
    cfg = _dc.replace(cfg, shape_bucket=32, enable_assertions=True)

    plane = ControlPlane.build(tmp_path, config=cfg)
    plane.registry = CollectorRegistry()
    plane.scheduler.metrics = SchedulerMetrics(
        registry=plane.registry, state_reset_interval_s=60.0
    )
    plane.server.create_queue(QueueRecord("q"))
    plane.server.submit_jobs("q", "m", [item()])
    for ex in plane.executors:
        ex.run_once()
    plane.ingest()
    plane.scheduler.cycle()
    labels = {"queue": "q", "state": "leased"}
    assert sample(plane, "armada_scheduler_job_state_counter_by_queue_total", labels) == 1
    # interval lapses -> the vector clears on the next cycle
    plane.clock.advance(120.0)
    plane.scheduler.cycle()
    assert sample(plane, "armada_scheduler_job_state_counter_by_queue_total", labels) is None
    plane.close()


def test_executor_usage_flows_into_queue_resource_used(cp):
    """Executor-reported pod usage reaches the scheduler's
    queue_resource_used gauge (cluster_utilisation.go:68,125 ->
    metrics.go:387-395 -> commonmetrics queue_resource_used): the fake
    cluster reports pending/running pods' requests per queue in its
    snapshot, and the next cycle publishes them."""
    cp.server.submit_jobs("heavy", "u", [item(cpu="2"), item(cpu="2")])
    cp.run_until(
        lambda: sum(
            1 for s in cp.job_states().values() if s in ("leased", "running")
        )
        == 2
    )
    # one more executor round-trip + cycle so the post-lease snapshot (with
    # the pods pending/running) reaches the scheduler
    for ex in cp.executors:
        ex.run_once()
    cp.ingest()
    cp.scheduler.cycle()

    ex_id = cp.executors[0].id
    used_cpu = sample(
        cp,
        "armada_scheduler_queue_resource_used",
        {"cluster": ex_id, "pool": "default", "queue": "heavy", "resource": "cpu"},
    )
    used_mem = sample(
        cp,
        "armada_scheduler_queue_resource_used",
        {"cluster": ex_id, "pool": "default", "queue": "heavy", "resource": "memory"},
    )
    assert used_cpu is not None and used_cpu > 0
    assert used_mem is not None and used_mem > 0
    # usage equals the two pods' cpu requests in atoms (2 cpu each)
    factory = cp.config.resource_list_factory()
    two_cpu_atoms = 2 * factory.from_mapping({"cpu": "2", "memory": "2"}).atoms[
        factory.index_of("cpu")
    ]
    assert used_cpu == float(two_cpu_atoms)
