# Fixture for rule `unmade-lock` (linted under armada_tpu/ingest/).  The
# rule is module-contextual: tests/test_lint.py also lints this buffer with
# the thread spawn removed and asserts the SAME Lock line goes clean -- a
# per-node matcher cannot condition on the rest of the module.
import threading

from armada_tpu.analysis import tsan


class Server:
    def __init__(self):
        self._lock = threading.Lock()  # TP
        # near-miss: the instrumented constructor the race harness sees
        self._stats_lock = tsan.make_lock("fixture.stats")

    def start(self):
        t = threading.Thread(target=self._run, daemon=True)  # spawn-marker
        t.start()
        return t

    def _run(self):
        with self._lock:
            pass
