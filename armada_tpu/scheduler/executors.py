"""Executor snapshots: what each cluster agent reports to the scheduler.

Equivalent of the reference's `schedulerobjects.Executor` (internal/scheduler/
schedulerobjects/schedulerobjects.proto:10-70) as stored by ExecutorApi
(internal/scheduler/api.go StoreExecutor) and read back by the scheduling
algorithm (scheduling_algo.go newFairSchedulingAlgoContext:201): the executor's
nodes with capacities/taints/labels, which runs it believes are active on which
node, and a heartbeat timestamp used for staleness filtering
(filterStaleExecutors, scheduling_algo.go:798).

Snapshots are JSON blobs in the scheduler DB's `executors` table: they cross
a process boundary (executor -> api -> db -> algo) but never a language
boundary, so JSON beats another proto here.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Mapping, Optional

from armada_tpu.core.resources import ResourceListFactory
from armada_tpu.core.types import NodeSpec, Taint


@dataclasses.dataclass(frozen=True)
class ExecutorSnapshot:
    """One executor's reported cluster state at `last_update_ns`."""

    id: str
    pool: str
    nodes: tuple[NodeSpec, ...] = ()
    # Active run id -> node id, as reported by the executor.  The scheduler
    # treats these as the executor's acknowledgement of leases.
    node_of_run: Mapping[str, str] = dataclasses.field(default_factory=dict)
    # Runs leased to this executor but not yet acknowledged back; counted by
    # the lagging-executor filter (filterLaggingExecutors, scheduling_algo.go:816).
    unacknowledged_runs: tuple[str, ...] = ()
    last_update_ns: int = 0
    cordoned: bool = False
    # Actual per-queue resource usage of the executor's non-terminal pods
    # (atoms by fixed resource axis) -- the usage scrape the reference ships
    # in its lease requests (utilisation/cluster_utilisation.go:125
    # ResourceUsageByQueueAndPool) and surfaces as queue_resource_used.
    queue_usage: Mapping[str, tuple[int, ...]] = dataclasses.field(
        default_factory=dict
    )

    # --- serialization ------------------------------------------------------

    def to_json(self) -> bytes:
        return json.dumps(
            {
                "id": self.id,
                "pool": self.pool,
                "nodes": [_node_to_dict(n) for n in self.nodes],
                "node_of_run": dict(self.node_of_run),
                "unacknowledged_runs": list(self.unacknowledged_runs),
                "last_update_ns": self.last_update_ns,
                "cordoned": self.cordoned,
                "queue_usage": {q: list(v) for q, v in self.queue_usage.items()},
            }
        ).encode()

    @staticmethod
    def from_json(blob: bytes, factory: ResourceListFactory) -> "ExecutorSnapshot":
        d = json.loads(blob)
        return ExecutorSnapshot(
            id=d["id"],
            pool=d["pool"],
            nodes=tuple(_node_from_dict(n, factory) for n in d["nodes"]),
            node_of_run=d.get("node_of_run", {}),
            unacknowledged_runs=tuple(d.get("unacknowledged_runs", ())),
            last_update_ns=int(d.get("last_update_ns", 0)),
            cordoned=bool(d.get("cordoned", False)),
            queue_usage={
                q: tuple(v) for q, v in d.get("queue_usage", {}).items()
            },
        )


def _node_to_dict(n: NodeSpec) -> dict:
    return {
        "id": n.id,
        "pool": n.pool,
        "executor": n.executor,
        "resources": (
            {name: int(a) for name, a in zip(n.total_resources.factory.names, n.total_resources.atoms)}
            if n.total_resources is not None
            else {}
        ),
        "taints": [[t.key, t.value, t.effect] for t in n.taints],
        "labels": dict(n.labels),
        "unschedulable": n.unschedulable,
        "node_type": n.node_type,
    }


def _node_from_dict(d: dict, factory: ResourceListFactory) -> NodeSpec:
    rl = factory.zero()
    for name, atoms in d.get("resources", {}).items():
        if name in factory.names:
            rl.atoms[factory.index_of(name)] = atoms
    return NodeSpec(
        id=d["id"],
        pool=d.get("pool", "default"),
        executor=d.get("executor", ""),
        total_resources=rl,
        taints=tuple(Taint(k, v, e) for k, v, e in d.get("taints", ())),
        labels=d.get("labels", {}),
        unschedulable=bool(d.get("unschedulable", False)),
        node_type=d.get("node_type", ""),
    )
