"""Leader election: only the leader schedules; followers mirror state.

Equivalent of the reference's `internal/scheduler/leader` (leader.go:19-190):
a LeaderController hands out tokens and validates them, so a scheduler that
loses leadership mid-cycle discards its work instead of publishing with stale
authority (token fencing, scheduler.go:263).  Two implementations:

* StandaloneLeaderController -- always leader (leader.go:64, dev/single-replica).
* FileLeaseLeaderController -- a lease file on shared storage stands in for the
  reference's Kubernetes coordination/v1 Lease (leader.go:112-186): holders
  renew before expiry; on expiry any replica may take over, bumping the fencing
  generation so stale holders fail validation.
"""

from __future__ import annotations

import dataclasses
import fcntl
import json
import os
import time
from typing import Callable, Optional, Protocol


@dataclasses.dataclass(frozen=True)
class LeaderToken:
    leader: bool
    generation: int = 0


class LeaderController(Protocol):
    def get_token(self) -> LeaderToken:
        """Current leadership claim; cheap, called once per cycle."""

    def validate_token(self, token: LeaderToken) -> bool:
        """True iff `token` still confers leadership (fencing re-check before
        publishing, scheduler.go:263,355)."""

    def leader_address(self) -> Optional[str]:
        """READ-ONLY leadership peek for followers that proxy leader-local
        queries (the reference's LeaderClientConnectionProvider,
        leader/leader_client.go).  Must not acquire/renew (query paths call
        this).  Returns None when this process holds the lease (serve
        locally), the leader's advertised address when another holder does,
        and "" when another holder is known but advertised no address."""

    def current_generation(self) -> int:
        """READ-ONLY peek at the election record's fencing generation
        (monotonic epoch).  Must not acquire/renew -- the publisher's epoch
        fence reads it on every publish to reject writes from a deposed
        leader without waiting for the next cycle's validate_token."""


class StandaloneLeaderController:
    """Always leader (leader.go StandaloneLeaderController:64)."""

    def get_token(self) -> LeaderToken:
        return LeaderToken(leader=True, generation=0)

    def validate_token(self, token: LeaderToken) -> bool:
        return token.leader

    def leader_address(self) -> Optional[str]:
        return None  # we ARE the leader

    def current_generation(self) -> int:
        return 0  # no elections, no epochs


class FileLeaseLeaderController:
    """Lease-file election with fencing generations.

    The lease file holds {holder, generation, expiry}.  acquire-or-renew runs
    under an exclusive flock on a sidecar lock file, so exactly one replica
    wins each expiry race.  Generations only grow; a token from generation g
    is invalid once any replica has acquired generation > g -- the property the
    reference gets from Lease resourceVersion fencing (leader.go:149-186).
    """

    def __init__(
        self,
        lease_path: str,
        holder_id: str,
        lease_duration_s: float = 15.0,
        clock: Callable[[], float] = time.time,
        advertised_address: str = "",
    ):
        self._path = lease_path
        self._holder = holder_id
        self._duration = lease_duration_s
        self._clock = clock
        # Rides in the lease record so followers can proxy leader-local
        # queries (reports).  Often set post-construction once the gRPC
        # port is bound (set_advertised_address).
        self._address = advertised_address

    def set_advertised_address(self, address: str) -> None:
        self._address = address  # picked up by the next acquire/renew write

    def leader_address(self) -> Optional[str]:
        lease = self._locked(self._read)
        if lease is None:
            # No election yet: "" maps to the retryable UNAVAILABLE in the
            # reports proxy; answering None here would have a replica that
            # never won serve report queries from its empty local repository
            # (ADVICE r4).
            return ""
        if lease.get("holder") == self._holder:
            # Our lease -- even just-expired: local state is current and the
            # next cycle's get_token renews/re-acquires.  Comparing our own
            # write against our own clock can't skew-flap.
            return None
        if self._clock() >= lease.get("expiry", 0):
            return ""  # expired foreign lease: election gap, retry
        return lease.get("address") or ""

    def current_generation(self) -> int:
        """The record's fencing generation, whoever holds it (0 before any
        election).  Read-only: a deposed leader peeking here must not renew
        itself back into authority."""
        lease = self._locked(self._read)
        return int(lease["generation"]) if lease else 0

    # --- lease file access (always under flock) -----------------------------

    def _locked(self, fn):
        os.makedirs(os.path.dirname(os.path.abspath(self._path)), exist_ok=True)
        with open(self._path + ".lock", "w") as lockf:
            fcntl.flock(lockf, fcntl.LOCK_EX)
            return fn()

    def _read(self) -> Optional[dict]:
        try:
            with open(self._path) as f:
                return json.load(f)
        except (FileNotFoundError, json.JSONDecodeError):
            return None

    def _write(self, lease: dict) -> None:
        # Election records are durable state files: the shared helper adds
        # the directory fsync a hand-rolled tmp+rename misses
        # (core/statefile.py; armada-lint atomic-state-file).
        from armada_tpu.core import statefile

        statefile.write_json(self._path, lease)

    # --- LeaderController ---------------------------------------------------

    def get_token(self) -> LeaderToken:
        def attempt() -> LeaderToken:
            now = self._clock()
            lease = self._read()
            if lease is None or now >= lease["expiry"]:
                generation = (lease["generation"] + 1) if lease else 1
                self._write(
                    {
                        "holder": self._holder,
                        "generation": generation,
                        "expiry": now + self._duration,
                        "address": self._address,
                    }
                )
                return LeaderToken(leader=True, generation=generation)
            if lease["holder"] == self._holder:
                # renew
                lease["expiry"] = now + self._duration
                lease["address"] = self._address
                self._write(lease)
                return LeaderToken(leader=True, generation=lease["generation"])
            return LeaderToken(leader=False, generation=lease["generation"])

        return self._locked(attempt)

    def validate_token(self, token: LeaderToken) -> bool:
        if not token.leader:
            return False

        def check() -> bool:
            lease = self._read()
            return (
                lease is not None
                and lease["holder"] == self._holder
                and lease["generation"] == token.generation
                and self._clock() < lease["expiry"]
            )

        return self._locked(check)
