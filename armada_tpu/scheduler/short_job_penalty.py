"""Short-job penalty: recently-exited short jobs keep charging their queue.

Equivalent of the reference's ShortJobPenalty (internal/scheduler/scheduling/
short_job_penalty.go:1-53): a job that exits sooner than the pool's cutoff
after it started RUNNING is treated, for DRF cost purposes, as if it were
still holding its resources until the cutoff passes.  This stops queues from
churning streams of instant-exit jobs to stay under their fair share.

Two integration points mirror the reference:
- JobDb retention: terminal jobs are kept in the JobDb while the penalty
  applies (scheduler.go:436-447 skips deleting them), so the scheduling algo
  can still see them.  Unlike the reference (which only re-examines changed
  jobs and so never deletes a retained job that stops changing), the
  scheduler sweeps retained jobs each cycle and deletes them once the window
  lapses.
- Cost: each queue's candidate-ordering DRF cost includes the penalty
  (queue_scheduler.go:514-515 GetAllocationInclShortJobPenalty); fair shares,
  caps and the eviction protected-share check do NOT (pqs.go:146-157 uses
  GetAllocation).
"""

from __future__ import annotations

from typing import Mapping

from armada_tpu.jobdb.job import Job


class ShortJobPenalty:
    """Pool-keyed penalty window (short_job_penalty.go ShouldApplyPenalty)."""

    def __init__(self, cutoffs_by_pool_s: Mapping[str, float]):
        self._cutoff_ns = {
            pool: int(sec * 1e9)
            for pool, sec in cutoffs_by_pool_s.items()
            if sec > 0
        }

    @property
    def enabled(self) -> bool:
        return bool(self._cutoff_ns)

    def applies(self, job: Job, now_ns: int) -> bool:
        """True while `job` should keep charging its queue (terminal, started
        recently, not preempted -- short_job_penalty.go:29-52)."""
        if not self._cutoff_ns or not job.in_terminal_state():
            return False
        run = job.latest_run
        if run is None or run.preempted or run.preempt_requested:
            return False
        if run.running_ns <= 0:
            return False
        cutoff = self._cutoff_ns.get(run.pool or "default", 0)
        return now_ns - run.running_ns < cutoff
