"""Ingest-plane observability: per-consumer events/s + per-partition lag.

A process-global registry (the watchdog-supervisor / slo-recorder pattern):
each running ingestion pipeline -- serial or partition-parallel -- registers
an adapter; /healthz embeds the snapshot as its `ingest` block and
SchedulerMetrics mirrors it to prometheus
(armada_ingest_lag_bytes{consumer,partition},
armada_ingest_events_per_second{consumer}) with stale-label removal.

The rate is a decayed-impulse estimator (the Unix load-average shape): each
applied batch adds n/tau and the whole estimate decays exp(-dt/tau), so the
value converges to the true arrival rate without keeping per-event
timestamps.  All clocks are monotonic (ops/metrics.mono_now).
"""

from __future__ import annotations

import logging
import math
from typing import Callable, Optional

from armada_tpu.analysis.tsan import make_lock
from armada_tpu.ops.metrics import mono_now

log = logging.getLogger(__name__)


class RateEstimator:
    """Exponentially-decayed event rate (events/second)."""

    def __init__(self, tau_s: float = 30.0):
        self.tau_s = tau_s
        self._rate = 0.0
        self._last = mono_now()
        self._lock = make_lock("ingest.rate")

    def record(self, n: int) -> None:
        now = mono_now()
        with self._lock:
            dt = max(0.0, now - self._last)
            self._rate = self._rate * math.exp(-dt / self.tau_s) + n / self.tau_s
            self._last = now

    def value(self) -> float:
        now = mono_now()
        with self._lock:
            dt = max(0.0, now - self._last)
            return self._rate * math.exp(-dt / self.tau_s)


class IngestStatsRegistry:
    """consumer name -> snapshot callable of the pipeline serving it."""

    def __init__(self):
        self._lock = make_lock("ingest.stats")
        self._sources: dict[str, Callable[[], dict]] = {}
        # Snapshot exceptions per view: counted (the metrics layer exports
        # armada_ingest_stats_errors_total{consumer}) and logged once per
        # registered view -- a broken snapshot used to be swallowed
        # entirely, so a view could misreport forever in silence.
        self._errors: dict[str, int] = {}
        self._logged: set[str] = set()

    def register(self, consumer: str, snapshot_fn: Callable[[], dict]) -> None:
        with self._lock:
            self._sources[consumer] = snapshot_fn
            # A re-registered (restarted) view gets one fresh log line if it
            # breaks again; the error count keeps accumulating.
            self._logged.discard(consumer)

    def unregister(self, consumer: str, snapshot_fn: Callable[[], dict]) -> None:
        """Remove `consumer` only if it still points at `snapshot_fn` -- a
        stopped pipeline must not evict its replacement (restart races)."""
        with self._lock:
            if self._sources.get(consumer) is snapshot_fn:
                del self._sources[consumer]

    def error_counts(self) -> dict[str, int]:
        with self._lock:
            return dict(self._errors)

    def snapshot(self) -> dict:
        with self._lock:
            sources = dict(self._sources)
        out = {}
        for consumer, fn in sources.items():
            try:
                out[consumer] = fn()
            except Exception as exc:  # noqa: BLE001 - one broken view must
                out[consumer] = {"error": str(exc)}  # not hide the others
                with self._lock:
                    self._errors[consumer] = self._errors.get(consumer, 0) + 1
                    first = consumer not in self._logged
                    self._logged.add(consumer)
                if first:
                    log.exception(
                        "ingest stats snapshot failed for view %r "
                        "(logged once per registration; "
                        "armada_ingest_stats_errors_total counts repeats)",
                        consumer,
                    )
        return out


_registry: Optional[IngestStatsRegistry] = None
_registry_lock = make_lock("ingest.stats.global")


def registry() -> IngestStatsRegistry:
    global _registry
    with _registry_lock:
        if _registry is None:
            _registry = IngestStatsRegistry()
        return _registry


def reset_registry() -> IngestStatsRegistry:
    """Fresh process-global registry (tests)."""
    global _registry
    with _registry_lock:
        _registry = IngestStatsRegistry()
        return _registry
