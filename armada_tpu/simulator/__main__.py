"""Simulator CLI: `python -m armada_tpu.simulator --clusters c.yaml --workloads w.yaml`.

Equivalent of the reference's `cmd/simulator` (cmd/simulator/cmd/root.go:18-35):
runs every (cluster, workload) pair, prints a summary per pair, optionally
writes per-cycle JSONL/parquet.
"""

from __future__ import annotations

import argparse
import os
import sys
import time


def main(argv=None) -> int:
    from armada_tpu.core.platform import respect_jax_platforms_env

    respect_jax_platforms_env()
    ap = argparse.ArgumentParser(prog="armada-tpu-simulator")
    ap.add_argument("--clusters", nargs="+", required=True, help="cluster spec YAMLs")
    ap.add_argument("--workloads", nargs="+", required=True, help="workload spec YAMLs")
    ap.add_argument("--config", help="scheduling config YAML (reference schema)")
    ap.add_argument("--schedule-interval", type=float, default=10.0)
    ap.add_argument("--output", help="per-cycle JSONL output path")
    ap.add_argument("--parquet", help="per-cycle parquet output path")
    args = ap.parse_args(argv)

    from armada_tpu.core.config import (
        default_scheduling_config,
        scheduling_config_from_yaml,
    )
    from armada_tpu.simulator import (
        JsonlSink,
        Simulator,
        cluster_spec_from_yaml,
        workload_spec_from_yaml,
        write_parquet,
    )

    config = (
        scheduling_config_from_yaml(args.config)
        if args.config
        else default_scheduling_config()
    )

    def pair_path(base: str, tag: str) -> str:
        if len(args.clusters) == 1 and len(args.workloads) == 1:
            return base
        root, ext = os.path.splitext(base)
        return f"{root}-{tag}{ext}"

    clusters = {c: cluster_spec_from_yaml(c) for c in args.clusters}
    workloads = {w: workload_spec_from_yaml(w) for w in args.workloads}
    for cpath in args.clusters:
        for wpath in args.workloads:
            cluster = clusters[cpath]
            workload = workloads[wpath]
            tag = (
                f"{os.path.splitext(os.path.basename(cpath))[0]}"
                f"-{os.path.splitext(os.path.basename(wpath))[0]}"
            )
            sink = JsonlSink(pair_path(args.output, tag)) if args.output else None
            t0 = time.perf_counter()
            sim = Simulator(
                cluster,
                workload,
                config,
                schedule_interval_s=args.schedule_interval,
                sink=sink,
            )
            result = sim.run()
            wall = time.perf_counter() - t0
            if sink:
                sink.close(result)
            if args.parquet:
                write_parquet(result, pair_path(args.parquet, tag))
            print(
                f"{cluster.name!r} x {workload.name!r}: "
                f"makespan={result.makespan:.0f}s scheduled={result.total_scheduled} "
                f"succeeded={result.total_succeeded} preempted={result.total_preempted} "
                f"failed={result.total_failed} never_scheduled={len(result.never_scheduled)} "
                f"cycles={len(result.cycles)} wall={wall:.2f}s"
            )
    return 0


if __name__ == "__main__":
    sys.exit(main())
