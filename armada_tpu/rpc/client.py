"""Client library over the gRPC services.

Equivalent of the reference's pkg/client (Go) / client/python bindings:
`ArmadaClient` speaks Submit + Event, `ExecutorApiClient` speaks ExecutorApi
and is a drop-in for the in-process ExecutorApi object (ExecutorService only
needs lease_job_runs/report_events), so the same agent code runs in-process
or across the wire.
"""

from __future__ import annotations

from typing import Iterator, Optional, Sequence

import grpc

from armada_tpu.rpc import convert, rpc_pb2 as pb
from armada_tpu.scheduler.api import LeaseRequest, LeaseResponse
from armada_tpu.server.eventapi import JobSetEvent
from armada_tpu.server.queues import QueueRecord
from armada_tpu.server.submit import JobSubmitItem

_PRINCIPAL_KEY = "x-armada-principal"
_GROUPS_KEY = "x-armada-groups"
_TRACE_KEY = "x-armada-trace-id"


class _Base:
    def __init__(
        self,
        address: str,
        principal: str = "anonymous",
        groups: Sequence[str] = (),
        channel: Optional[grpc.Channel] = None,
        bearer_token: Optional[str] = None,
        basic_auth: Optional[tuple[str, str]] = None,
        negotiate=None,
    ):
        """principal/groups ride trusted headers (dev chains only);
        bearer_token / basic_auth produce a standard `authorization` header
        for OIDC / token-review / basic authenticators (pkg/client/auth).
        negotiate: kerberos/SPNEGO -- bytes (one call: AP-REQ tokens are
        single-use, the server replay-caches them) or a zero-arg callable
        minting a FRESH token per request (e.g. a gssapi initiator)."""
        if channel is None:
            # Mirror the server's transport hardening: the default 4MB
            # receive cap would reject a large lease/queue response the
            # server is now allowed to send, and client-side keepalive
            # keeps long idle watches alive across NATs/proxies.
            from armada_tpu.rpc.transport import channel_options

            channel = grpc.insecure_channel(
                address, options=channel_options()
            )
        self._channel = channel
        self._static_meta = [(_PRINCIPAL_KEY, principal)]
        if groups:
            self._static_meta.append((_GROUPS_KEY, ",".join(groups)))
        if bearer_token:
            self._static_meta.append(
                ("authorization", f"Bearer {bearer_token}")
            )
        elif basic_auth:
            import base64

            cred = base64.b64encode(
                f"{basic_auth[0]}:{basic_auth[1]}".encode()
            ).decode()
            self._static_meta.append(("authorization", f"Basic {cred}"))
        self._negotiate = negotiate

    @property
    def _meta(self):
        if self._negotiate is None:
            return self._static_meta
        import base64

        token = self._negotiate() if callable(self._negotiate) else self._negotiate
        if isinstance(token, str):
            token = token.encode()
        return self._static_meta + [
            ("authorization", "Negotiate " + base64.b64encode(token).decode())
        ]

    def close(self) -> None:
        self._channel.close()

    def _unary(self, path: str, req, resp_cls, extra_metadata=()):
        call = self._channel.unary_unary(
            path,
            request_serializer=lambda m: m.SerializeToString(),
            response_deserializer=resp_cls.FromString,
        )
        meta = self._meta
        if extra_metadata:
            meta = list(meta) + list(extra_metadata)
        return call(req, metadata=meta)


class ArmadaClient(_Base):
    """Submit + Event client (pkg/client submit.go / watch.go)."""

    # --- submission ---------------------------------------------------------

    def submit_jobs(
        self, queue: str, jobset: str, items: Sequence[JobSubmitItem]
    ) -> list[str]:
        resp = self._unary(
            "/armada_tpu.api.Submit/SubmitJobs",
            pb.SubmitJobsRequest(
                queue=queue,
                jobset=jobset,
                items=[convert.submit_item_to_proto(i) for i in items],
            ),
            pb.SubmitJobsResponse,
        )
        return list(resp.job_ids)

    def cancel_jobs(
        self, queue: str, jobset: str, job_ids: Sequence[str], reason: str = ""
    ) -> None:
        self._unary(
            "/armada_tpu.api.Submit/CancelJobs",
            pb.CancelJobsRequest(
                queue=queue, jobset=jobset, job_ids=list(job_ids), reason=reason
            ),
            pb.Empty,
        )

    def cancel_jobset(
        self, queue: str, jobset: str, states: Sequence[str] = (), reason: str = ""
    ) -> None:
        self._unary(
            "/armada_tpu.api.Submit/CancelJobSet",
            pb.CancelJobSetRequest(
                queue=queue, jobset=jobset, states=list(states), reason=reason
            ),
            pb.Empty,
        )

    def preempt_jobs(
        self, queue: str, jobset: str, job_ids: Sequence[str], reason: str = ""
    ) -> None:
        self._unary(
            "/armada_tpu.api.Submit/PreemptJobs",
            pb.PreemptJobsRequest(
                queue=queue, jobset=jobset, job_ids=list(job_ids), reason=reason
            ),
            pb.Empty,
        )

    def reprioritize_jobs(
        self,
        queue: str,
        jobset: str,
        priority: int,
        job_ids: Sequence[str] = (),
    ) -> None:
        self._unary(
            "/armada_tpu.api.Submit/ReprioritizeJobs",
            pb.ReprioritizeJobsRequest(
                queue=queue,
                jobset=jobset,
                priority=priority,
                job_ids=list(job_ids),
            ),
            pb.Empty,
        )

    # --- queues -------------------------------------------------------------

    def create_queue(self, record: QueueRecord) -> None:
        self._unary(
            "/armada_tpu.api.Submit/CreateQueue",
            convert.queue_to_proto(record),
            pb.Empty,
        )

    def update_queue(self, record: QueueRecord) -> None:
        self._unary(
            "/armada_tpu.api.Submit/UpdateQueue",
            convert.queue_to_proto(record),
            pb.Empty,
        )

    def delete_queue(self, name: str) -> None:
        self._unary(
            "/armada_tpu.api.Submit/DeleteQueue",
            pb.QueueGetRequest(name=name),
            pb.Empty,
        )

    def get_queue(self, name: str) -> QueueRecord:
        resp = self._unary(
            "/armada_tpu.api.Submit/GetQueue",
            pb.QueueGetRequest(name=name),
            pb.Queue,
        )
        return convert.queue_from_proto(resp)

    def list_queues(self) -> list[QueueRecord]:
        resp = self._unary(
            "/armada_tpu.api.Submit/ListQueues", pb.Empty(), pb.QueueListResponse
        )
        return [convert.queue_from_proto(q) for q in resp.queues]

    # --- lookout queries ----------------------------------------------------

    def get_jobs(
        self,
        filters=(),
        order=None,
        skip: int = 0,
        take: int = 100,
    ) -> list[dict]:
        """filters: list of dicts {field, value, match, annotation_key};
        order: {field, direction}."""
        import json

        resp = self._unary(
            "/armada_tpu.api.Lookout/GetJobs",
            pb.LookoutQuery(
                query_json=json.dumps(
                    {
                        "filters": list(filters),
                        "order": order,
                        "skip": skip,
                        "take": take,
                    }
                )
            ),
            pb.JsonResponse,
        )
        return json.loads(resp.json)

    def group_jobs(
        self,
        group_by: str,
        filters=(),
        take: int = 100,
        aggregates=("state",),
        annotation_key: str = "",
    ) -> list[dict]:
        import json

        resp = self._unary(
            "/armada_tpu.api.Lookout/GroupJobs",
            pb.LookoutQuery(
                query_json=json.dumps(
                    {
                        "group_by": group_by,
                        "filters": list(filters),
                        "take": take,
                        "aggregates": list(aggregates),
                        "annotation_key": annotation_key,
                    }
                )
            ),
            pb.JsonResponse,
        )
        return json.loads(resp.json)

    def get_job_details(self, job_id: str) -> dict:
        import json

        resp = self._unary(
            "/armada_tpu.api.Lookout/GetJobDetails",
            pb.QueueGetRequest(name=job_id),
            pb.JsonResponse,
        )
        return json.loads(resp.json)

    # --- executor admin (control-plane events) ------------------------------

    def upsert_executor_settings(
        self, name: str, cordoned: bool, cordon_reason: str = ""
    ) -> None:
        self._unary(
            "/armada_tpu.api.ExecutorAdmin/UpsertExecutorSettings",
            pb.ExecutorSettingsUpsertRequest(
                name=name, cordoned=cordoned, cordon_reason=cordon_reason
            ),
            pb.Empty,
        )

    def delete_executor_settings(self, name: str) -> None:
        self._unary(
            "/armada_tpu.api.ExecutorAdmin/DeleteExecutorSettings",
            pb.ExecutorSettingsDeleteRequest(name=name),
            pb.Empty,
        )

    def preempt_on_executor(
        self,
        name: str,
        queues: Sequence[str] = (),
        priority_classes: Sequence[str] = (),
    ) -> None:
        self._unary(
            "/armada_tpu.api.ExecutorAdmin/PreemptOnExecutor",
            pb.ExecutorScopedActionRequest(
                name=name,
                queues=list(queues),
                priority_classes=list(priority_classes),
            ),
            pb.Empty,
        )

    def cancel_on_executor(
        self,
        name: str,
        queues: Sequence[str] = (),
        priority_classes: Sequence[str] = (),
    ) -> None:
        self._unary(
            "/armada_tpu.api.ExecutorAdmin/CancelOnExecutor",
            pb.ExecutorScopedActionRequest(
                name=name,
                queues=list(queues),
                priority_classes=list(priority_classes),
            ),
            pb.Empty,
        )

    def preempt_on_queue(
        self, name: str, priority_classes: Sequence[str] = ()
    ) -> None:
        self._unary(
            "/armada_tpu.api.ExecutorAdmin/PreemptOnQueue",
            pb.QueueScopedActionRequest(
                name=name, priority_classes=list(priority_classes)
            ),
            pb.Empty,
        )

    def cancel_on_queue(
        self,
        name: str,
        priority_classes: Sequence[str] = (),
        job_states: Sequence[str] = (),
    ) -> None:
        self._unary(
            "/armada_tpu.api.ExecutorAdmin/CancelOnQueue",
            pb.QueueScopedActionRequest(
                name=name,
                priority_classes=list(priority_classes),
                job_states=list(job_states),
            ),
            pb.Empty,
        )

    # --- checkpoints (armadactl checkpoint; scheduler/checkpoint.py) --------

    def trigger_checkpoint(self) -> dict:
        resp = self._unary(
            "/armada_tpu.api.ExecutorAdmin/TriggerCheckpoint",
            pb.Empty(),
            pb.CheckpointTriggerResponse,
        )
        return {
            "path": resp.path,
            "created_ns": resp.created_ns,
            "epoch": resp.epoch,
            "fenced_offset_total": resp.fenced_offset_total,
        }

    def checkpoint_status(self) -> dict:
        import json

        resp = self._unary(
            "/armada_tpu.api.ExecutorAdmin/CheckpointStatus",
            pb.Empty(),
            pb.CheckpointStatusResponse,
        )
        return json.loads(resp.status_json)

    # --- cycle traces (armadactl trace; ops/trace.py) -----------------------

    def dump_trace(self) -> dict:
        """The plane's last N cycle span trees (offset form); feed to
        ops/trace.chrome_trace for a Perfetto-loadable file."""
        import json

        resp = self._unary(
            "/armada_tpu.api.ExecutorAdmin/DumpTrace",
            pb.Empty(),
            pb.JsonResponse,
        )
        return json.loads(resp.json)

    # --- device quarantine (armadactl quarantine; scheduler/quarantine.py) --

    def quarantine_status(self) -> dict:
        """The round-verification ledger + device quarantine scoreboard
        (the same block /healthz embeds)."""
        import json

        resp = self._unary(
            "/armada_tpu.api.ExecutorAdmin/QuarantineStatus",
            pb.Empty(),
            pb.JsonResponse,
        )
        return json.loads(resp.json)

    def quarantine_clear(self, device: str = "") -> dict:
        """Clear the device quarantine (one device, or all when empty);
        the next healthy re-probe may then promote back."""
        import json

        resp = self._unary(
            "/armada_tpu.api.ExecutorAdmin/QuarantineClear",
            pb.QueueGetRequest(name=device),
            pb.JsonResponse,
        )
        return json.loads(resp.json)

    # --- dead letters (armadactl dlq; ingest/dlq.py) ------------------------

    def dlq_status(self) -> dict:
        """Quarantine census + pending control-plane halts (the /healthz
        ``dlq`` block plus per-store row counts)."""
        import json

        resp = self._unary(
            "/armada_tpu.api.ExecutorAdmin/DlqStatus",
            pb.Empty(),
            pb.JsonResponse,
        )
        return json.loads(resp.json)

    def dlq_list(self, selector: str = "") -> list:
        """Quarantined rows matching 'consumer[:partition[:offset]]'
        (payload omitted; sizes only)."""
        import json

        resp = self._unary(
            "/armada_tpu.api.ExecutorAdmin/DlqList",
            pb.QueueGetRequest(name=selector),
            pb.JsonResponse,
        )
        return json.loads(resp.json)

    def dlq_show(self, selector: str) -> dict:
        """One full dead-letter row (key/payload base64-encoded); the
        selector must be a full consumer:partition:offset triple."""
        import json

        resp = self._unary(
            "/armada_tpu.api.ExecutorAdmin/DlqShow",
            pb.QueueGetRequest(name=selector),
            pb.JsonResponse,
        )
        return json.loads(resp.json)

    def dlq_replay(self, selector: str = "") -> dict:
        """Re-publish matching dead rows' raw bytes and mark them
        replayed.  Run only after fixing the poison's cause."""
        import json

        resp = self._unary(
            "/armada_tpu.api.ExecutorAdmin/DlqReplay",
            pb.QueueGetRequest(name=selector),
            pb.JsonResponse,
        )
        return json.loads(resp.json)

    def dlq_discard(self, selector: str) -> dict:
        """Approve a pending control-plane skip or mark rows discarded."""
        import json

        resp = self._unary(
            "/armada_tpu.api.ExecutorAdmin/DlqDiscard",
            pb.QueueGetRequest(name=selector),
            pb.JsonResponse,
        )
        return json.loads(resp.json)

    # --- scheduling reports -------------------------------------------------

    def get_job_report(self, job_id: str) -> dict:
        import json

        resp = self._unary(
            "/armada_tpu.api.Reports/GetJobReport",
            pb.QueueGetRequest(name=job_id),
            pb.JsonResponse,
        )
        return json.loads(resp.json)

    def get_queue_report(self, queue: str) -> list[dict]:
        import json

        resp = self._unary(
            "/armada_tpu.api.Reports/GetQueueReport",
            pb.QueueGetRequest(name=queue),
            pb.JsonResponse,
        )
        return json.loads(resp.json)

    def get_pool_report(self, pool: str = "") -> dict:
        import json

        resp = self._unary(
            "/armada_tpu.api.Reports/GetPoolReport",
            pb.QueueGetRequest(name=pool),
            pb.JsonResponse,
        )
        return json.loads(resp.json)

    # --- events -------------------------------------------------------------

    def get_jobset_events(
        self, queue: str, jobset: str, from_idx: int = 0
    ) -> list[JobSetEvent]:
        return list(self._events(queue, jobset, from_idx, watch=False))

    def watch(
        self,
        queue: str,
        jobset: str,
        from_idx: int = 0,
        idle_timeout_s: float = 0.0,
    ) -> Iterator[JobSetEvent]:
        return self._events(
            queue, jobset, from_idx, watch=True, idle_timeout_s=idle_timeout_s
        )

    def _events(
        self,
        queue: str,
        jobset: str,
        from_idx: int,
        watch: bool,
        idle_timeout_s: float = 0.0,
    ) -> Iterator[JobSetEvent]:
        call = self._channel.unary_stream(
            "/armada_tpu.api.Event/GetJobSetEvents",
            request_serializer=lambda m: m.SerializeToString(),
            response_deserializer=pb.JobSetEventMessage.FromString,
        )
        stream = call(
            pb.JobSetEventsRequest(
                queue=queue,
                jobset=jobset,
                from_idx=from_idx,
                watch=watch,
                idle_timeout_s=idle_timeout_s,
            ),
            metadata=self._meta,
        )
        for msg in stream:
            yield JobSetEvent(int(msg.idx), msg.sequence)


class BinocularsClient(_Base):
    """Per-cluster logs + cordon client (pkg/api/binoculars)."""

    def logs(self, job_id: str = "", run_id: str = "") -> str:
        resp = self._unary(
            "/armada_tpu.api.Binoculars/Logs",
            pb.LogsRequest(job_id=job_id, run_id=run_id),
            pb.LogsResponse,
        )
        return resp.log

    def cordon(self, node_id: str) -> None:
        self._unary(
            "/armada_tpu.api.Binoculars/Cordon",
            pb.CordonRequest(node_id=node_id),
            pb.Empty,
        )

    def uncordon(self, node_id: str) -> None:
        self._unary(
            "/armada_tpu.api.Binoculars/Cordon",
            pb.CordonRequest(node_id=node_id, uncordon=True),
            pb.Empty,
        )


class ExecutorApiClient(_Base):
    """Drop-in wire replacement for the in-process ExecutorApi.

    `factory` should be the executor's ResourceListFactory so queue_usage
    axis names serialize against the true axis order (convert.py
    snapshot_to_proto); without it the names are inferred from node
    payloads."""

    def __init__(self, *args, factory=None, **kwargs):
        super().__init__(*args, **kwargs)
        self._factory = factory

    def lease_job_runs(self, request: LeaseRequest) -> LeaseResponse:
        resp = self._unary(
            "/armada_tpu.api.ExecutorApi/LeaseJobRuns",
            convert.lease_request_to_proto(request, self._factory),
            pb.LeaseJobRunsResponse,
        )
        return convert.lease_response_from_proto(resp)

    def report_events(self, sequences) -> None:
        self._unary(
            "/armada_tpu.api.ExecutorApi/ReportEvents",
            pb.ReportEventsRequest(sequences=list(sequences)),
            pb.Empty,
        )


def job_state_of(job) -> "pb.JobState":
    """jobdb Job -> JobState wire message: what a mirroring control plane
    sends in SyncState (the Go caller builds the equivalent from ITS jobDb
    rows, jobdb/job.go)."""
    from armada_tpu.events.convert import job_spec_to_proto

    msg = pb.JobState(
        job_id=job.id,
        queue=job.queue,
        jobset=job.jobset,
        spec=job_spec_to_proto(job.spec),
        priority=int(job.priority),
        queued=job.queued,
        validated=job.validated,
        pools=list(job.pools),
        terminal=job.in_terminal_state(),
        banned_nodes=list(job.anti_affinity_nodes()),
        submit_time=job.spec.submit_time,
    )
    # Live runs always ride; a TERMINAL job's final run rides too -- the
    # short-job penalty needs its pool + running_ns to keep charging the
    # queue (short_job_penalty.py applies()).
    run = job.latest_run
    if run is not None and (
        not run.in_terminal_state() or job.in_terminal_state()
    ):
        msg.run.MergeFrom(
            pb.JobRunState(
                run_id=run.id,
                node_id=run.node_id,
                node_name=run.node_name,
                executor=run.executor,
                pool=run.pool,
                scheduled_at_priority=run.scheduled_at_priority or 0,
                has_scheduled_at_priority=run.scheduled_at_priority is not None,
                away=run.pool_scheduled_away,
                running=run.running,
                running_ns=run.running_ns,
                preempted=run.preempted or run.preempt_requested,
            )
        )
    return msg


class ScheduleClient(_Base):
    """Client for the scheduling sidecar (armada_tpu.api.Schedule): mirror
    job/executor/queue state into a server-side session, then drive rounds.
    The reference-Go-colocation client would be generated from rpc.proto;
    this is the same wire surface from python.

    Cycle tracing (ops/trace.py): when the CALLER has an active cycle
    trace, sync/round calls propagate its trace id as gRPC metadata and
    ``schedule_round`` grafts the server's returned round spans under the
    RPC span -- one stitched cross-process tree, no clock agreement needed
    (spans travel as offsets)."""

    @staticmethod
    def _active_trace():
        """(recorder, trace_id) when a cycle trace is open, else (None, "")."""
        from armada_tpu.ops.trace import recorder

        rec = recorder()
        active = rec.active()
        if active is None or not rec.enabled:
            return None, ""
        return rec, active.trace_id

    def create_session(
        self, session_id: str = "", config_yaml: str = ""
    ) -> str:
        resp = self._unary(
            "/armada_tpu.api.Schedule/CreateSession",
            pb.ScheduleSessionConfig(
                session_id=session_id, config_yaml=config_yaml
            ),
            pb.ScheduleSessionHandle,
        )
        return resp.session_id

    def sync_state(
        self,
        session_id: str,
        jobs=(),
        deleted_job_ids=(),
        executors=None,
        queues=None,
        bids=None,
        factory=None,
    ) -> None:
        """jobs: JobState messages (see job_state_of) or jobdb Jobs;
        executors: ExecutorSnapshot dataclasses (None = leave unchanged);
        queues: core Queue sequence (None = leave unchanged);
        bids: {(queue, band, pool): price} (None = leave unchanged)."""
        msg = pb.SyncStateRequest(session_id=session_id)
        for j in jobs:
            msg.jobs.append(j if isinstance(j, pb.JobState) else job_state_of(j))
        msg.deleted_job_ids.extend(deleted_job_ids)
        if executors is not None:
            msg.set_executors = True
            for e in executors:
                msg.executors.append(convert.snapshot_to_proto(e, factory))
        if queues is not None:
            msg.set_queues = True
            for q in queues:
                msg.queues.append(pb.Queue(name=q.name, weight=q.weight))
        if bids is not None:
            msg.set_bids = True
            by_queue = {}
            for (queue, band, pool), price in bids.items():
                by_queue.setdefault(queue, []).append(
                    pb.PriceBandBid(band=band, pool=pool, price=price)
                )
            for queue, items in by_queue.items():
                msg.bids.queues.append(pb.QueueBids(queue=queue, bids=items))
        rec, tid = self._active_trace()
        if rec is None:
            self._unary("/armada_tpu.api.Schedule/SyncState", msg, pb.Empty)
            return
        with rec.span("rpc_sync_state", session=session_id):
            self._unary(
                "/armada_tpu.api.Schedule/SyncState",
                msg,
                pb.Empty,
                extra_metadata=((_TRACE_KEY, tid),),
            )

    def schedule_round(
        self,
        session_id: str,
        now_ns: int = 0,
        quarantined_node_ids=(),
    ) -> "pb.ScheduleRoundResponse":
        req = pb.ScheduleRoundRequest(
            session_id=session_id,
            now_ns=now_ns,
            quarantined_node_ids=list(quarantined_node_ids),
        )
        rec, tid = self._active_trace()
        if rec is None:
            return self._unary(
                "/armada_tpu.api.Schedule/ScheduleRound",
                req,
                pb.ScheduleRoundResponse,
            )
        with rec.span("rpc_schedule_round", session=session_id):
            resp = self._unary(
                "/armada_tpu.api.Schedule/ScheduleRound",
                req,
                pb.ScheduleRoundResponse,
                extra_metadata=((_TRACE_KEY, tid),),
            )
            # Stitch: the server shipped its round's span tree because we
            # sent a trace id; graft it under this RPC span.
            import json

            try:
                remote = json.loads(resp.pool_stats_json or "{}").get("trace")
            except ValueError:
                remote = None
            if remote:
                rec.graft(remote)
        return resp

    def close_session(self, session_id: str) -> None:
        self._unary(
            "/armada_tpu.api.Schedule/CloseSession",
            pb.ScheduleSessionHandle(session_id=session_id),
            pb.Empty,
        )


class ReplicationClient(_Base):
    """Tail a replica's durable event log (armada_tpu.api.LogReplication):
    the follower side of cross-host HA (eventlog/replicator.py)."""

    def get_log_info(self):
        return self._unary(
            "/armada_tpu.api.LogReplication/GetLogInfo",
            pb.LogInfoRequest(),
            pb.LogInfoResponse,
        )

    def tail_log(
        self,
        partition: int,
        from_offset: int = 0,
        follow: bool = False,
        idle_timeout_s: float = 0.0,
    ):
        call = self._channel.unary_stream(
            "/armada_tpu.api.LogReplication/TailLog",
            request_serializer=lambda m: m.SerializeToString(),
            response_deserializer=pb.LogRecord.FromString,
        )
        yield from call(
            pb.TailLogRequest(
                partition=partition,
                from_offset=from_offset,
                follow=follow,
                idle_timeout_s=idle_timeout_s,
            ),
            metadata=self._meta,
        )
