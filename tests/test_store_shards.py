"""Sharded materialized stores (ingest/storeunion.py): the round-19 contract.

The parity pin: draining the SAME churned event log into the plain
single-writer SchedulerDb (serial pipeline) and into a ShardedSchedulerDb
(partition-parallel pipeline, one store file per store shard) must
materialize bit-equal state through the union read surface -- raw serial
columns excluded, as everywhere (allocation order differs across concurrent
shard commits; see tests/test_ingest_shards.py).  Plus the per-shard crash
drill (a committed-but-unacked batch in ONE shard's file must not
double-apply on restart), checkpoint export/restore across shard files
(including a width-changing restore, which re-routes every row), the
committed-horizon clamp that keeps the single-cursor fetch sound, width
permanence, and globals routing (one home per non-partition-owned row)."""

from __future__ import annotations

import pytest

from armada_tpu.eventlog import EventLog, Publisher
from armada_tpu.events import events_pb2 as pb
from armada_tpu.ingest import (
    IngestionPipeline,
    PartitionedIngestionPipeline,
    SchedulerDb,
    convert_sequences,
)
from armada_tpu.ingest.schedulerdb import SerialAllocator
from armada_tpu.ingest.storeunion import ShardedLookoutDb, ShardedSchedulerDb
from armada_tpu.lookout import LookoutDb, lookout_converter
from armada_tpu.server.queues import QueueRecord
from tests.control_plane import ControlPlane
from tests.test_ingest_shards import _churn_plane, _materialized, _serial_replay

STORE_SHARDS = 2
INGEST_SHARDS = 4  # must be a multiple of STORE_SHARDS


def _sharded_db(tmp_path, name="store-shards", shards=STORE_SHARDS, parts=4):
    return ShardedSchedulerDb(
        str(tmp_path / name), num_shards=shards, num_partitions=parts
    )


def _sharded_drain(
    log, db, consumer="scheduler", converter=convert_sequences, resume=False
):
    pipe = PartitionedIngestionPipeline(
        log,
        db,
        converter,
        consumer_name=consumer,
        num_shards=INGEST_SHARDS,
        convert_mode="inline",
        start_positions=db.positions(consumer) if resume else None,
    )
    return pipe.run_until_caught_up()


# --------------------------------------------------------------- equality ----


@pytest.mark.parametrize("seed,mode", [(0, "process"), (1, "inline"), (2, "inline")])
def test_sharded_store_bit_equal_serial_over_churn(
    tmp_path, monkeypatch, seed, mode
):
    """The satellite equality pin, under the tsan race harness: serial
    single-writer vs W-file sharded store over real churn; seed 0
    additionally routes conversion through the subprocess pool (the
    production sharded shape: columnar plans land via store_plan in each
    shard's own file)."""
    from armada_tpu.analysis import tsan

    monkeypatch.setenv("ARMADA_INGEST_SHARDS", str(INGEST_SHARDS))
    monkeypatch.setenv("ARMADA_INGEST_CONVERT", "inline")
    plane = _churn_plane(tmp_path, seed)
    tsan_was = tsan.enabled()
    monkeypatch.setenv("ARMADA_TSAN", "1")
    tsan.enable()
    tsan.reset()
    try:
        db_serial = _serial_replay(plane.log)
        db_sharded = _sharded_db(
            tmp_path, parts=plane.log.num_partitions
        )
        pipe = PartitionedIngestionPipeline(
            plane.log,
            db_sharded,
            convert_sequences,
            consumer_name="scheduler",
            num_shards=INGEST_SHARDS,
            convert_mode=mode,
        )
        n = pipe.run_until_caught_up()
        assert n > 0
        assert _materialized(db_serial) == _materialized(db_sharded)
        assert db_serial.positions("scheduler") == db_sharded.positions(
            "scheduler"
        )
        # the union fetch surface agrees row-for-row with the plain store
        # (serial VALUES differ; compare the job identity + state columns)
        def fetch_ids(db):
            jobs, runs = db.fetch_job_updates(0, 0)
            return (
                sorted((r["job_id"], r["queued"], r["succeeded"]) for r in jobs),
                sorted((r["run_id"], r["job_id"]) for r in runs),
            )

        assert fetch_ids(db_serial) == fetch_ids(db_sharded)
        violations = tsan.take_violations()
        assert not violations, "\n".join(violations)
        db_serial.close()
        db_sharded.close()
    finally:
        if not tsan_was:
            tsan.disable()
        plane.close()


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_exactly_once_under_per_shard_store_crash(tmp_path, monkeypatch, seed):
    """The satellite crash drill: ingest_ack fires in ONE shard mid-drain --
    its batch is COMMITTED in that shard's own file, the in-memory ack died.
    A restarted pipeline resumes from the store's per-shard committed
    cursors and must not double-apply; final state bit-equal to serial,
    under tsan."""
    from armada_tpu.analysis import tsan
    from armada_tpu.core import faults

    monkeypatch.setenv("ARMADA_INGEST_SHARDS", str(INGEST_SHARDS))
    monkeypatch.setenv("ARMADA_INGEST_CONVERT", "inline")
    plane = _churn_plane(tmp_path, seed)
    tsan_was = tsan.enabled()
    monkeypatch.setenv("ARMADA_TSAN", "1")
    tsan.enable()
    tsan.reset()
    try:
        db_serial = _serial_replay(plane.log)
        db_sharded = _sharded_db(tmp_path, parts=plane.log.num_partitions)
        faults.reset_counters()
        monkeypatch.setenv("ARMADA_FAULT", "ingest_ack:error:1")
        pipe = PartitionedIngestionPipeline(
            plane.log,
            db_sharded,
            convert_sequences,
            consumer_name="scheduler",
            num_shards=INGEST_SHARDS,
            convert_mode="inline",
        )
        with pytest.raises(faults.FaultInjected):
            pipe.run_until_caught_up()
        monkeypatch.delenv("ARMADA_FAULT")
        # The crashed shard's cursor rows live in ITS OWN file and committed
        # with the batch; the union MIN-merge hands the restart exactly the
        # per-partition resume points.
        resumed = db_sharded.positions("scheduler")
        pipe2 = PartitionedIngestionPipeline(
            plane.log,
            db_sharded,
            convert_sequences,
            consumer_name="scheduler",
            num_shards=INGEST_SHARDS,
            start_positions=resumed,
            convert_mode="inline",
        )
        pipe2.run_until_caught_up()
        assert _materialized(db_serial) == _materialized(db_sharded)
        violations = tsan.take_violations()
        assert not violations, "\n".join(violations)
        db_serial.close()
        db_sharded.close()
    finally:
        if not tsan_was:
            tsan.disable()
        plane.close()


# ------------------------------------------------------------- checkpoint ----


def test_checkpoint_roundtrip_across_shard_files(tmp_path, monkeypatch):
    """Snapshot a sharded store, restore onto a DIFFERENT width, and get
    the same materialized state: export merges per-shard dumps
    (consumer_positions MIN, serials MAX), restore re-routes every row by
    the publisher's partition function onto the target's files."""
    from armada_tpu.scheduler.checkpoint import (
        CheckpointManager,
        maybe_restore,
        snapshot_plane,
    )

    monkeypatch.setenv("ARMADA_INGEST_SHARDS", str(INGEST_SHARDS))
    monkeypatch.setenv("ARMADA_INGEST_CONVERT", "inline")
    plane = _churn_plane(tmp_path, 0)
    try:
        src = _sharded_db(tmp_path, "src", parts=plane.log.num_partitions)
        _sharded_drain(plane.log, src)
        mgr = CheckpointManager(str(tmp_path / "ckpt"))
        mgr.write(snapshot_plane(src))
        st = mgr.status()
        assert st["snapshot"]["store_shards"] == STORE_SHARDS
        # restore onto width 4 (re-routed) and onto the plain store
        dst4 = _sharded_db(
            tmp_path, "dst4", shards=4, parts=plane.log.num_partitions
        )
        info = maybe_restore(dst4, mgr)
        assert info["restored"]
        assert _materialized(src) == _materialized(dst4)
        assert src.positions("scheduler") == dst4.positions("scheduler")
        dst_plain = SchedulerDb(":memory:")
        assert maybe_restore(dst_plain, mgr)["restored"]
        assert _materialized(src) == _materialized(dst_plain)
        # fast-forward-only: a second maybe_restore on the live target skips
        info2 = maybe_restore(dst4, mgr)
        assert not info2["restored"]
        # and the restored sharded store keeps ingesting: drain the same log
        # again from the restored cursors -- exactly-once, nothing reapplies
        n = _sharded_drain(plane.log, dst4, resume=True)
        assert n == 0
        assert _materialized(src) == _materialized(dst4)
        # serial allocation resumes past the restored high-water mark
        jh, rh = dst4.max_serials()
        assert jh >= src.max_serials()[0]
        src.close()
        dst4.close()
        dst_plain.close()
    finally:
        plane.close()


# ----------------------------------------------------- horizon / routing ----


def test_horizon_clamps_fetch_past_inflight_serial(tmp_path):
    """Serial 101 committed in one shard while 100 sits in another shard's
    open transaction: the cursor must NOT advance past 99 or the eventual
    commit of 100 is skipped forever.  The allocator's horizon is that
    clamp; this drives it through real shard sinks."""
    db = _sharded_db(tmp_path, parts=4)
    s0 = db.shard_sink(0, STORE_SHARDS)
    s1 = db.shard_sink(1, STORE_SHARDS)
    from armada_tpu.ingest import dbops

    def job_batch(jid):
        return [
            dbops.InsertJobs(
                jobs={jid: {"job_id": jid, "queue": "q", "jobset": "j"}}
            )
        ]

    s0.store(job_batch("h1"), next_positions={0: 1})
    # simulate shard 1 holding an open txn: allocate without committing
    pending = db._alloc.allocate("jobs")
    s0.store(job_batch("h2"), next_positions={0: 2})
    jobs, _ = db.fetch_job_updates(0, 0)
    # h2's serial is past the in-flight one -- the clamp hides it for now
    assert [r["job_id"] for r in jobs] == ["h1"]
    assert db.max_serials()[0] == pending - 1
    db._alloc.committed([("jobs", pending)])
    jobs, _ = db.fetch_job_updates(0, 0)
    assert sorted(r["job_id"] for r in jobs) == ["h1", "h2"]
    db.close()


def test_globals_have_one_home(tmp_path):
    """Queue CRUD and dedup land in the globals (control) shard only, and
    are visible through the union -- a row with two homes would resurrect
    through the union after a one-file delete."""
    db = _sharded_db(tmp_path, parts=4)
    db.upsert_queue("gq", weight=2.0)
    db.store_dedup({"cid-1": "job-1"})
    occupied = [
        k
        for k, s in enumerate(db._stores)
        if s._query("SELECT COUNT(*) AS c FROM queues")[0]["c"]
    ]
    assert occupied == [db._control_shard]
    assert [r["name"] for r in db._query("SELECT name FROM queues")] == ["gq"]
    db.delete_queue("gq")
    assert db._query("SELECT name FROM queues") == []
    db.close()


def test_width_is_permanent_and_adopted(tmp_path):
    """STORE_META doctrine: reopen with num_shards=None adopts; an explicit
    mismatch refuses; a fresh dir without widths refuses."""
    db = _sharded_db(tmp_path, parts=4)
    db.close()
    adopted = ShardedSchedulerDb(str(tmp_path / "store-shards"))
    assert (adopted.num_shards, adopted.num_partitions) == (STORE_SHARDS, 4)
    adopted.close()
    with pytest.raises(ValueError, match="permanent"):
        ShardedSchedulerDb(
            str(tmp_path / "store-shards"), num_shards=8, num_partitions=4
        )
    with pytest.raises(ValueError, match="fresh sharded store"):
        ShardedSchedulerDb(str(tmp_path / "fresh-dir"))


def test_divisibility_and_union_write_refusals(tmp_path):
    """shard_sink refuses an ingest width the store width does not divide
    (the batch could not commit as one transaction); store/store_plan on
    the union object refuse outright."""
    db = _sharded_db(tmp_path, parts=4)
    with pytest.raises(ValueError, match="not a multiple"):
        db.shard_sink(0, 3)
    with pytest.raises(RuntimeError, match="union reader"):
        db.store([], next_positions={})
    with pytest.raises(RuntimeError, match="union reader"):
        db.store_plan([], next_positions={})
    db.close()


# ----------------------------------------------------------------- lookout ----


def test_sharded_lookout_matches_serial(tmp_path, monkeypatch):
    """The lookout view through sharded files equals the serial drain on
    the queryable surface (job + job_run rows)."""
    monkeypatch.setenv("ARMADA_INGEST_CONVERT", "inline")
    plane = _churn_plane(tmp_path, 1)
    try:
        serial = LookoutDb(":memory:")
        IngestionPipeline(
            plane.log, serial, lookout_converter, consumer_name="lookout"
        ).run_until_caught_up()
        sharded = ShardedLookoutDb(
            str(tmp_path / "lookout-shards"),
            num_shards=STORE_SHARDS,
            num_partitions=plane.log.num_partitions,
        )
        _sharded_drain(
            plane.log, sharded, consumer="lookout", converter=lookout_converter
        )

        def rows(db, sql):
            return sorted(tuple(r) for r in db.query(sql))

        for sql in (
            "SELECT job_id, queue, jobset, state, priority FROM job",
            "SELECT run_id, job_id, state FROM job_run",
        ):
            assert rows(serial, sql) == rows(sharded, sql)
        assert serial.positions("lookout") == sharded.positions("lookout")
        # saved views are globals: execute routes to the globals shard and
        # reads resolve through the union
        sharded.execute(
            "INSERT INTO saved_view (name, payload, updated_ns) "
            "VALUES (?, ?, ?)",
            ("v1", "{}", 1),
        )
        assert rows(sharded, "SELECT name FROM saved_view") == [("v1",)]
        serial.close()
        sharded.close()
    finally:
        plane.close()


# ------------------------------------------------------------- end to end ----


def test_sharded_store_world_end_to_end(tmp_path, monkeypatch):
    """The whole control plane on sharded stores (the serve --store-shards
    shape): jobs submit, lease and finish with every materialized write
    landing in a per-shard file."""
    monkeypatch.setenv("ARMADA_STORE_SHARDS", str(STORE_SHARDS))
    monkeypatch.setenv("ARMADA_INGEST_SHARDS", str(INGEST_SHARDS))
    monkeypatch.setenv("ARMADA_INGEST_CONVERT", "inline")
    plane = ControlPlane.build(tmp_path)
    try:
        assert isinstance(plane.db, ShardedSchedulerDb)
        from armada_tpu.server.submit import JobSubmitItem

        plane.server.create_queue(QueueRecord("ssq"))
        plane.server.submit_jobs(
            "ssq",
            "js",
            [JobSubmitItem(resources={"cpu": "1", "memory": "1"})],
        )
        plane.run_until(
            lambda: "succeeded" in plane.job_states().values(), max_steps=40
        )
        # every shard file carries real rows or cursors; none is a stray
        parts = {
            db_part
            for db_part in plane.db.positions("scheduler")
        }
        assert parts  # cursors committed through shard files
    finally:
        plane.close()


def test_serial_allocator_reopen_seeds_from_all_shards(tmp_path):
    """Reopening a sharded store seeds ONE allocator from every shard's
    high-water mark -- new serials always land past everything on disk."""
    db = _sharded_db(tmp_path, parts=4)
    from armada_tpu.ingest import dbops

    for k, jid in ((0, "r1"), (1, "r2")):
        db.shard_sink(k, STORE_SHARDS).store(
            [
                dbops.InsertJobs(
                    jobs={jid: {"job_id": jid, "queue": "q", "jobset": "j"}}
                )
            ],
            next_positions={k: 1},
        )
    hi = db.max_serials()[0]
    db.close()
    db2 = ShardedSchedulerDb(str(tmp_path / "store-shards"))
    assert db2._alloc.allocate("jobs") == hi + 1
    db2._alloc.discarded([("jobs", hi + 1)])
    db2.close()


def test_serial_allocator_horizon_unit():
    """The allocator's clamp algebra, independent of any store."""
    alloc = SerialAllocator()
    a = alloc.allocate("jobs")
    b = alloc.allocate("jobs")
    c = alloc.allocate("jobs")
    assert (a, b, c) == (1, 2, 3)
    alloc.committed([("jobs", b)])
    assert alloc.horizon("jobs") == a - 1  # a still in flight
    alloc.discarded([("jobs", a)])  # rollback: permanent gap
    assert alloc.horizon("jobs") == b  # c in flight
    alloc.committed([("jobs", c)])
    assert alloc.horizon("jobs") == c
    alloc.seed("jobs", 10)
    assert alloc.allocate("jobs") == 11
