"""Idealised scheduled value straight from the incremental builder's columns.

Same quantity as scheduler/idealised.calculate_idealised_values (the
analogue of internal/scheduler/scheduling/idealised_value.go:21-101): re-run
the market round on a boundary-less "mega node" holding the pool's total
resources, static requirements stripped, per-round limits off, and value the
scheduled set at bid x resource units.  The legacy path materialises a
JobSpec per backlog entry and runs the round kernel; at 1M queued jobs that
spec walk is the only remaining O(backlog) Python in a market cycle
(algo.py need_job_scan).  This module computes the SAME scheduled set from
models/incremental.IncrementalBuilder columns.

On the mega problem the kernel collapses --- empty cluster (no eviction, all
priority levels see identical allocatable), one unlabeled node (static fit
always true after stripping), per-round caps off --- to a single
deterministic admission order: each iteration picks the queue head with the
max f32 bid (ties: lowest queue index; market pools never use prefer-large,
models/__init__.py kernel_kwargs), and queue streams are price-sorted, so
the admission order is exactly sort-by (-f32 price, queue index,
within-queue market order).  Three things interrupt plain greedy admission,
all mirrored here:

  * per-(queue, pc) allocation caps (maximumResourceFractionPerQueue) stay
    ACTIVE in the permissive config; a candidate tripping one KILLS its
    queue for the round (fair_scheduler.py gate_queue -> q_killed), and the
    gate runs BEFORE the fit check;
  * unfeasible-key retirement (fair_scheduler.py:644-650): a failed card-1
    candidate's scheduling key is retired and identical-key entries are
    SKIPPED from then on -- skipped entries are never candidates, so they
    get NO gate check (a retired shape can therefore never kill a queue,
    while an equal-shape DIFFERENT-key row still can);
  * the all-or-nothing group unwind for split heterogeneous gangs
    (models/__init__.py:44-69), re-run with doomed groups invalidated.

The sweep runs blocked: within a block every still-active row is assumed
admitted, one vectorized pass finds the first violation event (gate trip or
fit failure), the event is applied (queue killed from that position / key
retired / unit failed), and the block re-evaluates; event-free blocks
commit in one step.  Work is O(n*R) + O(events * B*R) with events bounded
by the distinct failing scheduling keys + queues + gang units -- real
backlogs are template-shaped, so events are few.

Exactness against the kernel path is pinned by
tests/test_market_columnar.py's randomized cross-checks (dozens of seeds
incl. tight capacity, lookback truncation, split gangs, the per-(queue, pc)
cap queue-kill, plus full-algo mode-equivalence runs).

Known bound (ADVICE r3): the sweep accumulates per-(queue, pc) allocation in
f64 while the kernel's gate accumulates q_alloc in f32.  The two paths agree
while every (queue, pc, resource) allocation stays below ~2^24 resolution
units (f32 integer-exact range); past that the kernel's f32 sum rounds and a
cap trip sitting exactly on the boundary can flip between the paths.  The
cap *threshold* itself is shared f32 (pc_queue_caps), so the divergence is
metric-only and requires both >16M units on one (queue, pc, resource) AND a
trip within one rounding ulp of the boundary -- accepted, not mirrored,
because the f64 sweep is the more accurate of the two.
"""

from __future__ import annotations

from typing import Callable

import numpy as np

from armada_tpu.core.config import SchedulingConfig
from armada_tpu.core.keys import SchedulingKeyIndex, class_signature
from armada_tpu.core.ordering import scheduling_order_key
from armada_tpu.core.types import JobSpec, NodeSpec
from armada_tpu.models.problem import (
    _GangFitContext,
    _joint_capacity_ok,
    _uniform_domain_ban,
)
from armada_tpu.scheduler.idealised import (
    DEFAULT_RESOURCE_UNIT,
    _strip_static_requirements,
)

_SWEEP_BLOCK = 8192


class _Unit:
    """One gang candidate unit (sub-gang after heterogeneous splitting)."""

    __slots__ = (
        "qi", "price64", "sub", "id", "need", "sig", "kkey",
        "card", "value", "nmembers", "tag", "dead", "pc",
    )

    def __init__(self):
        self.tag = ""
        self.dead = False


def _band_price_table(builder, bid_price_of) -> np.ndarray:
    """f64[Q, B] bid prices (the builder's _prices() without the f32 cast:
    build_problem's unit sort compares f64 prices; only the cross-queue
    kernel comparison is f32)."""
    from armada_tpu.models.incremental import _BandProbe

    Q = max(1, len(builder.queue_names))
    B = max(1, len(builder.bands))
    table = np.zeros((Q, B), np.float64)
    for qname, qi in builder.queue_by_name.items():
        for band, bi in builder._band_index.items():
            table[qi, bi] = float(bid_price_of(_BandProbe(qname, band)))
    return table


def calculate_idealised_values_columnar(
    config: SchedulingConfig,
    *,
    pool: str,
    builder,
    bid_price_of: Callable[[JobSpec], float],
    extra_candidates: tuple = (),
    price_table: "np.ndarray | None" = None,
) -> dict:
    """{queue: idealised value} over the builder's backlog + leased sets.

    `extra_candidates`: specs that left the builder tables this cycle but
    were running when the round started -- the legacy path feeds the mega
    round the PRE-round running list (idealised_value.go:68-76), so jobs
    preempted this cycle are still candidates; the algo passes them from
    the outcome (O(preempted)).  `price_table` shares one per-cycle
    _band_price_table build with the indicative pricer.

    Mirrors calculate_idealised_values feature-for-feature: queued singles
    and every running job re-enter as candidates (idealised_value.go:68-76),
    gang siblings regroup across the queued/running split, heterogeneous
    gangs split per scheduling-key class with the joint-capacity dead check
    and the all-or-nothing group unwind, per-queue lookback cap with atomic
    split-gang truncation, floating-resource pool gate, per-(queue, pc)
    allocation caps with the queue kill, unfeasible-key retirement.
    Valuation uses the default resource unit (value_of_jobs)."""
    factory = builder.factory
    R = factory.num_resources

    # --- mega-node capacity (sum RAW atoms, floor-quantise ONCE, exactly as
    # --- the legacy mega NodeSpec flows through build_problem) --------------
    total_atoms = np.zeros((R,), np.int64)
    have_node = False
    for i, spec in enumerate(builder.node_specs):
        if not builder.node_present[i] or spec.pool != pool or spec.unschedulable:
            continue
        have_node = True
        if spec.total_resources is not None:
            total_atoms += np.asarray(spec.total_resources.atoms, np.int64)
    if not have_node:
        return {}

    floating = set(config.floating_resource_names())
    node_axes = np.array(
        [0.0 if name in floating else 1.0 for name in factory.names], np.float64
    )
    mega_units = factory.floor_units(total_atoms).astype(np.float64)
    float_total = np.zeros((R,), np.float64)
    if floating:
        fl = factory.from_mapping(config.floating_totals_for_pool(pool))
        float_total = factory.floor_units(fl.atoms).astype(np.float64) * (
            1.0 - node_axes
        )
    # One combined per-axis budget: node axes get the mega allocatable, float
    # axes the pool float cap + the kernel's 1e-3 epsilon
    # (fair_scheduler.py:425 float gate); fit viol <=> need > cap - consumed.
    cap_fit = mega_units * node_axes + (float_total + 1e-3) * (1.0 - node_axes)

    unit_vec = np.asarray(
        factory.from_mapping(DEFAULT_RESOURCE_UNIT).atoms, np.float64
    )

    price64 = (
        price_table
        if price_table is not None
        else _band_price_table(builder, bid_price_of)
    )
    qok = builder.queue_known & (builder.queue_weight > 0)

    # --- vector candidates: columnar singles + every pools-compatible run ---
    jt, rt = builder.jobs, builder.runs
    jrows = jt.live_rows()
    rrows = rt.live_rows()
    if rrows.size:
        rrows = rrows[rt.pok[rrows]]
    # running gang members regroup into gang units (below) exactly like the
    # legacy candidate list -- drop their table rows or they'd count twice
    if rrows.size and builder.running_gang_specs:
        gang_row_ids = np.array(
            [k.encode() for k in builder.running_gang_specs], rt.ids.dtype
        )
        rrows = rrows[~np.isin(rt.ids[rrows], gang_row_ids)]
    qi = np.concatenate([jt.qi[jrows], rt.qi[rrows]]).astype(np.int64)
    band = np.concatenate([jt.band[jrows], rt.band[rrows]]).astype(np.int64)
    sub = np.concatenate([jt.sub[jrows], rt.sub[rrows]])
    ids = np.concatenate([jt.ids[jrows], rt.ids[rrows]])
    need = np.concatenate(
        [jt.req[jrows], rt.req[rrows]], axis=0
    ).astype(np.float64)
    pcrow = np.concatenate([jt.pc[jrows], rt.pc[rrows]]).astype(np.int64)
    prio = np.concatenate([jt.prio[jrows], rt.prio[rrows]]).astype(np.int64)
    if jt.atoms is None:
        raise ValueError("idealised_columnar requires a market builder")
    atoms = np.concatenate([jt.atoms[jrows], rt.atoms[rrows]], axis=0)
    hasres = np.concatenate([jt.hasres[jrows], rt.hasres[rrows]])

    price = price64[qi, band]
    # bans-only entries in gang_jobs have no gang id: the mega round drops
    # bans (calculate_idealised_values passes none), so they are plain
    # singles there.  Their bands were never interned -> price them
    # directly off the provider (build_problem prices units the same way).
    # Pre-round-running extras (preempted this cycle) join the same way.
    extra_specs = [s for s in builder.gang_jobs.values() if not s.gang_id]
    extra_gang_specs = []
    for s in extra_candidates:
        if s.pools and pool not in s.pools:
            continue
        if s.queue not in builder.queue_by_name:
            continue
        (extra_gang_specs if s.gang_id else extra_specs).append(s)
    if extra_specs:
        e_qi = np.array(
            [builder.queue_by_name[s.queue] for s in extra_specs], np.int64
        )
        e_sub = np.array([s.submit_time for s in extra_specs], np.float64)
        e_ids = np.array([s.id.encode() for s in extra_specs], ids.dtype)
        e_req = np.stack(
            [
                factory.ceil_units(s.resources.atoms).astype(np.float64)
                if s.resources is not None
                else np.zeros((R,), np.float64)
                for s in extra_specs
            ]
        )
        e_atoms = np.stack(
            [
                np.asarray(s.resources.atoms, np.int64)
                if s.resources is not None
                else np.zeros((R,), np.int64)
                for s in extra_specs
            ]
        )
        e_has = np.array([s.resources is not None for s in extra_specs], bool)
        e_price = np.array(
            [float(bid_price_of(s)) for s in extra_specs], np.float64
        )
        e_pc = np.array(
            [
                builder.pc_index[config.priority_class(s.priority_class).name]
                for s in extra_specs
            ],
            np.int64,
        )
        e_prio = np.array([s.priority for s in extra_specs], np.int64)
        qi = np.concatenate([qi, e_qi])
        sub = np.concatenate([sub, e_sub])
        ids = np.concatenate([ids, e_ids])
        need = np.concatenate([need, e_req], axis=0)
        atoms = np.concatenate([atoms, e_atoms], axis=0)
        hasres = np.concatenate([hasres, e_has])
        price = np.concatenate([price, e_price])
        pcrow = np.concatenate([pcrow, e_pc])
        prio = np.concatenate([prio, e_prio])

    keep = qok[qi]
    qi, sub, ids = qi[keep], sub[keep], ids[keep]
    need, atoms, hasres = need[keep], atoms[keep], hasres[keep]
    price, pcrow, prio = price[keep], pcrow[keep], prio[keep]
    n_rows = qi.shape[0]

    # Per-(queue, priority-class) allocation caps stay ACTIVE in the mega
    # round (idealised.py's permissive config clears only the per-round
    # limits); same f32 math as the kernel problems (problem.pc_queue_caps).
    from armada_tpu.models.problem import pc_queue_caps

    pc_queue_cap = pc_queue_caps(
        config,
        builder.pc_names,
        factory,
        (mega_units + float_total).astype(np.float32),
    ).astype(np.float64)

    # per-row valuation: price x max_r(raw atoms / unit) (value_of_jobs)
    with np.errstate(divide="ignore", invalid="ignore"):
        vu = np.where(
            unit_vec[None, :] > 0,
            atoms.astype(np.float64) / np.maximum(unit_vec[None, :], 1e-12),
            0.0,
        )
    val = vu.max(axis=1) if vu.shape[0] else np.zeros((0,))
    rowvalue = np.where(hasres, price * val, 0.0)

    # --- gang units ---------------------------------------------------------
    mega = NodeSpec(id="__mega__", pool=pool)
    fitctx = _GangFitContext(
        [mega],
        mega_units[None, :].astype(np.float32),
        {"__mega__": 0},
        factory,
        node_axes,
    )
    kidx = SchedulingKeyIndex()
    nil = config.node_id_label
    by_gang: dict[tuple, list] = {}
    seen = set()
    for s in builder.gang_jobs.values():
        if not s.gang_id:
            continue
        seen.add(s.id)
        gqi = builder.queue_by_name.get(s.queue)
        if gqi is None or not qok[gqi]:
            continue
        by_gang.setdefault((gqi, s.gang_id), []).append(
            _strip_static_requirements(s)
        )
    for s in builder.running_gang_specs.values():
        if s.id in seen:
            continue
        seen.add(s.id)
        if s.pools and pool not in s.pools:
            continue
        gqi = builder.queue_by_name.get(s.queue)
        if gqi is None or not qok[gqi]:
            continue
        by_gang.setdefault((gqi, s.gang_id), []).append(
            _strip_static_requirements(s)
        )
    for s in extra_gang_specs:
        if s.id in seen:
            continue
        seen.add(s.id)
        gqi = builder.queue_by_name.get(s.queue)
        if gqi is None or not qok[gqi]:
            continue
        by_gang.setdefault((gqi, s.gang_id), []).append(
            _strip_static_requirements(s)
        )

    units: list[_Unit] = []
    for (gqi, gang_id), members in by_gang.items():
        label = members[0].gang_node_uniformity_label
        uniformity = ("", "")
        uban = None
        if label:
            prov: dict = {}
            for m in members:
                prov.setdefault(class_signature(m, nil), []).append(m)
            classes = [(grp[0], len(grp)) for grp in prov.values()]
            if len(classes) == 1:
                classes = [
                    (members[0], max(len(members), members[0].gang_cardinality or 1))
                ]
            # no running placements in the mega round -> no pinned domain
            uban, chosen = _uniform_domain_ban(fitctx, label, classes, (), nil)
            uniformity = (label, chosen)
        keys = {kidx.key_of(m, nil, uniformity=uniformity) for m in members}
        if len(keys) > 1:
            by_key: dict[int, list] = {}
            for m in members:
                by_key.setdefault(
                    kidx.key_of(m, nil, uniformity=uniformity), []
                ).append(m)
            groups = list(by_key.items())
        else:
            groups = [(next(iter(keys)), members)]
        group_tag = f"{gqi}:{gang_id}" if len(groups) > 1 else ""
        dead = False
        if len(groups) > 1:
            class_info = []
            for _, grp in groups:
                glead = grp[0]
                usable = fitctx.ok & fitctx.static_fit(glead, nil)
                if uban:
                    usable = usable.copy()
                    usable[np.asarray(sorted(uban), np.int64)] = False
                req_units = (
                    factory.ceil_units(glead.resources.atoms).astype(np.float64)
                    if glead.resources is not None
                    else np.zeros((R,), np.float64)
                )
                cap = fitctx.capacity(req_units, len(grp))
                if int(cap[usable].sum()) < len(grp):
                    dead = True
                    break
                class_info.append(
                    (usable, fitctx.frac_capacity(req_units), len(grp))
                )
            if not dead:
                dead = not _joint_capacity_ok(class_info)
        for grp_key, grp in groups:
            lead = min(
                grp,
                key=lambda m: scheduling_order_key(
                    config.priority_class(m.priority_class).priority,
                    m.priority,
                    m.submit_time,
                    m.id,
                ),
            )
            lead_req = (
                factory.ceil_units(lead.resources.atoms).astype(np.float64)
                if lead.resources is not None
                else np.zeros((R,), np.float64)
            )
            value = 0.0
            nmem = 0
            for m in grp:
                if m.resources is None:
                    continue
                ratoms = np.asarray(m.resources.atoms, np.float64)
                with np.errstate(divide="ignore", invalid="ignore"):
                    mu = np.where(
                        unit_vec > 0, ratoms / np.maximum(unit_vec, 1e-12), 0.0
                    ).max()
                value += float(bid_price_of(m)) * float(mu)
                nmem += 1
            u = _Unit()
            u.qi = gqi
            u.price64 = float(bid_price_of(lead))
            u.pc = builder.pc_index[
                config.priority_class(lead.priority_class).name
            ]
            u.sub = lead.submit_time
            u.id = lead.id
            u.need = lead_req * len(grp)
            u.card = len(grp)
            u.value = value
            u.nmembers = nmem
            u.tag = group_tag
            # member signature in the kernel's stripped key space: identical
            # (request, pc, priority) entries share one scheduling key with
            # plain singles when the gang adds no uniformity component
            if not label:
                u.sig = (
                    tuple(np.asarray(lead.resources.atoms, np.int64).tolist())
                    if lead.resources is not None
                    else tuple([0] * R),
                    u.pc,
                    lead.priority,
                )
            else:
                u.sig = None
            u.kkey = grp_key
            # a banned-out uniformity gang cannot use the single node
            u.dead = bool(dead or (uban and 0 in uban))
            units.append(u)

    # --- merge units into the row arrays ------------------------------------
    n = n_rows + len(units)
    unit_of = np.full((n,), -1, np.int64)
    card = np.ones((n,), np.int64)
    if units:
        unit_of[n_rows:] = np.arange(len(units))
        card[n_rows:] = [u.card for u in units]
        qi = np.concatenate([qi, np.array([u.qi for u in units], np.int64)])
        sub = np.concatenate([sub, np.array([u.sub for u in units])])
        ids = np.concatenate(
            [ids, np.array([u.id.encode() for u in units], ids.dtype)]
        )
        need = np.concatenate([need, np.stack([u.need for u in units])], axis=0)
        hasres = np.concatenate(
            [hasres, np.array([u.nmembers > 0 for u in units], bool)]
        )
        price = np.concatenate(
            [price, np.array([u.price64 for u in units], np.float64)]
        )
        rowvalue = np.concatenate(
            [rowvalue, np.array([u.value for u in units], np.float64)]
        )
        pcrow = np.concatenate(
            [pcrow, np.array([u.pc for u in units], np.int64)]
        )
        prio = np.concatenate([prio, np.zeros((len(units),), np.int64)])
    if n == 0:
        return {}

    # --- scheduling-key ids (skip/retire space) -----------------------------
    # Rows and uniformity-free units share the stripped-key space
    # ((raw atoms, pc, priority)); uniformity units key off the interned
    # kidx key in a disjoint namespace.  Retirement registers only card-1
    # entries (fair_scheduler.py:647), but SKIPPING applies to any entry
    # whose key is retired -- including gangs (the cursor wbad check).
    pack = np.zeros((n, R + 2), np.int64)
    pack[:n_rows, :R] = atoms
    pack[:, R] = pcrow
    pack[:, R + 1] = prio
    uni_key = np.full((n,), -1, np.int64)
    for k, u in enumerate(units):
        if u.sig is not None:
            pack[n_rows + k, :R] = np.array(u.sig[0], np.int64)
            pack[n_rows + k, R + 1] = u.sig[2]
        else:
            uni_key[n_rows + k] = u.kkey
    packv = np.ascontiguousarray(pack).view(
        [("", np.int64)] * (R + 2)
    ).reshape(-1)
    _, key_id = np.unique(packv, return_inverse=True)
    key_id = np.asarray(key_id, np.int64)
    K_rows = int(key_id.max()) + 1
    has_uni = uni_key >= 0
    key_id[has_uni] = K_rows + uni_key[has_uni]
    num_keys = K_rows + (len(kidx.keys) if units else 0)

    return _admit(
        config, builder, n, qi, sub, ids, need, hasres, price, rowvalue,
        pcrow, card, key_id, num_keys, unit_of, units, cap_fit, pc_queue_cap,
    )


def _admit(
    config, builder, n, qi, sub, ids, need, hasres, price, rowvalue,
    pcrow, card, key_id, num_keys, unit_of, units, cap_fit, pc_queue_cap,
):
    """Lookback-cap the per-queue streams, order globally, and run the
    blocked event-driven sweep (re-run with doomed groups killed on a
    partial-group unwind, models/__init__.py:44-69)."""
    # --- within-queue market order + lookback cap ---------------------------
    wq = np.lexsort((ids, sub, -price, qi))
    qi, sub, ids = qi[wq], sub[wq], ids[wq]
    need, hasres, price = need[wq], hasres[wq], price[wq]
    rowvalue, pcrow, card = rowvalue[wq], pcrow[wq], card[wq]
    key_id, unit_of = key_id[wq], unit_of[wq]

    L = config.max_queue_lookback
    qstart = np.zeros((n,), np.int64)
    first = np.ones((n,), bool)
    first[1:] = qi[1:] != qi[:-1]
    starts = np.flatnonzero(first)
    qstart[starts] = starts
    np.maximum.accumulate(qstart, out=qstart)
    rank = np.arange(n) - qstart
    keep = rank < L
    if not keep.all() and units:
        kept_tags = set()
        cut_tags = set()
        for i in np.flatnonzero(unit_of >= 0):
            t = units[unit_of[i]].tag
            if t:
                (kept_tags if keep[i] else cut_tags).add(t)
        partial = kept_tags & cut_tags
        if partial:
            for i in np.flatnonzero(unit_of >= 0):
                if units[unit_of[i]].tag in partial:
                    keep[i] = False
    if not keep.all():
        qi, need, hasres = qi[keep], need[keep], hasres[keep]
        rowvalue, pcrow, card = rowvalue[keep], pcrow[keep], card[keep]
        key_id, unit_of, price = key_id[keep], unit_of[keep], price[keep]
        n = qi.shape[0]
        if n == 0:
            return {}

    # --- global admission order: (-f32 price, queue, within-queue pos) ------
    price32 = price.astype(np.float32)
    wq_pos = np.arange(n)  # already within-queue sorted; stable tiebreak
    order = np.lexsort((wq_pos, qi, -price32))
    qi, need, hasres = qi[order], need[order], hasres[order]
    rowvalue, pcrow, card = rowvalue[order], pcrow[order], card[order]
    key_id, unit_of = key_id[order], unit_of[order]

    total_by_tag: dict[str, int] = {}
    for i in np.flatnonzero(unit_of >= 0):
        t = units[unit_of[i]].tag
        if t:
            total_by_tag[t] = total_by_tag.get(t, 0) + 1

    excluded0 = np.zeros((n,), bool)
    for i in np.flatnonzero(unit_of >= 0):
        if units[unit_of[i]].dead:
            excluded0[i] = True

    killed_groups: set = set()
    partial: set = set()
    admitted = np.zeros((n,), bool)
    for _ in range(5):
        excluded = excluded0.copy()
        if killed_groups:
            for i in np.flatnonzero(unit_of >= 0):
                if units[unit_of[i]].tag in killed_groups:
                    excluded[i] = True
        admitted = _sweep(
            n, qi, pcrow, need, card, key_id, num_keys, excluded,
            cap_fit, pc_queue_cap, len(builder.queue_names),
        )
        placed_by_tag: dict[str, int] = {}
        for i in np.flatnonzero(admitted & (unit_of >= 0)):
            t = units[unit_of[i]].tag
            if t:
                placed_by_tag[t] = placed_by_tag.get(t, 0) + 1
        partial = {
            t
            for t, total in total_by_tag.items()
            if 0 < placed_by_tag.get(t, 0) < total
        } - killed_groups
        if not partial:
            break
        killed_groups |= partial
    if partial:
        # Attempt cap reached (models/__init__.py attempts < 4): decode
        # unwinds the still-partial groups, so their placed members leave
        # the scheduled set entirely (no value, no queue entry) while the
        # capacity they consumed stays consumed.
        for i in np.flatnonzero(unit_of >= 0):
            if units[unit_of[i]].tag in partial:
                admitted[i] = False
    values: dict = {}
    take = admitted & hasres
    if take.any():
        counts = np.bincount(qi[take])
        sums = np.bincount(
            qi[admitted],
            weights=rowvalue[admitted],
            minlength=counts.shape[0],
        )
        for q in np.flatnonzero(counts):
            values[builder.queue_names[q]] = float(sums[q])
    return values


def _sweep(
    n, qi, pcrow, need, card, key_id, num_keys, excluded,
    cap_fit, pc_queue_cap, Qn,
):
    """One full admission sweep in global order.  Within each block every
    active row is assumed admitted; the first violation event is applied
    (gate trip kills the queue from that position; a card-1 fit failure
    retires its key; any fit failure excludes the row) and the block
    re-evaluates.  Retired-key entries are SKIPPED (no gate check), exactly
    like the kernel's cursor (wbad, fair_scheduler.py:330)."""
    R = need.shape[1]
    Cn = pc_queue_cap.shape[0]
    admitted = np.zeros((n,), bool)
    consumed = np.zeros((R,), np.float64)
    q_alloc = np.zeros((Qn, Cn, R), np.float64)
    # positional: rows BEFORE the retiring/killing event keep their admission
    retired_from = np.full((max(num_keys, 1),), np.iinfo(np.int64).max, np.int64)
    killed_from = np.full((Qn,), np.iinfo(np.int64).max, np.int64)

    i = 0
    while i < n:
        j = min(n, i + _SWEEP_BLOCK)
        blk = slice(i, j)
        bq = qi[blk]
        bpc = pcrow[blk]
        bneed = need[blk]
        bkey = key_id[blk]
        bpos = np.arange(i, j)
        grp = bq * Cn + bpc
        sidx = np.argsort(grp, kind="stable")
        g_s = grp[sidx]
        newg = np.ones((g_s.shape[0],), bool)
        if g_s.shape[0] > 1:
            newg[1:] = g_s[1:] != g_s[:-1]
        seg_starts = np.flatnonzero(newg)
        seg_counts = np.diff(np.append(seg_starts, g_s.shape[0]))
        dead = np.zeros((j - i,), bool)
        while True:
            act = (
                ~excluded[blk]
                & ~dead
                & (bpos < killed_from[bq])
                & (bpos <= retired_from[bkey])
            )
            consume = bneed * act[:, None]
            bcum = np.cumsum(consume, axis=0)
            cum_before = consumed[None, :] + bcum - consume
            viol = (
                (bneed > cap_fit[None, :] - cum_before) & (bneed > 0)
            ).any(axis=1)
            # per-(queue, pc) exclusive prefix within the block
            c_s = np.cumsum(consume[sidx], axis=0) - consume[sidx]
            if seg_starts.shape[0]:
                offs = c_s[seg_starts]
                c_s = c_s - np.repeat(offs, seg_counts, axis=0)
            alloc_before = np.empty_like(c_s)
            alloc_before[sidx] = c_s
            alloc_before = alloc_before + q_alloc[bq, bpc]
            trip = (alloc_before + bneed > pc_queue_cap[bpc]).any(axis=1)
            ev = act & (trip | viol)
            idx = np.flatnonzero(ev)
            if idx.size == 0:
                break
            e = int(idx[0])
            if trip[e]:
                killed_from[bq[e]] = i + e
            else:
                dead[e] = True
                if card[i + e] == 1 and key_id[i + e] >= 0:
                    retired_from[key_id[i + e]] = min(
                        retired_from[key_id[i + e]], i + e
                    )
        admitted[blk] = act
        consumed = consumed + consume.sum(axis=0)
        if act.any():
            np.add.at(q_alloc, (bq[act], bpc[act]), bneed[act])
        i = j
    return admitted
