"""The scheduling sidecar: the round kernel behind a gRPC boundary.

The reference's SchedulingAlgo.Schedule (scheduling_algo.go:36-41) is an
in-process interface; the sidecar exports the same boundary over the wire so
an external (Go) control plane can use the TPU kernel.  The core property is
EQUALITY: a world mirrored through SyncState and scheduled via ScheduleRound
must produce exactly the decisions the in-process FairSchedulingAlgo makes
on the same world.
"""

from __future__ import annotations

import dataclasses

import grpc
import pytest

from armada_tpu.core.config import PriorityClass, SchedulingConfig
from armada_tpu.core.types import NodeSpec, Queue
from armada_tpu.jobdb.job import Job, JobRun, JobSpec
from armada_tpu.jobdb.jobdb import JobDb
from armada_tpu.rpc.client import ScheduleClient, job_state_of
from armada_tpu.rpc.server import make_server
from armada_tpu.scheduler.algo import FairSchedulingAlgo
from armada_tpu.scheduler.executors import ExecutorSnapshot
from armada_tpu.scheduler.sidecar import ScheduleSidecar

NOW_NS = 1_000_000_000_000


def config_for(incremental: bool) -> SchedulingConfig:
    return SchedulingConfig(
        shape_bucket=64,
        enable_assertions=True,
        incremental_problem_build=incremental,
        protected_fraction_of_fair_share=0.5,
        priority_classes={
            "pc-high": PriorityClass(
                "pc-high", priority=3000, preemptible=False
            ),
            "pc-low": PriorityClass(
                "pc-low", priority=1000, preemptible=True
            ),
        },
        default_priority_class="pc-low",
    )


def build_world(config):
    """Nodes, queues and Job rows exercising the whole JobState surface:
    mixed priority classes, a gang, node bans, pool restrictions, running
    jobs (incl. an away run) and preemption pressure from an over-share
    queue."""
    F = config.resource_list_factory()
    nodes = [
        NodeSpec(
            id=f"n{i:02d}",
            pool="default",
            executor="ex1",
            total_resources=F.from_mapping({"cpu": "8", "memory": "32"}),
            labels={"rack": f"r{i % 3}"},
        )
        for i in range(12)
    ]
    queues = [Queue("alpha", 2.0), Queue("beta", 1.0), Queue("gamma", 1.0)]

    def spec(jid, queue, pc="pc-low", cpu="2", mem="8", prio=0, **kw):
        return JobSpec(
            id=jid,
            queue=queue,
            jobset="set1",
            priority_class=pc,
            priority=prio,
            submit_time=float(int(jid[1:]) if jid[1:].isdigit() else 1),
            resources=F.from_mapping({"cpu": cpu, "memory": mem}),
            **kw,
        )

    jobs = []
    # hog queue "alpha": runs the whole cluster at low PC (2 cpu free per
    # node) -> beta's 4-cpu jobs fit nowhere without fair-share eviction
    for i in range(12):
        s = spec(f"r{i:03d}", "alpha", cpu="6", mem="24")
        jobs.append(
            Job(
                spec=s,
                queued=False,
                validated=True,
                runs=(
                    JobRun(
                        id=f"run-r{i:03d}",
                        job_id=s.id,
                        executor="ex1",
                        node_id=f"n{i:02d}",
                        node_name=f"n{i:02d}",
                        pool="default",
                        scheduled_at_priority=1000,
                        running=True,
                        running_ns=NOW_NS - 10**9,
                    ),
                ),
            )
        )
    # one away run (home/away semantics must survive the wire): rides in
    # n11's leftover capacity at the away level, first to go under pressure
    s = spec("r100", "alpha", cpu="2", mem="8")
    jobs.append(
        Job(
            spec=s,
            queued=False,
            validated=True,
            runs=(
                JobRun(
                    id="run-r100",
                    job_id="r100",
                    executor="ex1",
                    node_id="n11",
                    node_name="n11",
                    pool="default",
                    scheduled_at_priority=0,
                    pool_scheduled_away=True,
                    running=True,
                ),
            ),
        )
    )
    # queued: beta wants capacity (forces eviction of alpha's preemptible
    # runs), gamma brings a gang + a banned job + a priority spread
    for i in range(6):
        jobs.append(
            Job(
                spec=spec(f"q{i:03d}", "beta", cpu="4", mem="16", prio=i),
                queued=True,
                validated=True,
            )
        )
    for i in range(3):
        jobs.append(
            Job(
                spec=spec(
                    f"g{i:03d}",
                    "gamma",
                    gang_id="gang1",
                    gang_cardinality=3,
                    cpu="2",
                    mem="8",
                ),
                queued=True,
                validated=True,
            )
        )
    # retry anti-affinity: failed attempts on n00/n01 ban those nodes
    s = spec("q100", "gamma", cpu="1", mem="4")
    jobs.append(
        Job(
            spec=s,
            queued=True,
            validated=True,
            runs=(
                JobRun(
                    id="old-1",
                    job_id="q100",
                    node_id="n00",
                    node_name="n00",
                    failed=True,
                    run_attempted=True,
                ),
                JobRun(
                    id="old-2",
                    job_id="q100",
                    node_id="n01",
                    node_name="n01",
                    failed=True,
                    run_attempted=True,
                ),
            ),
        )
    )
    # an unvalidated job must be invisible to scheduling on both sides
    jobs.append(Job(spec=spec("q200", "beta"), queued=True, validated=False))
    executors = [
        ExecutorSnapshot(
            id="ex1",
            pool="default",
            nodes=tuple(nodes),
            last_update_ns=NOW_NS,
        )
    ]
    return nodes, queues, jobs, executors


def run_in_process(config, queues, jobs, executors):
    jobdb = JobDb(config)
    feed = None
    if config.incremental_problem_build:
        from armada_tpu.scheduler.incremental_algo import IncrementalProblemFeed

        feed = IncrementalProblemFeed(config)
        feed.attach(jobdb)
    txn = jobdb.write_txn()
    txn.upsert(jobs)
    txn.commit()
    algo = FairSchedulingAlgo(
        config,
        queues=lambda: queues,
        clock_ns=lambda: NOW_NS,
        collect_stats=False,
        feed=feed,
    )
    txn = jobdb.write_txn()
    result = algo.schedule(txn, executors, now_ns=NOW_NS)
    txn.commit()
    return result, jobdb


@pytest.fixture()
def sidecar_env():
    """A live Schedule service + client; yields (client, sidecar)."""
    made = []

    def start(config):
        sidecar = ScheduleSidecar(config, clock_ns=lambda: NOW_NS)
        server, port = make_server(schedule_sidecar=sidecar)
        client = ScheduleClient(f"127.0.0.1:{port}")
        made.append((server, client))
        return client, sidecar

    yield start
    for server, client in made:
        client.close()
        server.stop(0)


@pytest.mark.parametrize("incremental", [False, True])
def test_sidecar_round_equals_in_process(sidecar_env, incremental):
    config = config_for(incremental)
    nodes, queues, jobs, executors = build_world(config)
    inproc, _ = run_in_process(config, queues, jobs, executors)
    in_sched = {job.id: run.node_id for job, run in inproc.scheduled}
    in_preempted = {job.id for job, _ in inproc.preempted}
    assert in_sched, "scenario must schedule something"
    assert in_preempted, "scenario must preempt something"

    client, _ = sidecar_env(config)
    sid = client.create_session()
    client.sync_state(
        sid,
        jobs=jobs,
        executors=executors,
        queues=queues,
        factory=config.resource_list_factory(),
    )
    resp = client.schedule_round(sid, now_ns=NOW_NS)
    side_sched = {l.job_id: l.node_id for l in resp.scheduled}
    side_preempted = {p.job_id for p in resp.preempted}
    assert side_sched == in_sched
    assert side_preempted == in_preempted
    # lease metadata a Go caller applies to its jobDb
    for lease in resp.scheduled:
        assert lease.run_id and lease.pool == "default"
        assert lease.executor == "ex1"
    # the banned job avoided its ban set on both sides
    if "q100" in side_sched:
        assert side_sched["q100"] not in ("n00", "n01")
    assert "q200" not in side_sched  # unvalidated stays invisible
    # gang atomicity survived the wire
    gang_placed = [j for j in side_sched if j.startswith("g")]
    assert len(gang_placed) in (0, 3)


@pytest.mark.parametrize("incremental", [False, True])
def test_sidecar_steady_state_deltas(sidecar_env, incremental):
    """Cycle 2 ships only deltas: the mirror already holds cycle 1's
    decisions (the sidecar committed them), so the caller syncs just new
    submits and the round schedules them without disturbing settled jobs."""
    config = config_for(incremental)
    nodes, queues, jobs, executors = build_world(config)
    client, _ = sidecar_env(config)
    sid = client.create_session()
    F = config.resource_list_factory()
    client.sync_state(
        sid, jobs=jobs, executors=executors, queues=queues, factory=F
    )
    r1 = client.schedule_round(sid, now_ns=NOW_NS)
    placed_r1 = {l.job_id for l in r1.scheduled}
    preempted_r1 = {p.job_id for p in r1.preempted}
    assert placed_r1

    fresh = Job(
        spec=JobSpec(
            id="fresh1",
            queue="beta",
            jobset="set1",
            priority_class="pc-low",
            submit_time=2000.0,
            resources=F.from_mapping({"cpu": "1", "memory": "2"}),
        ),
        queued=True,
        validated=True,
    )
    client.sync_state(sid, jobs=[fresh])
    r2 = client.schedule_round(sid, now_ns=NOW_NS + 10**9)
    placed_r2 = {l.job_id for l in r2.scheduled}
    assert "fresh1" in placed_r2
    # cycle 1's placements are leased in the mirror now -- they must not be
    # re-scheduled as if still queued
    assert not (placed_r2 & placed_r1)
    # nothing preempted twice either
    assert not ({p.job_id for p in r2.preempted} & preempted_r1)


def test_sidecar_sessions_and_errors(sidecar_env):
    config = config_for(False)
    client, sidecar = sidecar_env(config)
    with pytest.raises(grpc.RpcError) as err:
        client.schedule_round("nope")
    assert err.value.code() == grpc.StatusCode.NOT_FOUND

    # per-session config via YAML (reference key schema)
    sid = client.create_session(
        config_yaml=(
            "maximumSchedulingBurst: 1\n"
            "maximumPerQueueSchedulingBurst: 1\n"
            "priorityClasses:\n"
            "  pc-high: {priority: 3000}\n"
            "  pc-low: {priority: 1000, preemptible: true}\n"
            "defaultPriorityClass: pc-low\n"
        )
    )
    assert sidecar.session(sid).config.maximum_scheduling_burst == 1
    nodes, queues, jobs, executors = build_world(config)
    client.sync_state(
        sid,
        jobs=[j for j in jobs if j.queued],
        executors=executors,
        queues=queues,
        factory=config.resource_list_factory(),
    )
    resp = client.schedule_round(sid, now_ns=NOW_NS)
    assert len(resp.scheduled) <= 1  # burst cap from the session config
    client.close_session(sid)
    with pytest.raises(grpc.RpcError):
        client.schedule_round(sid)


def test_sidecar_terminal_and_delete_free_capacity(sidecar_env):
    """A terminal sync (or a delete) releases the job's capacity: the next
    round can place a job that previously did not fit."""
    config = config_for(False)
    F = config.resource_list_factory()
    node = NodeSpec(
        id="n0",
        pool="default",
        executor="ex1",
        total_resources=F.from_mapping({"cpu": "4", "memory": "16"}),
    )
    executors = [
        ExecutorSnapshot(
            id="ex1", pool="default", nodes=(node,), last_update_ns=NOW_NS
        )
    ]
    queues = [Queue("alpha", 1.0)]

    def job(jid, queued, cpu="4"):
        s = JobSpec(
            id=jid,
            queue="alpha",
            jobset="s",
            priority_class="pc-high",
            submit_time=1.0,
            resources=F.from_mapping({"cpu": cpu, "memory": "8"}),
        )
        runs = ()
        if not queued:
            runs = (
                JobRun(
                    id=f"run-{jid}",
                    job_id=jid,
                    node_id="n0",
                    node_name="n0",
                    pool="default",
                    scheduled_at_priority=3000,
                    running=True,
                ),
            )
        return Job(spec=s, queued=queued, validated=True, runs=runs)

    client, _ = sidecar_env(config)
    sid = client.create_session()
    client.sync_state(
        sid,
        jobs=[job("occupier", queued=False), job("waiter", queued=True)],
        executors=executors,
        queues=queues,
        factory=F,
    )
    r1 = client.schedule_round(sid, now_ns=NOW_NS)
    assert not r1.scheduled  # node full, non-preemptible occupant
    # occupier finished: caller syncs the terminal state
    done = job_state_of(job("occupier", queued=False))
    done.terminal = True
    client.sync_state(sid, jobs=[done])
    r2 = client.schedule_round(sid, now_ns=NOW_NS + 10**9)
    assert {l.job_id for l in r2.scheduled} == {"waiter"}


def test_serve_hosts_algo_port(tmp_path):
    """`serve --algo-port` exposes the sidecar next to the control plane."""
    from armada_tpu.cli.serve import start_control_plane

    plane = start_control_plane(
        data_dir=str(tmp_path / "data"),
        port=0,
        algo_port=0,
        cycle_interval_s=3600,
    )
    try:
        assert plane.algo_port
        client = ScheduleClient(f"127.0.0.1:{plane.algo_port}")
        sid = client.create_session()
        assert sid
        client.close_session(sid)
        client.close()
    finally:
        plane.stop()


def test_sidecar_fifo_tie_break_matches(sidecar_env):
    """submit_time must survive the wire: same queue/PC/priority, capacity
    for one -- the EARLIER submit wins on both sides (without submit_time on
    JobState both would tie at 0.0 and the id tie-break would pick the
    other job)."""
    config = config_for(False)
    F = config.resource_list_factory()
    node = NodeSpec(
        id="n0",
        pool="default",
        executor="ex1",
        total_resources=F.from_mapping({"cpu": "4", "memory": "16"}),
    )
    executors = [
        ExecutorSnapshot(
            id="ex1", pool="default", nodes=(node,), last_update_ns=NOW_NS
        )
    ]
    queues = [Queue("alpha", 1.0)]
    jobs = [
        # lexicographically-smaller id submitted LATER: the id tie-break
        # and the submit-time order disagree, so a dropped submit_time flips
        # the winner
        Job(
            spec=JobSpec(
                id="aaa",
                queue="alpha",
                jobset="s",
                priority_class="pc-low",
                submit_time=10.0,
                resources=F.from_mapping({"cpu": "4", "memory": "8"}),
            ),
            queued=True,
            validated=True,
        ),
        Job(
            spec=JobSpec(
                id="zzz",
                queue="alpha",
                jobset="s",
                priority_class="pc-low",
                submit_time=5.0,
                resources=F.from_mapping({"cpu": "4", "memory": "8"}),
            ),
            queued=True,
            validated=True,
        ),
    ]
    inproc, _ = run_in_process(config, queues, jobs, executors)
    in_sched = {job.id for job, _ in inproc.scheduled}
    assert in_sched == {"zzz"}

    client, _ = sidecar_env(config)
    sid = client.create_session()
    client.sync_state(
        sid, jobs=jobs, executors=executors, queues=queues, factory=F
    )
    resp = client.schedule_round(sid, now_ns=NOW_NS)
    assert {l.job_id for l in resp.scheduled} == {"zzz"}


def test_sidecar_session_id_collision_rejected(sidecar_env):
    """A caller-chosen session id that is already live must abort
    ALREADY_EXISTS, never silently replace the existing mirror."""
    client, _ = sidecar_env(config_for(False))
    assert client.create_session("prod") == "prod"
    with pytest.raises(grpc.RpcError) as err:
        client.create_session("prod")
    assert err.value.code() == grpc.StatusCode.ALREADY_EXISTS
    client.close_session("prod")
    assert client.create_session("prod") == "prod"  # reusable after close


def test_sidecar_short_job_penalty_rides_terminal_runs(sidecar_env):
    """A terminal job's final run must cross the wire (pool + running_ns):
    the short-job penalty keeps charging the queue for it, and a preempted
    run is exempt -- both mirrored through job_state_of."""
    import dataclasses as dc

    base = config_for(False)
    pool = dc.replace(
        base.pools[0], short_job_penalty_cutoff_s=3600.0
    )
    config = dc.replace(base, pools=(pool,))
    F = config.resource_list_factory()
    nodes = [
        NodeSpec(
            id=f"n{i}",
            pool="default",
            executor="ex1",
            total_resources=F.from_mapping({"cpu": "4", "memory": "16"}),
        )
        for i in range(2)
    ]
    executors = [
        ExecutorSnapshot(
            id="ex1",
            pool="default",
            nodes=tuple(nodes),
            last_update_ns=NOW_NS,
        )
    ]
    queues = [Queue("churner", 1.0), Queue("steady", 1.0)]

    def terminal_job(jid, preempted):
        s = JobSpec(
            id=jid,
            queue="churner",
            jobset="s",
            priority_class="pc-low",
            submit_time=1.0,
            resources=F.from_mapping({"cpu": "4", "memory": "8"}),
        )
        return Job(
            spec=s,
            queued=False,
            validated=True,
            failed=True,
            runs=(
                JobRun(
                    id=f"run-{jid}",
                    job_id=jid,
                    node_id="n0",
                    node_name="n0",
                    pool="default",
                    running=False,
                    failed=not preempted,
                    preempted=preempted,
                    run_attempted=True,
                    running_ns=NOW_NS - 10**9,  # died 1s in: "short"
                ),
            ),
        )

    for preempted, expect_penalty in ((False, True), (True, False)):
        jobs = [terminal_job("dead1", preempted)]
        inproc, _ = run_in_process(config, queues, jobs, executors)
        client, sidecar = sidecar_env(config)
        sid = client.create_session()
        client.sync_state(
            sid, jobs=jobs, executors=executors, queues=queues, factory=F
        )
        # the penalty is visible via the algo's internal scan: mirror and
        # source must agree on whether the dead run still charges churner
        session = sidecar.session(sid)
        mirrored = session.jobdb.read_txn().get("dead1")
        assert session.algo.short_job_penalty.applies(
            mirrored, NOW_NS
        ) is expect_penalty, f"preempted={preempted}"
