"""A minimal OIDC identity provider for login-flow tests.

Implements just enough of the spec for the lookout UI's authorization-code
+ PKCE flow (lookout/oidc.py) to run end-to-end against it:

  GET  /.well-known/openid-configuration   discovery document
  GET  /authorize      auto-approves (no login form): validates client_id +
                       redirect_uri shape + PKCE challenge present, mints a
                       single-use code bound to (challenge, redirect_uri),
                       302s back with code + state
  POST /token          authorization_code grant: verifies the code, the
                       redirect_uri echo and the S256 code_verifier, then
                       issues an HS256-signed JWT access token (+ id_token,
                       refresh_token).  refresh_token grant: rotates the
                       access token.  Counters record every grant so tests
                       can assert refresh happened.
  GET  /logout         end_session endpoint; records the hit.

Tokens sign with HS256 over `secret`, so the server's chain validates them
with an OidcAuthenticator key of "hs256:<secret>" -- the same trust setup a
deployment gets from the IdP's JWKS.
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import json
import secrets as pysecrets
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlencode, urlparse


def _b64url(data: bytes) -> str:
    return base64.urlsafe_b64encode(data).rstrip(b"=").decode()


def make_jwt(claims: dict, secret: str, kid: str = "") -> str:
    header = {"alg": "HS256", "typ": "JWT"}
    if kid:
        header["kid"] = kid
    signed = (
        _b64url(json.dumps(header).encode())
        + "."
        + _b64url(json.dumps(claims).encode())
    )
    sig = hmac.new(secret.encode(), signed.encode(), hashlib.sha256).digest()
    return signed + "." + _b64url(sig)


class MockIdp:
    def __init__(
        self,
        *,
        issuer_path: str = "",
        secret: str = "idp-signing-secret",
        audience: str = "lookout-ui",
        subject: str = "alice",
        groups: tuple = ("sre",),
        access_ttl_s: float = 3600.0,
        client_id: str = "lookout-ui",
        client_secret: str = "",
        expected_scope: str = "",
    ):
        self.secret = secret
        self.audience = audience
        self.subject = subject
        self.groups = groups
        self.access_ttl_s = access_ttl_s
        self.client_id = client_id
        self.client_secret = client_secret
        self.expected_scope = expected_scope
        self.codes: dict[str, dict] = {}  # code -> {challenge, redirect_uri}
        self.refresh_tokens: set[str] = set()
        self.code_grants = 0
        self.refresh_grants = 0
        self.logouts = 0
        self.authorize_requests: list[dict] = []
        idp = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):
                pass

            def _json(self, obj, status=200):
                body = json.dumps(obj).encode()
                self.send_response(status)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):  # noqa: N802
                parsed = urlparse(self.path)
                qs = {k: v[0] for k, v in parse_qs(parsed.query).items()}
                if parsed.path == "/.well-known/openid-configuration":
                    self._json(
                        {
                            "issuer": idp.issuer,
                            "authorization_endpoint": idp.base + "/authorize",
                            "token_endpoint": idp.base + "/token",
                            "end_session_endpoint": idp.base + "/logout",
                        }
                    )
                elif parsed.path == "/authorize":
                    idp.authorize_requests.append(qs)
                    if qs.get("client_id") != idp.client_id:
                        self._json({"error": "unknown client"}, 400)
                        return
                    if qs.get("response_type") != "code":
                        self._json({"error": "unsupported response_type"}, 400)
                        return
                    if qs.get("code_challenge_method") != "S256" or not qs.get(
                        "code_challenge"
                    ):
                        self._json({"error": "PKCE required"}, 400)
                        return
                    if idp.expected_scope and qs.get("scope") != idp.expected_scope:
                        self._json({"error": "bad scope"}, 400)
                        return
                    code = pysecrets.token_urlsafe(16)
                    idp.codes[code] = {
                        "challenge": qs["code_challenge"],
                        "redirect_uri": qs.get("redirect_uri", ""),
                    }
                    sep = "&" if "?" in qs.get("redirect_uri", "") else "?"
                    self.send_response(302)
                    self.send_header(
                        "Location",
                        qs.get("redirect_uri", "")
                        + sep
                        + urlencode(
                            {"code": code, "state": qs.get("state", "")}
                        ),
                    )
                    self.send_header("Content-Length", "0")
                    self.end_headers()
                elif parsed.path == "/logout":
                    idp.logouts += 1
                    self._json({"ok": True})
                else:
                    self._json({"error": "not found"}, 404)

            def do_POST(self):  # noqa: N802
                if urlparse(self.path).path != "/token":
                    self._json({"error": "not found"}, 404)
                    return
                length = int(self.headers.get("Content-Length", "0"))
                form = {
                    k: v[0]
                    for k, v in parse_qs(
                        self.rfile.read(length).decode()
                    ).items()
                }
                if form.get("client_id") != idp.client_id:
                    self._json({"error": "invalid_client"}, 401)
                    return
                if idp.client_secret and form.get("client_secret") != idp.client_secret:
                    self._json({"error": "invalid_client"}, 401)
                    return
                grant = form.get("grant_type")
                if grant == "authorization_code":
                    entry = idp.codes.pop(form.get("code", ""), None)
                    if entry is None:
                        self._json({"error": "invalid_grant"}, 400)
                        return
                    if form.get("redirect_uri") != entry["redirect_uri"]:
                        self._json({"error": "redirect_uri mismatch"}, 400)
                        return
                    verifier = form.get("code_verifier", "")
                    expect = _b64url(
                        hashlib.sha256(verifier.encode()).digest()
                    )
                    if expect != entry["challenge"]:
                        self._json({"error": "PKCE verification failed"}, 400)
                        return
                    idp.code_grants += 1
                    self._json(idp._token_response())
                elif grant == "refresh_token":
                    token = form.get("refresh_token")
                    if token not in idp.refresh_tokens:
                        self._json({"error": "invalid_grant"}, 400)
                        return
                    # single-use rotation (the strict IdP posture): clients
                    # must store the rotated token from the response
                    idp.refresh_tokens.discard(token)
                    idp.refresh_grants += 1
                    self._json(idp._token_response())
                else:
                    self._json({"error": "unsupported_grant_type"}, 400)

        self._httpd = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self.port = self._httpd.server_address[1]
        self.base = f"http://127.0.0.1:{self.port}"
        self.issuer = self.base + issuer_path
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True
        )
        self._thread.start()

    def _token_response(self) -> dict:
        now = time.time()
        claims = {
            "iss": self.issuer,
            "aud": self.audience,
            "sub": self.subject,
            "groups": list(self.groups),
            "iat": now,
            "exp": now + self.access_ttl_s,
        }
        refresh = pysecrets.token_urlsafe(16)
        self.refresh_tokens.add(refresh)
        return {
            "access_token": make_jwt(claims, self.secret),
            "id_token": make_jwt(dict(claims, nonce=""), self.secret),
            "refresh_token": refresh,
            "token_type": "Bearer",
            "expires_in": self.access_ttl_s,
        }

    def stop(self) -> None:
        self._httpd.shutdown()
        self._thread.join(timeout=5)
        self._httpd.server_close()
