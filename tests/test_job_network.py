"""Per-job network objects: typed services + ingress (VERDICT r4 #5).

Reference behavior: pkg/api/submit.proto ServiceConfig/IngressConfig,
validation in internal/server/submit/validation/submit_request.go:84-107,
materialisation in internal/executor/util/kubernetes_object.go, and the
executor's StandaloneIngressInfo report surfaced by lookout.
"""

from __future__ import annotations

import pytest

from armada_tpu.core.config import SchedulingConfig
from armada_tpu.core.types import IngressSpec, JobSpec, ServiceSpec
from armada_tpu.server.submit import JobSubmitItem
from armada_tpu.server.validation import ValidationError, validate_submission

CFG = SchedulingConfig(shape_bucket=32, enable_assertions=True)
F = CFG.resource_list_factory()


def item(**kw):
    return JobSubmitItem(resources={"cpu": "1", "memory": "1"}, **kw)


# ---- validation (submit_request.go:84-107) ----------------------------------


def test_ingress_validation_rules():
    validate_submission(
        [item(ingress=(IngressSpec(ports=(8080,)),))], CFG
    )
    with pytest.raises(ValidationError, match="zero ports"):
        validate_submission([item(ingress=(IngressSpec(),))], CFG)
    with pytest.raises(ValidationError, match="two ingress configurations"):
        validate_submission(
            [
                item(
                    ingress=(
                        IngressSpec(ports=(8080, 9090)),
                        IngressSpec(ports=(9090,)),
                    )
                )
            ],
            CFG,
        )
    with pytest.raises(ValidationError, match="out of range"):
        validate_submission([item(ingress=(IngressSpec(ports=(0,)),))], CFG)


def test_service_validation_rules():
    validate_submission(
        [item(services=(ServiceSpec(type="Headless", ports=(9000,)),))], CFG
    )
    with pytest.raises(ValidationError, match="unknown service type"):
        validate_submission(
            [item(services=(ServiceSpec(type="LoadBalancer", ports=(1,)),))],
            CFG,
        )
    with pytest.raises(ValidationError, match="zero ports"):
        validate_submission([item(services=(ServiceSpec(),))], CFG)


# ---- wire round trips --------------------------------------------------------


def test_spec_round_trips_through_events_proto():
    from armada_tpu.events.convert import job_spec_from_proto, job_spec_to_proto

    spec = JobSpec(
        id="j1",
        queue="q",
        jobset="s",
        resources=F.from_mapping({"cpu": "1", "memory": "1"}),
        services=(ServiceSpec(type="Headless", ports=(9000, 9001), name="svc"),),
        ingress=(
            IngressSpec(
                ports=(8080,),
                annotations={"nginx": "on"},
                tls_enabled=True,
                cert_name="cert1",
            ),
        ),
    )
    msg = job_spec_to_proto(spec)
    back = job_spec_from_proto("j1", "q", "s", msg, F)
    assert back.services == spec.services
    assert back.ingress == spec.ingress


def test_submit_item_round_trips_through_rpc_proto():
    from armada_tpu.rpc.convert import (
        submit_item_from_proto,
        submit_item_to_proto,
    )

    it = item(
        services=(ServiceSpec(ports=(7000,)),),
        ingress=(IngressSpec(ports=(7000,), use_cluster_ip=True),),
    )
    back = submit_item_from_proto(submit_item_to_proto(it))
    assert back.services == it.services
    assert back.ingress == it.ingress


# ---- fake cluster + end-to-end ingest → lookout ------------------------------


def test_network_objects_flow_to_lookout(tmp_path):
    """Submit a job with a service + ingress; once it RUNs the executor
    reports StandaloneIngressInfo and lookout's job details carry the
    addresses (the reference lookout's ingress panel)."""
    from armada_tpu.ingest.pipeline import IngestionPipeline
    from armada_tpu.lookout import LookoutDb, LookoutQueries, lookout_converter
    from armada_tpu.server.queues import QueueRecord
    from tests.control_plane import ControlPlane

    plane = ControlPlane.build(tmp_path, runtime_s=50.0)
    lookoutdb = LookoutDb(":memory:")
    lookout_pipeline = IngestionPipeline(
        plane.log, lookoutdb, lookout_converter, consumer_name="lookout"
    )
    try:
        plane.queues.create(QueueRecord("teamnet"))
        (job_id,) = plane.server.submit_jobs(
            "teamnet",
            "set1",
            [
                item(
                    services=(ServiceSpec(type="NodePort", ports=(8080,)),),
                    ingress=(IngressSpec(ports=(8080,)),),
                )
            ],
        )
        from armada_tpu.executor.cluster import PodPhase

        cluster = plane.executors[0].cluster
        plane.run_until(
            lambda: any(
                p.phase is PodPhase.RUNNING for p in cluster.pod_states()
            ),
            max_steps=60,
        )
        plane.step()  # one more cycle so the RUNNING report lands in the log
        lookout_pipeline.run_until_caught_up()
        details = LookoutQueries(lookoutdb).get_job_details(job_id)
        assert details is not None
        assert details["ingress"], "running job must expose its addresses"
        assert "8080" in details["ingress"]
        addr = details["ingress"]["8080"]
        assert f"{job_id}-8080." in addr or ":" in addr
        # the fake cluster materialised the objects next to the pod
        run_id = next(iter(cluster._pods))
        services, ingresses = cluster.pod_network_objects(run_id)
        assert services and ingresses
    finally:
        plane.close()
        lookoutdb.close()


# ---- real-kube adapter against the fake apiserver ----------------------------


def test_kube_adapter_materialises_and_cleans_network_objects():
    from armada_tpu.executor.kubernetes import (
        RUN_LABEL,
        KubernetesClusterContext,
    )
    from tests.fake_kube_api import FakeKubeApi

    api = FakeKubeApi()
    try:
        ctx = KubernetesClusterContext(api.url, F, pool_label="pool")
        spec = JobSpec(
            id="j1",
            queue="q",
            resources=F.from_mapping({"cpu": "1", "memory": "1"}),
            services=(ServiceSpec(type="NodePort", ports=(8080,), name="mysvc"),),
            ingress=(IngressSpec(ports=(9090,), tls_enabled=True),),
        )
        ctx.submit_pod("run-1", "j1", "q", "js", spec, "worker-1")
        # the declared service, plus the synthesized backend for the
        # serviceless ingress port
        assert ("default", "mysvc") in api.services
        synth = [k for k in api.services if k[1].startswith("armada-run-1-ingsvc")]
        assert synth
        svc = api.services[("default", "mysvc")]
        assert svc["spec"]["selector"] == {RUN_LABEL: "run-1"}
        assert svc["metadata"]["ownerReferences"][0]["name"] == "armada-run-1"
        assert ("default", "armada-run-1-ing0") in api.ingresses
        ing = api.ingresses[("default", "armada-run-1-ing0")]
        rule = ing["spec"]["rules"][0]
        assert rule["host"] == "j1-9090.jobs.local"
        assert ing["spec"]["tls"][0]["hosts"] == ["j1-9090.jobs.local"]
        net = ctx.pod_network("run-1")
        assert net[9090] == "j1-9090.jobs.local"
        assert net[8080].startswith("worker-1:30")  # allocated NodePort
        ctx.delete_pod("run-1")
        assert not api.services and not api.ingresses
        assert not ctx.pod_network("run-1")
    finally:
        api.stop()
