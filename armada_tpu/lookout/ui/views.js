// Server-side saved views (lookout DB saved_view table -- the reference
// UI's server-backed job-table views).
import { $, esc } from "./util.js";
import { j, raw } from "./api.js";

let serverViews = {};

export async function loadViews() {
  try {
    const d = await j("/api/views");
    serverViews = Object.fromEntries(
      d.views.map((v) => [v.name, JSON.parse(v.payload)]));
  } catch (e) { serverViews = {}; }
  renderViews();
}

function renderViews() {
  const sel = $("views").value;
  $("views").innerHTML = '<option value="">saved views…</option>' +
    Object.keys(serverViews).sort().map((n) =>
      `<option value="${esc(n)}">${esc(n)}</option>`).join("");
  if (serverViews[sel] !== undefined) $("views").value = sel;
}

export function wireViews(state, refresh) {
  $("save-view").onclick = async () => {
    const name = prompt("view name:");
    if (!name) return;
    const payload = Object.fromEntries(
      ["f-queue", "f-jobset", "f-state", "f-ann", "f-group", "f-groupkey"]
        .map((id) => [id, $(id).value]));
    // raw() (not bare fetch): a dead session bounces to /login instead of
    // silently losing the save
    const r = await raw("/api/views", {
      method: "POST", headers: {"Content-Type": "application/json"},
      body: JSON.stringify({name, payload}),
    });
    if (!r.ok) { alert(`save failed: ${(await r.json()).error}`); return; }
    await loadViews();
    $("views").value = name;
  };
  $("del-view").onclick = async () => {
    const name = $("views").value;
    if (!name || !confirm(`delete view "${name}"?`)) return;
    const r = await raw("/api/views/" + encodeURIComponent(name),
                        {method: "DELETE"});
    if (!r.ok) { alert(`delete failed: ${(await r.json()).error}`); return; }
    $("views").value = "";
    await loadViews();
  };
  $("views").addEventListener("change", () => {
    const v = serverViews[$("views").value];
    if (!v) return;
    for (const [id, val] of Object.entries(v)) { if ($(id)) $(id).value = val; }
    $("f-groupkey").style.display =
      $("f-group").value === "annotation" ? "" : "none";
    state.drill = [];
    state.skip = 0;
    refresh();
  });
}
