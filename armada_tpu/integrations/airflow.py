"""Airflow operator for armada-tpu.

Equivalent of the reference's airflow integration (third_party/airflow/
armada/operators/armada.py ArmadaOperator): an Airflow task that submits one
job, polls its jobset events until the job reaches a terminal state, raises
on failure/cancellation/preemption, and cancels the job when the Airflow task
is killed (on_kill, armada.py:313).

Airflow itself is an optional dependency: when it is not installed the
operator still imports and `execute(context=None)` works standalone, so the
submit-and-wait flow is testable (and usable as a plain blocking helper)
without an Airflow deployment.
"""

from __future__ import annotations

import time
from typing import Mapping, Optional

try:  # pragma: no cover - exercised only under a real Airflow install
    from airflow.exceptions import AirflowException
    from airflow.models import BaseOperator
except Exception:  # Airflow absent: minimal stand-ins with the same contract

    class AirflowException(RuntimeError):
        pass

    class BaseOperator:  # noqa: D401 - duck-typed stand-in
        """Stand-in exposing the attributes ArmadaOperator relies on."""

        def __init__(self, task_id: str = "", **kwargs):
            self.task_id = task_id

TERMINAL_STATES = ("succeeded", "failed", "cancelled", "preempted")
_FAILURE_EVENTS = {
    "job_errors": "failed",
    "cancelled_job": "cancelled",
}


class ArmadaOperator(BaseOperator):
    """Submit one job and wait for it to finish.

    :param armada_url: gRPC address of the control plane ("host:port").
    :param queue: target queue (must exist).
    :param job: the job shape -- a mapping accepted by JobSubmitItem
        (resources, priority, priorityClass, annotations, ...).
    :param jobset: jobset id; defaults to the Airflow task id.
    :param poll_interval_s: seconds between event polls (armada.py:117).
    :param timeout_s: overall deadline; 0 = wait forever.
    """

    template_fields = ("queue", "jobset")

    def __init__(
        self,
        *,
        armada_url: str,
        queue: str,
        job: Mapping,
        jobset: str = "",
        poll_interval_s: float = 5.0,
        timeout_s: float = 0.0,
        task_id: str = "armada-job",
        **kwargs,
    ):
        super().__init__(task_id=task_id, **kwargs)
        self.armada_url = armada_url
        self.queue = queue
        self.job = dict(job)
        self.jobset = jobset or task_id
        self.poll_interval_s = poll_interval_s
        self.timeout_s = timeout_s
        self.job_id: Optional[str] = None
        self._client = None

    # --- client plumbing ----------------------------------------------------

    def _get_client(self):
        if self._client is None:
            from armada_tpu.rpc.client import ArmadaClient

            self._client = ArmadaClient(self.armada_url)
        return self._client

    def _close(self) -> None:
        if self._client is not None:
            self._client.close()
            self._client = None

    # --- the task -----------------------------------------------------------

    def execute(self, context=None) -> str:
        """Submit, then block until terminal; returns the job id."""
        from armada_tpu.server import JobSubmitItem

        client = self._get_client()
        try:
            item = JobSubmitItem(**_snake_item(self.job))
            (self.job_id,) = client.submit_jobs(self.queue, self.jobset, [item])
            state = self._poll_for_termination(client)
            if state != "succeeded":
                raise AirflowException(
                    f"armada job {self.job_id} ended {state}"
                )
            return self.job_id
        finally:
            self._close()

    def _poll_for_termination(self, client) -> str:
        deadline = time.monotonic() + self.timeout_s if self.timeout_s else None
        from_idx = 0
        while True:
            state, from_idx = self._scan_events(client, from_idx)
            if state in TERMINAL_STATES:
                return state
            if deadline is not None and time.monotonic() > deadline:
                # Airflow only calls on_kill on external termination, not when
                # execute raises -- cancel here or the job leaks on-cluster.
                try:
                    client.cancel_jobs(
                        self.queue,
                        self.jobset,
                        [self.job_id],
                        reason=f"operator timeout after {self.timeout_s}s",
                    )
                except Exception:
                    pass  # best effort; the timeout error is the headline
                raise AirflowException(
                    f"armada job {self.job_id} timed out after {self.timeout_s}s"
                    " (cancellation requested)"
                )
            time.sleep(self.poll_interval_s)

    def _scan_events(self, client, from_idx: int):
        """One pass over new jobset events; returns (state | None, next idx)."""
        for idx, seq in client.get_jobset_events(
            self.queue, self.jobset, from_idx=from_idx
        ):
            from_idx = idx + 1
            for ev in seq.events:
                kind = ev.WhichOneof("event")
                ev_job_id = getattr(getattr(ev, kind), "job_id", "")
                if ev_job_id != self.job_id:
                    continue
                if kind == "job_succeeded":
                    return "succeeded", from_idx
                if kind == "job_run_preempted":
                    return "preempted", from_idx
                if kind in _FAILURE_EVENTS:
                    return _FAILURE_EVENTS[kind], from_idx
        return None, from_idx

    def on_kill(self) -> None:
        """Airflow task killed: cancel the armada job (armada.py:313)."""
        if self.job_id is None:
            return
        try:
            client = self._get_client()
            client.cancel_jobs(
                self.queue, self.jobset, [self.job_id], reason="airflow task killed"
            )
        finally:
            self._close()


def _snake_item(job: Mapping) -> dict:
    """Accept both snake_case and the reference's camelCase job keys."""
    aliases = {
        "priorityClass": "priority_class",
        "priorityClassName": "priority_class",
        "nodeSelector": "node_selector",
        "gangId": "gang_id",
        "gangCardinality": "gang_cardinality",
        "clientId": "client_id",
    }
    return {aliases.get(k, k): v for k, v in job.items()}
