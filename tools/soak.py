"""Standing soak drill: sustained open-loop traffic + streaming SLOs.

Drives the full in-process serving stack (armada_tpu/loadgen/soak.py) for a
wall-clock window at a target event rate, optionally arming an ARMADA_FAULT
site mid-window so failover is measured as a latency distribution under
load.  Prints exactly ONE JSON line (same contract as bench.py); exit 0
when the run's invariants held (no dropped/double-leased jobs, fault fired
and re-promoted when configured).

    python tools/soak.py --window 120 --rate 500
    python tools/soak.py --window 60 --rate 200 --fault device_round:hang

Env downscale (CPU hosts): ARMADA_SOAK_WINDOW_S, ARMADA_SOAK_RATE,
ARMADA_SOAK_NODES, ARMADA_SOAK_QUEUES, ARMADA_SOAK_DSN (external postgres
for the scheduler DB, through the pgwire driver).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--window", type=float, default=None, help="soak window seconds")
    ap.add_argument("--rate", type=float, default=None, help="target events/s")
    ap.add_argument(
        "--process",
        choices=("poisson", "bursty", "ramp"),
        default="poisson",
        help="arrival process shape",
    )
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--nodes", type=int, default=None)
    ap.add_argument("--queues", type=int, default=None)
    ap.add_argument(
        "--fault",
        default=None,
        help="ARMADA_FAULT entry to arm mid-soak, e.g. device_round:hang",
    )
    ap.add_argument(
        "--fault-at",
        type=float,
        default=0.5,
        help="when to arm the fault, as a fraction of the window",
    )
    ap.add_argument(
        "--watchdog-s",
        type=float,
        default=5.0,
        help="round deadline while a fault is armed",
    )
    ap.add_argument(
        "--crash",
        nargs="?",
        const=0.5,
        type=float,
        default=None,
        metavar="FRAC",
        help="mid-soak kill/restart leg: checkpoint, wipe the materialized "
        "store, rebuild from snapshot + log-suffix replay at FRAC of the "
        "window (default 0.5); RTO lands in restart_recovery_s",
    )
    ap.add_argument(
        "--commit-k",
        type=int,
        default=None,
        dest="commit_k",
        help="arm the conflict-free multi-commit kernel (ARMADA_COMMIT_K) "
        "for the whole soak window, including the fault/crash legs (the "
        "drill's env save/restore keeps it armed); default: inherit the "
        "environment",
    )
    ap.add_argument(
        "--ingest-shards",
        type=int,
        default=None,
        dest="ingest_shards",
        help="partition-parallel ingestion width (ingest/shards.py; sets "
        "ARMADA_INGEST_SHARDS for the window incl. the fault/crash legs "
        "via the drill's env save/restore); default: inherit the "
        "environment (1 = serial)",
    )
    ap.add_argument(
        "--store-shards",
        type=int,
        default=None,
        dest="store_shards",
        help="sharded materialized store width (ingest/storeunion.py; sets "
        "ARMADA_STORE_SHARDS for the window; the ingest width rounds up to "
        "a multiple); default: inherit the environment (1 = one writer)",
    )
    ap.add_argument(
        "--json",
        action="store_true",
        help="JSON-line output (the default; kept for bench.py symmetry)",
    )
    args = ap.parse_args()
    if args.commit_k is not None:
        os.environ["ARMADA_COMMIT_K"] = str(args.commit_k)
    if args.ingest_shards is not None:
        os.environ["ARMADA_INGEST_SHARDS"] = str(args.ingest_shards)
    if args.store_shards is not None:
        os.environ["ARMADA_STORE_SHARDS"] = str(args.store_shards)

    # Tests force CPU; a standalone run uses whatever backend is healthy.
    from armada_tpu.loadgen.soak import SoakConfig, run_soak_cli

    overrides = {}
    if args.window is not None:
        overrides["window_s"] = args.window
    if args.rate is not None:
        overrides["target_eps"] = args.rate
    if args.nodes is not None:
        overrides["num_nodes"] = args.nodes
    if args.queues is not None:
        overrides["num_queues"] = args.queues
    report = run_soak_cli(
        SoakConfig.from_env(
            process=args.process,
            seed=args.seed,
            fault=args.fault,
            fault_at_frac=args.fault_at,
            watchdog_s=args.watchdog_s,
            crash_at_frac=args.crash,
            **overrides,
        )
    )
    print(json.dumps(report, default=float))
    return 0 if report.get("ok") else 1


if __name__ == "__main__":
    try:
        sys.exit(main())
    except SystemExit:
        raise
    except Exception as e:  # one-JSON-line contract, like bench.py
        import traceback

        traceback.print_exc()
        print(
            json.dumps(
                {"tool": "soak", "ok": False, "error": f"{type(e).__name__}: {e}"[:500]}
            )
        )
        sys.exit(2)
