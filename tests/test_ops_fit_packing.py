import numpy as np
import jax.numpy as jnp

from armada_tpu.ops.fit import (
    allocatable_from_used,
    dynamic_fit,
    job_fit,
    static_fit,
)
from armada_tpu.ops.packing import (
    bind_counts,
    bind_to_node,
    member_capacity,
    node_packing_score,
    select_best_node,
    select_gang_nodes,
    unbind_from_node,
)


def test_allocatable_suffix_sum():
    # 2 priority levels, 1 node, 1 resource; total 10.
    total = np.array([[10.0]], np.float32)
    used = np.zeros((2, 1, 1), np.float32)
    used[0, 0, 0] = 3.0  # low-priority job uses 3
    used[1, 0, 0] = 2.0  # high-priority job uses 2
    alloc = np.asarray(allocatable_from_used(total, used))
    # At low priority you see both users: 10-5. At high priority only the
    # high-priority usage blocks you: 10-2.
    assert alloc[0, 0, 0] == 5.0
    assert alloc[1, 0, 0] == 8.0


def test_dynamic_and_static_fit():
    alloc = np.array([[4.0, 8.0], [1.0, 8.0]], np.float32)  # 2 nodes x 2 res
    req = np.array([2.0, 8.0], np.float32)
    fit = np.asarray(dynamic_fit(alloc, req))
    assert fit.tolist() == [True, False]

    compat = np.array([[True, False]])  # 1 key x 2 types
    node_type = np.array([0, 1, 0])
    s = np.asarray(static_fit(jnp.asarray(compat), 0, jnp.asarray(node_type)))
    assert s.tolist() == [True, False, True]


def test_job_fit_pinning():
    alloc = np.ones((3, 1), np.float32)
    req = np.zeros((1,), np.float32)
    compat = jnp.ones((1, 1), bool)
    node_type = jnp.zeros((3,), jnp.int32)
    ok = jnp.ones((3,), bool)
    free = np.asarray(
        job_fit(compat, 0, node_type, jnp.asarray(alloc), jnp.asarray(req), ok, jnp.int32(-1))
    )
    pinned = np.asarray(
        job_fit(compat, 0, node_type, jnp.asarray(alloc), jnp.asarray(req), ok, jnp.int32(1))
    )
    assert free.tolist() == [True, True, True]
    assert pinned.tolist() == [False, True, False]


def test_select_best_node_is_best_fit():
    # Fuller node (lower score) wins; unfit nodes ignored; ties -> lowest index.
    alloc = np.array([[8.0], [2.0], [2.0], [1.0]], np.float32)
    inv = np.array([1.0 / 8.0], np.float32)
    score = node_packing_score(jnp.asarray(alloc), jnp.asarray(inv))
    mask = jnp.asarray(np.array([True, True, True, False]))
    found, node = select_best_node(mask, score)
    assert bool(found) and int(node) == 1  # fullest fitting; tie 1 vs 2 -> 1

    found, node = select_best_node(jnp.zeros((4,), bool), score)
    assert not bool(found) and int(node) == -1


def test_member_capacity_and_gang_select():
    alloc = np.array([[4.0, 2.0], [10.0, 0.5], [6.0, 9.0]], np.float32)
    req = np.array([2.0, 1.0], np.float32)
    cap = np.asarray(member_capacity(jnp.asarray(alloc), jnp.asarray(req)))
    assert cap.tolist() == [2, 0, 3]

    score = jnp.asarray(np.array([0.1, 0.2, 0.3], np.float32))
    mask = jnp.ones((3,), bool)
    feasible, counts = select_gang_nodes(mask, jnp.asarray(cap), 4, score)
    assert bool(feasible)
    # Fills fullest (node 0, cap 2) then node 2 for the remaining 2 members.
    assert np.asarray(counts).tolist() == [2, 0, 2]

    feasible, counts = select_gang_nodes(mask, jnp.asarray(cap), 6, score)
    assert not bool(feasible)
    assert np.asarray(counts).sum() == 0  # all-or-nothing

    # zero-resource request: capacity clamps, doesn't overflow
    cap0 = np.asarray(member_capacity(jnp.asarray(alloc), jnp.zeros((2,), np.float32)))
    assert (cap0 > 0).all()


def test_bind_unbind_roundtrip():
    used = jnp.zeros((2, 3, 2), jnp.float32)
    req = jnp.asarray(np.array([2.0, 1.0], np.float32))
    u1 = bind_to_node(used, 1, req, 1, count=2)
    assert np.asarray(u1)[1, 1].tolist() == [4.0, 2.0]
    u2 = unbind_from_node(u1, 1, req, 1, count=2)
    assert np.asarray(u2).sum() == 0.0

    counts = jnp.asarray(np.array([1, 0, 3], np.int32))
    u3 = bind_counts(used, counts, req, 0)
    got = np.asarray(u3)
    assert got[0, 0].tolist() == [2.0, 1.0]
    assert got[0, 2].tolist() == [6.0, 3.0]
