# Fixture for rule `axis1-scatter` (linted under armada_tpu/models/).
# One true positive (marked TP) + near misses the rule must NOT flag.


def update_cache(cache, idx, rows, scalar_row):
    cache = cache.at[:, idx].set(rows)  # TP
    # near-miss: constant scalar lane keeps the copy bounded
    cache = cache.at[:, 0].set(scalar_row)
    # near-miss: leading-dim (flat) index vector -- the prescribed layout
    cache = cache.at[idx].set(rows)
    # near-miss: static unroll -- python loop var over range() is a
    # trace-time constant lane
    for i in range(4):
        cache = cache.at[:, i].set(scalar_row)
    return cache
