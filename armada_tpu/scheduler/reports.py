"""Scheduling reports: "why (was | wasn't) my job scheduled?" forensics.

Equivalent of the reference's scheduling-context reports
(internal/scheduler/reports: repository.go keeps the most recent round's
SchedulingContext per queue and per job; server.go serves them over gRPC;
armadactl surfaces them).  After every scheduling cycle the repository
records, per pool: round stats + per-queue shares, and per job: what happened
to it (scheduled where / failed why / preempted), in bounded LRU caches.
"""

from __future__ import annotations

import collections
import itertools
import threading
import time
from typing import Optional


class SchedulingReportsRepository:
    def __init__(self, max_job_reports: int = 10_000):
        self._lock = threading.Lock()
        self._queue_reports: dict[tuple[str, str], dict] = {}  # (pool, queue)
        self._pool_reports: dict[str, dict] = {}
        self._job_reports: collections.OrderedDict[str, dict] = collections.OrderedDict()
        self._max_jobs = max_job_reports
        # Last explain pass per pool (models/explain.py summary()): the
        # /healthz `explain` block and the pool-report forensics.  Explain
        # runs on a cadence (ARMADA_EXPLAIN_INTERVAL), so this holds the
        # most recent attribution, stamped with its cycle time.
        self._explain: dict[str, dict] = {}

    # --- recording (called by the Scheduler after algo.schedule) ------------

    def record_cycle(self, scheduler_result, now: Optional[float] = None) -> None:
        now = time.time() if now is None else now
        # Preemptor attribution (the reference's job report names the
        # preempting job, reports/repository.go preemptedJobReport): record
        # the first job this cycle scheduled onto the preempted run's node.
        # Co-location, not proven causation -- the newcomer may have landed
        # on pre-existing free capacity while the eviction came from
        # fair-share rebalancing elsewhere -- so the reason text says
        # "scheduled onto the freed node", never "preempted by".
        preemptor_of_node: dict[str, tuple] = {}
        if scheduler_result.preempted:  # steady cycles preempt nothing
            for job, run in scheduler_result.scheduled:
                if run.node_id not in preemptor_of_node:
                    preemptor_of_node[run.node_id] = (
                        job.id,
                        job.queue,
                        run.scheduled_at_priority,
                    )
        with self._lock:
            for job, run in scheduler_result.scheduled:
                self._put_job(
                    job.id,
                    {
                        "time": now,
                        "outcome": "scheduled",
                        "node": run.node_id,
                        "executor": run.executor,
                        "pool": run.pool,
                        "queue": job.queue,
                    },
                )
            for job, run in scheduler_result.preempted:
                report = {
                    "time": now,
                    "outcome": "preempted",
                    "node": run.node_id,
                    "queue": job.queue,
                    "reason": "fair-share or oversubscription eviction",
                }
                preemptor = preemptor_of_node.get(run.node_id)
                if preemptor is not None and preemptor[0] != job.id:
                    pj, pq, pp = preemptor
                    report["preemptor_job"] = pj
                    report["preemptor_queue"] = pq
                    report["preemptor_priority"] = pp
                    report["reason"] = (
                        "fair-share or oversubscription eviction; queue "
                        f"{pq!r} scheduled onto the freed node at priority "
                        f"{pp} this cycle"
                    )
                self._put_job(job.id, report)
            for stats in scheduler_result.pools:
                o = stats.outcome
                explain = getattr(o, "explain", None)
                # Bounded like the reference's
                # maxJobSchedulingContextsPerExecutor (config.yaml:107): a
                # round can retire a whole unfeasible key class (~the entire
                # backlog in o.failed); decoding more ids than the LRU can
                # hold burns seconds per cycle for entries that would evict
                # each other anyway.
                covered: set = set()
                if explain is not None:
                    # Explain cycles carry per-job reason codes (lazy pairs,
                    # same bounded decode discipline).
                    for job_id, reason in itertools.islice(
                        explain.iter_job_reasons(), self._max_jobs
                    ):
                        covered.add(job_id)
                        self._put_job(
                            job_id,
                            {
                                "time": now,
                                "outcome": "failed",
                                "pool": stats.pool,
                                "reason": reason,
                            },
                        )
                # Failed jobs the pass did not pair (decode-time gang
                # unwinds landed in o.failed after the device scan; failed
                # gangs past the fcap) still get the generic report --
                # explain cycles must never answer FEWER jobs than plain
                # ones.  The scan examines at most _max_jobs ids (the same
                # bound the generic branch always had: LazyJobIds makes a
                # full walk O(backlog)).
                for job_id in itertools.islice(o.failed, self._max_jobs):
                    if job_id in covered:
                        continue
                    self._put_job(
                        job_id,
                        {
                            "time": now,
                            "outcome": "failed",
                            "pool": stats.pool,
                            "reason": "no node with sufficient free capacity "
                            "matched the job's scheduling key this round",
                        },
                    )
                pool_report = {
                    "time": now,
                    "num_nodes": stats.num_nodes,
                    "num_queued": stats.num_queued,
                    "num_running": stats.num_running,
                    "scheduled": len(o.scheduled),
                    "preempted": len(o.preempted),
                    "failed": len(o.failed),
                    "iterations": o.num_iterations,
                    # physical while-loop trips under the multi-commit
                    # kernel (ARMADA_COMMIT_K); == iterations at K=1,
                    # 0 on synthetic outcomes that never ran a kernel
                    "kernel_iters": getattr(o, "kernel_iters", 0),
                    "termination": o.termination,
                }
                if explain is not None:
                    summary = explain.summary()
                    pool_report["explain"] = {**summary, "attributed_at": now}
                    self._explain[stats.pool] = {"time": now, **summary}
                elif stats.pool in self._explain:
                    # keep the last attribution visible, stamped with the
                    # cycle it was COMPUTED at -- a stale histogram must
                    # never read as current next to pool_report["time"]
                    block = self._explain[stats.pool]
                    pool_report["explain"] = {
                        **{k: v for k, v in block.items() if k != "time"},
                        "attributed_at": block["time"],
                    }
                self._pool_reports[stats.pool] = pool_report
                for qname, qs in o.queue_stats.items():
                    qr = {
                        "time": now,
                        "pool": stats.pool,
                        "queue": qname,
                        **qs,
                    }
                    # Fairness headroom: how much share the queue could still
                    # claim before its (demand-capped) fair share gates it --
                    # the aggregate ROADMAP items 2/4/5 read.
                    qr["fairness_headroom"] = max(
                        0.0,
                        qs.get("adjusted_fair_share", 0.0)
                        - qs.get("actual_share", 0.0),
                    )
                    if explain is not None:
                        qr["unschedulable"] = dict(
                            explain.queue_counts.get(qname, {})
                        )
                    self._queue_reports[(stats.pool, qname)] = qr

    def explain_summary(self) -> dict:
        """Last explain attribution per pool (the /healthz `explain` block):
        reason counts, fragmentation indices, per-key table, stamped with
        the cycle time it was computed at."""
        with self._lock:
            return {pool: dict(block) for pool, block in self._explain.items()}

    def _put_job(self, job_id: str, report: dict) -> None:
        self._job_reports[job_id] = report
        self._job_reports.move_to_end(job_id)
        while len(self._job_reports) > self._max_jobs:
            self._job_reports.popitem(last=False)

    # --- queries (reports/server.go) ----------------------------------------

    def job_report(self, job_id: str) -> Optional[dict]:
        with self._lock:
            return self._job_reports.get(job_id)

    def queue_report(self, queue: str) -> list[dict]:
        with self._lock:
            return [
                r for (p, q), r in self._queue_reports.items() if q == queue
            ]

    def pool_report(self, pool: Optional[str] = None) -> dict:
        with self._lock:
            if pool is not None:
                return {pool: self._pool_reports.get(pool, {})}
            return dict(self._pool_reports)


def try_job_report(reports, job_id: str) -> Optional[dict]:
    """Best-effort job report for read surfaces that must keep answering
    when the reports backend cannot (a follower cut off from the leader
    behind LeaderProxyingReports): the report, or None on a miss OR any
    backend error.  Shared by the lookout web UI and the REST gateway's
    job-details attachment."""
    if reports is None:
        return None
    try:
        return reports.job_report(job_id)
    except Exception:  # noqa: BLE001 -- proxy outage: serve without it
        return None


class ReportsUnavailable(Exception):
    """A follower could not reach the leader for a report query; the gRPC
    layer maps this to UNAVAILABLE (retryable), never NOT_FOUND."""


class LeaderProxyingReports:
    """Answer report queries on ANY replica (the reference's
    leader_proxying_reports_server.go + leader_client.go).

    Reports record only on the leader (only the leader runs scheduling
    cycles), so a follower replica's repository is empty -- without
    proxying, asking the follower "why wasn't my job scheduled" answers
    NOT_FOUND (VERDICT r3 missing #2).  This wrapper serves locally while
    leader and forwards to the leader's advertised address otherwise,
    discovered through the election record (leader.py lease `address` /
    kube_leader.py Lease annotation).

    `client_factory(address)` returns an object with
    get_job_report/get_queue_report/get_pool_report (rpc/client.py
    ArmadaClient); clients cache per address so leadership churn redials."""

    def __init__(self, local: SchedulingReportsRepository, controller, client_factory):
        self.local = local
        self._controller = controller
        self._client_factory = client_factory
        # Guarded: gRPC worker threads race the cache on leadership churn.
        self._clients_lock = threading.Lock()
        self._clients: dict[str, object] = {}
        self._self_address = ""

    def set_self_address(self, address: str) -> None:
        """This replica's own advertised address, once the port is bound --
        the recursion guard below compares against it."""
        self._self_address = address

    def _leader_client(self):
        # READ-ONLY peek: get_token() acquires/renews the lease, which a
        # query path must never do (a follower answering a report query
        # could otherwise steal an expired lease).
        address = self._controller.leader_address()
        if address is None:
            return None  # we hold the lease (or run standalone): local
        if not address:
            raise ReportsUnavailable(
                "not the leader and the election record carries no leader "
                "address (leader down or a pre-address lease)"
            )
        if self._self_address and address == self._self_address:
            # A misadvertised election record (e.g. another replica launched
            # with OUR --advertised-address) would have us dial ourselves,
            # and each hop would dial again -- unbounded recursion tying up
            # one server thread per hop.  Fail fast instead.
            raise ReportsUnavailable(
                f"election record advertises THIS replica's address "
                f"{address!r} but another replica holds the lease -- check "
                f"each replica's --advertised-address"
            )
        with self._clients_lock:
            client = self._clients.get(address)
            if client is not None:
                return client
            stale = []
            if len(self._clients) > 8:
                # leadership churn: drop dials to old leaders, keeping only
                # the current target.  An RPC in flight on a just-closed
                # channel fails UNAVAILABLE -- the retryable semantic the
                # caller already maps for a gone leader.
                for addr in list(self._clients):
                    if addr != address:
                        stale.append(self._clients.pop(addr))
            client = self._clients[address] = self._client_factory(address)
        for old in stale:  # close outside the lock (network teardown)
            close = getattr(old, "close", None)
            if close is not None:
                try:
                    close()
                except Exception:
                    pass
        return client

    def _proxy(self, call, not_found):
        import grpc

        try:
            return call()
        except grpc.RpcError as e:
            if e.code() == grpc.StatusCode.NOT_FOUND:
                return not_found
            raise ReportsUnavailable(
                f"leader report query failed: {e.code().name}"
            ) from e

    def job_report(self, job_id: str) -> Optional[dict]:
        client = self._leader_client()
        if client is None:
            return self.local.job_report(job_id)
        return self._proxy(lambda: client.get_job_report(job_id), None)

    def queue_report(self, queue: str) -> list[dict]:
        client = self._leader_client()
        if client is None:
            return self.local.queue_report(queue)
        return self._proxy(lambda: client.get_queue_report(queue), [])

    def pool_report(self, pool: Optional[str] = None) -> dict:
        client = self._leader_client()
        if client is None:
            return self.local.pool_report(pool)
        return self._proxy(lambda: client.get_pool_report(pool or ""), {})
