"""EventSequence -> lookout row ops.

Equivalent of the reference's lookoutingester instruction converter
(internal/lookoutingester/instructions/instructions.go): each event updates
the denormalized job/run rows; the state machine mirrors the lookout UI's
job states.
"""

from __future__ import annotations

from armada_tpu.events import events_pb2 as pb


def lookout_converter(sequences) -> list[dict]:
    ops: list[dict] = []
    for seq in sequences:
        for ev in seq.events:
            kind = ev.WhichOneof("event")
            ts = int(ev.created_ns)
            if kind == "submit_job":
                e = ev.submit_job
                milli = dict(e.spec.resources.milli)
                ops.append(
                    {
                        "kind": "insert_job",
                        "job_id": e.job_id,
                        "queue": seq.queue,
                        "jobset": seq.jobset,
                        "namespace": e.spec.namespace,
                        "priority": int(e.spec.priority),
                        "priority_class": e.spec.priority_class,
                        "cpu_milli": int(milli.get("cpu", 0)),
                        "memory": int(milli.get("memory", 0)),
                        "gpu": int(milli.get("nvidia.com/gpu", 0)),
                        "gang_id": e.spec.gang_id,
                        "annotations": dict(e.spec.annotations),
                        # deterministic: stable bytes across the sharded
                        # plane's converter subprocesses (see
                        # ingest/converter.py)
                        "spec": e.spec.SerializeToString(deterministic=True),
                        "ts": ts,
                    }
                )
            elif kind == "reprioritised_job":
                ops.append(
                    {
                        "kind": "job_priority",
                        "job_id": ev.reprioritised_job.job_id,
                        "priority": int(ev.reprioritised_job.priority),
                    }
                )
            elif kind == "reprioritise_job":
                ops.append(
                    {
                        "kind": "job_priority",
                        "job_id": ev.reprioritise_job.job_id,
                        "priority": int(ev.reprioritise_job.priority),
                    }
                )
            elif kind == "reprioritise_job_set":
                ops.append(
                    {
                        "kind": "jobset_priority",
                        "queue": seq.queue,
                        "jobset": seq.jobset,
                        "priority": int(ev.reprioritise_job_set.priority),
                    }
                )
            elif kind == "cancelled_job":
                ops.append(
                    {
                        "kind": "job_state",
                        "job_id": ev.cancelled_job.job_id,
                        "state": "CANCELLED",
                        "ts": ts,
                        "error": ev.cancelled_job.reason,
                    }
                )
            elif kind == "job_succeeded":
                ops.append(
                    {
                        "kind": "job_state",
                        "job_id": ev.job_succeeded.job_id,
                        "state": "SUCCEEDED",
                        "ts": ts,
                    }
                )
            elif kind == "job_errors":
                e = ev.job_errors
                terminal = [err for err in e.errors if err.terminal]
                if terminal:
                    state = (
                        "PREEMPTED"
                        if terminal[0].reason == "preempted"
                        else "FAILED"
                    )
                    ops.append(
                        {
                            "kind": "job_state",
                            "job_id": e.job_id,
                            "state": state,
                            "ts": ts,
                            "error": f"{terminal[0].reason}: {terminal[0].message}",
                        }
                    )
            elif kind == "job_requeued":
                ops.append(
                    {
                        "kind": "job_state",
                        "job_id": ev.job_requeued.job_id,
                        "state": "QUEUED",
                        "ts": ts,
                    }
                )
            elif kind == "job_run_leased":
                e = ev.job_run_leased
                ops.append(
                    {
                        "kind": "insert_run",
                        "run_id": e.run_id,
                        "job_id": e.job_id,
                        "executor": e.executor_id,
                        "node": e.node_id,
                        "ts": ts,
                    }
                )
                ops.append(
                    {
                        "kind": "job_state",
                        "job_id": e.job_id,
                        "state": "LEASED",
                        "ts": ts,
                    }
                )
            elif kind == "job_run_assigned":
                e = ev.job_run_assigned
                ops.append(
                    {"kind": "run_state", "run_id": e.run_id, "state": "PENDING", "ts": ts}
                )
                ops.append(
                    {"kind": "job_state", "job_id": e.job_id, "state": "PENDING", "ts": ts}
                )
            elif kind == "job_run_running":
                e = ev.job_run_running
                ops.append(
                    {
                        "kind": "run_state",
                        "run_id": e.run_id,
                        "state": "RUNNING",
                        "ts": ts,
                        "node": e.node_id,
                    }
                )
                ops.append(
                    {"kind": "job_state", "job_id": e.job_id, "state": "RUNNING", "ts": ts}
                )
            elif kind == "ingress_info":
                e = ev.ingress_info
                ops.append(
                    {
                        "kind": "job_ingress",
                        "job_id": e.job_id,
                        "addresses": {
                            str(port): addr
                            for port, addr in e.addresses.items()
                        },
                    }
                )
            elif kind == "job_run_succeeded":
                e = ev.job_run_succeeded
                ops.append(
                    {"kind": "run_state", "run_id": e.run_id, "state": "SUCCEEDED", "ts": ts}
                )
            elif kind == "job_run_cancelled":
                e = ev.job_run_cancelled
                ops.append(
                    {"kind": "run_state", "run_id": e.run_id, "state": "CANCELLED", "ts": ts}
                )
            elif kind == "job_run_preempted":
                e = ev.job_run_preempted
                ops.append(
                    {"kind": "run_state", "run_id": e.run_id, "state": "PREEMPTED", "ts": ts}
                )
            elif kind == "resource_utilisation":
                e = ev.resource_utilisation
                ops.append(
                    {
                        "kind": "run_usage",
                        "run_id": e.run_id,
                        "usage": {
                            "max": dict(e.max_resources_for_period.milli),
                            "cumulative": dict(e.total_cumulative_usage.milli),
                            "ts": ts,
                        },
                    }
                )
            elif kind == "job_run_errors":
                e = ev.job_run_errors
                run_over = any(
                    err.terminal or err.lease_returned for err in e.errors
                )
                if run_over:
                    msg = "; ".join(
                        f"{err.reason}: {err.message}" for err in e.errors
                    )
                    ops.append(
                        {
                            "kind": "run_state",
                            "run_id": e.run_id,
                            "state": "FAILED",
                            "ts": ts,
                            "error": msg,
                        }
                    )
    return ops
