"""IncrementalBuilder equivalence: the cycle-persistent columnar state must
produce rounds indistinguishable from the from-scratch builder.

The reference keeps jobDb/nodeDb alive between cycles and applies deltas
(scheduler.go:240-246); models/incremental.py is our equivalent.  These tests
pin the contract: for any delta history, `assemble()` and a fresh
`build_problem()` over the same logical state schedule the SAME jobs onto the
SAME nodes, preempt the same runs, and fail the same jobs.
"""

import dataclasses
import random

import numpy as np
import jax.numpy as jnp

from armada_tpu.core.config import PriorityClass, SchedulingConfig
from armada_tpu.core.types import JobSpec, NodeSpec, Queue, RunningJob
from armada_tpu.models import (
    SchedulingProblem,
    build_problem,
    decode_result,
    schedule_round,
)
from armada_tpu.models.incremental import IncrementalBuilder

CFG = SchedulingConfig(
    shape_bucket=32,
    indexed_node_labels=("rack",),
    priority_classes={
        "low": PriorityClass("low", priority=100, preemptible=True),
        "high": PriorityClass("high", priority=1000, preemptible=False),
    },
    default_priority_class="high",
)
F = CFG.resource_list_factory()


def _node(nid, rack="a", cpu="16", pool="default", unschedulable=False):
    return NodeSpec(
        id=nid,
        pool=pool,
        labels={"rack": rack},
        total_resources=F.from_mapping({"cpu": cpu, "memory": "64"}),
        unschedulable=unschedulable,
    )


def _job(jid, queue, cpu, pc="high", prio=0, sub=0.0, **kw):
    return JobSpec(
        id=jid,
        queue=queue,
        priority_class=pc,
        priority=prio,
        submit_time=sub,
        resources=F.from_mapping({"cpu": str(cpu), "memory": "2"}),
        **kw,
    )


def _round(problem, ctx):
    # The production wrapper (gang-txn rollback + running-gang cascade), not
    # a bare schedule_round: equivalence must hold on the path the scheduler
    # actually runs.
    from armada_tpu.models import run_round_on_device

    _, outcome = run_round_on_device(problem, ctx, ctx.config)
    return outcome


def _outcomes_equal(a, b):
    assert a.scheduled == b.scheduled, (
        f"scheduled diverged:\nonly fresh: "
        f"{ {k: v for k, v in a.scheduled.items() if b.scheduled.get(k) != v} }\n"
        f"only incr: "
        f"{ {k: v for k, v in b.scheduled.items() if a.scheduled.get(k) != v} }"
    )
    assert sorted(a.preempted) == sorted(b.preempted)
    assert sorted(a.failed) == sorted(b.failed)
    assert sorted(a.rescheduled) == sorted(b.rescheduled)


def _random_world(seed, num_nodes=12, num_jobs=120, num_running=10, gangs=3):
    rng = random.Random(seed)
    nodes = [
        _node(f"n{i:03d}", rack=rng.choice("ab"), cpu=rng.choice(["8", "16", "32"]))
        for i in range(num_nodes)
    ]
    queues = [Queue("qa", 1.0), Queue("qb", 2.0), Queue("qc", 0.5)]
    jobs = []
    for i in range(num_jobs):
        sel = {"rack": rng.choice("ab")} if rng.random() < 0.3 else {}
        jobs.append(
            _job(
                f"j{i:05d}",
                rng.choice(["qa", "qb", "qc"]),
                rng.choice([1, 2, 4, 8]),
                pc=rng.choice(["low", "high"]),
                prio=rng.randrange(3),
                sub=rng.random(),
                node_selector=sel,
            )
        )
    for g in range(gangs):
        card = rng.choice([2, 3])
        for m in range(card):
            jobs.append(
                _job(
                    f"g{g}m{m}",
                    "qa",
                    2,
                    pc="high",
                    sub=2.0 + g,
                    gang_id=f"gang{g}",
                    gang_cardinality=card,
                    node_selector={"rack": "a"} if m == 0 else {},
                )
            )
    running = []
    for i in range(num_running):
        running.append(
            RunningJob(
                job=_job(
                    f"r{i:03d}",
                    rng.choice(["qa", "qb"]),
                    rng.choice([2, 4]),
                    pc=rng.choice(["low", "high"]),
                    sub=-1.0 - i,
                ),
                node_id=f"n{rng.randrange(num_nodes):03d}",
            )
        )
    return nodes, queues, jobs, running


def _fresh(nodes, queues, jobs, running, banned=None):
    return build_problem(
        CFG,
        pool="default",
        nodes=nodes,
        queues=queues,
        queued_jobs=jobs,
        running=running,
        banned_nodes=banned,
    )


def _incremental(nodes, queues, jobs, running, banned=None):
    b = IncrementalBuilder(CFG, "default", queues)
    b.set_nodes(nodes)
    b.submit_many(jobs, banned)
    for r in running:
        b.lease(r)
        if r.job.gang_id:
            b.note_running_gang(r.job.queue, r.job.gang_id, r.job.id)
    return b


def test_equivalence_single_shot():
    for seed in range(3):
        nodes, queues, jobs, running = _random_world(seed)
        fresh = _round(*_fresh(nodes, queues, jobs, running))
        incr = _round(*_incremental(nodes, queues, jobs, running).assemble())
        _outcomes_equal(fresh, incr)


def test_equivalence_across_delta_cycles():
    """Five cycles of submits/removals/leases: the persistent builder must
    track the same logical state as a from-scratch rebuild every cycle."""
    rng = random.Random(42)
    nodes, queues, jobs, running = _random_world(7, num_jobs=80)
    builder = _incremental(nodes, queues, jobs, running)
    jobs_by_id = {j.id: j for j in jobs}
    running = list(running)
    next_id = [0]

    for cycle in range(5):
        fresh = _round(*_fresh(nodes, queues, list(jobs_by_id.values()), running))
        incr = _round(*builder.assemble())
        _outcomes_equal(fresh, incr)

        # lease this cycle's scheduled jobs (both views)
        for jid, nid in incr.scheduled.items():
            spec = jobs_by_id.pop(jid, None)
            if spec is None:
                continue
            builder.remove(jid)
            r = RunningJob(job=spec, node_id=nid)
            running.append(r)
            builder.lease(r)
            if spec.gang_id:
                builder.note_running_gang(spec.queue, spec.gang_id, spec.id)
        # preemptions leave the cluster
        for jid in incr.preempted:
            running = [r for r in running if r.job.id != jid]
            builder.unlease(jid)
        # random terminations
        for _ in range(2):
            if running:
                r = running.pop(rng.randrange(len(running)))
                builder.unlease(r.job.id)
        # random cancels
        for _ in range(3):
            if jobs_by_id:
                jid = rng.choice(sorted(jobs_by_id))
                jobs_by_id.pop(jid)
                builder.remove(jid)
        # new submits (later submit times, mixed shapes)
        for _ in range(12):
            i = next_id[0]
            next_id[0] += 1
            sel = {"rack": rng.choice("ab")} if rng.random() < 0.3 else {}
            spec = _job(
                f"new{i:04d}",
                rng.choice(["qa", "qb", "qc"]),
                rng.choice([1, 2, 4]),
                pc=rng.choice(["low", "high"]),
                prio=rng.randrange(3),
                sub=10.0 + cycle + rng.random(),
                node_selector=sel,
            )
            jobs_by_id[spec.id] = spec
            builder.submit(spec)
        # a reprioritisation
        if jobs_by_id:
            jid = rng.choice(sorted(jobs_by_id))
            spec = dataclasses.replace(jobs_by_id[jid], priority=rng.randrange(5))
            jobs_by_id[jid] = spec
            builder.reprioritise(spec)


def test_equivalence_with_banned_nodes():
    nodes, queues, jobs, running = _random_world(3, num_jobs=40, gangs=0)
    banned = {jobs[0].id: (nodes[0].id, nodes[1].id), jobs[5].id: (nodes[2].id,)}
    fresh = _round(*_fresh(nodes, queues, jobs, running, banned))
    incr = _round(*_incremental(nodes, queues, jobs, running, banned).assemble())
    _outcomes_equal(fresh, incr)


def test_equivalence_lookback_cap():
    cfg = dataclasses.replace(CFG, max_queue_lookback=10)
    nodes, queues, jobs, running = _random_world(5, num_jobs=60, gangs=2)
    fresh_p, fresh_ctx = build_problem(
        cfg, pool="default", nodes=nodes, queues=queues,
        queued_jobs=jobs, running=running,
    )
    b = IncrementalBuilder(cfg, "default", queues)
    b.set_nodes(nodes)
    b.submit_many(jobs)
    for r in running:
        b.lease(r)
    incr_p, incr_ctx = b.assemble()
    _outcomes_equal(_round(fresh_p, fresh_ctx), _round(incr_p, incr_ctx))


def test_node_churn_and_unschedulable():
    nodes, queues, jobs, running = _random_world(9, num_jobs=30, gangs=0)
    b = _incremental(nodes, queues, jobs, running)
    # cordon two nodes, add one, drop one
    nodes2 = [
        dataclasses.replace(n, unschedulable=True) if i < 2 else n
        for i, n in enumerate(nodes)
    ]
    dropped = nodes2.pop()
    nodes2.append(_node("n-new", rack="b", cpu="32"))
    b.set_nodes(nodes2)
    running2 = [r for r in running if r.node_id != dropped.id]
    for r in running:
        if r.node_id == dropped.id:
            b.unlease(r.job.id)
    fresh = _round(*_fresh(nodes2, queues, jobs, running2))
    incr = _round(*b.assemble())
    _outcomes_equal(fresh, incr)


def test_removed_nodes_leave_totals_and_runs():
    """Scale-down regression (round-3 advisor, high): a removed node must
    vanish from pool totals / round caps / fair-share scale, and its runs
    must drop out of the problem WITHOUT an explicit unlease -- exactly what
    build_problem does by never seeing the node (problem.py run_list
    filter)."""
    nodes, queues, jobs, running = _random_world(11, num_jobs=40, gangs=2)
    b = _incremental(nodes, queues, jobs, running)
    b.assemble()  # populate the node-tensor cache at full fleet size
    dropped = nodes[-1]
    nodes2 = nodes[:-1]
    b.set_nodes(nodes2)
    running2 = [r for r in running if r.node_id != dropped.id]
    fresh_p, fresh_ctx = _fresh(nodes2, queues, jobs, running2)
    incr_p, incr_ctx = b.assemble()
    np.testing.assert_allclose(
        np.asarray(incr_p.total_pool), np.asarray(fresh_p.total_pool)
    )
    np.testing.assert_allclose(
        np.asarray(incr_p.round_cap), np.asarray(fresh_p.round_cap)
    )
    _outcomes_equal(_round(fresh_p, fresh_ctx), _round(incr_p, incr_ctx))
    # the node comes back: totals recover and its still-leased runs (never
    # unleased -- the tombstone retained their rows) count again
    b.set_nodes(nodes)
    running3 = running2 + [r for r in running if r.node_id == dropped.id]
    fresh_p3, fresh_ctx3 = _fresh(nodes, queues, jobs, running3)
    incr_p3, incr_ctx3 = b.assemble()
    np.testing.assert_allclose(
        np.asarray(incr_p3.total_pool), np.asarray(fresh_p3.total_pool)
    )
    _outcomes_equal(_round(fresh_p3, fresh_ctx3), _round(incr_p3, incr_ctx3))


def test_removed_node_does_not_pin_uniformity_domain():
    """A gang sibling stranded on a REMOVED node must not pin the uniformity
    domain: build_problem drops that run before computing pinned_values, so
    the re-queued members are free to land in any (live) domain."""
    nodes = [_node(f"n{i}", rack=("a" if i < 2 else "b")) for i in range(4)]
    queues = [Queue("qa", 1.0)]
    sib = _job(
        "sib", "qa", 4, sub=0.0, gang_id="g1", gang_cardinality=2,
        gang_node_uniformity_label="rack",
    )
    mate = _job(
        "mate", "qa", 4, sub=0.1, gang_id="g1", gang_cardinality=2,
        gang_node_uniformity_label="rack",
    )
    b = IncrementalBuilder(CFG, "default", queues)
    b.set_nodes(nodes)
    b.lease(RunningJob(job=sib, node_id="n0"))  # rack a
    b.note_running_gang("qa", "g1", "sib")
    b.submit(mate)
    # rack-a nodes vanish: only rack b remains
    b.set_nodes(nodes[2:])
    fresh_p, fresh_ctx = _fresh(nodes[2:], queues, [mate], [])
    incr_p, incr_ctx = b.assemble()
    fresh = _round(fresh_p, fresh_ctx)
    incr = _round(incr_p, incr_ctx)
    _outcomes_equal(fresh, incr)
    assert "mate" in incr.scheduled  # not banned off every live node


def test_sorted_table_invariant():
    """Random inserts/removes keep the (qi, npc, prio, sub, id) order."""
    from armada_tpu.models.incremental import _SortedTable

    rng = random.Random(0)
    t = _SortedTable(2, {"level": np.int32}, cap=4)
    live = {}
    for step in range(60):
        if rng.random() < 0.65 or not live:
            batch = []
            reqs = []
            for _ in range(rng.randrange(1, 5)):
                jid = f"job{rng.randrange(1000):04d}".encode()
                if jid in t:
                    continue
                row = {
                    "ids": jid,
                    "qi": rng.randrange(3),
                    "npc": -rng.choice([100, 1000]),
                    "prio": rng.randrange(3),
                    "sub": rng.random(),
                    "level": 2,
                }
                batch.append(row)
                reqs.append(np.ones(2, np.float32))
                live[jid] = row
            # drop duplicate ids within batch
            seen = set()
            uniq = [
                (r, q) for r, q in zip(batch, reqs)
                if not (r["ids"] in seen or seen.add(r["ids"]))
            ]
            t.insert_batch([r for r, _ in uniq], [q for _, q in uniq])
        else:
            jid = rng.choice(sorted(live))
            t.remove(jid)
            live.pop(jid)
        rows = t.live_rows()
        keys = [
            (int(t.qi[r]), int(t.npc[r]), int(t.prio[r]), float(t.sub[r]), t.ids[r])
            for r in rows
        ]
        assert keys == sorted(keys), f"sort invariant broken at step {step}"
        assert {t.ids[r].tobytes().rstrip(b'\0') for r in rows} == set(live)


def test_txn_abort_resyncs_the_feed(tmp_path):
    """An aborted txn (publish failure / fencing loss) must not leave the
    cycle-persistent builders ahead of the JobDb: the feed resyncs from
    committed state (CLAUDE.md: state only advances with a committed txn)."""
    from armada_tpu.jobdb.job import Job, JobRun
    from armada_tpu.jobdb.jobdb import JobDb
    from armada_tpu.scheduler.incremental_algo import IncrementalProblemFeed

    jobdb = JobDb(CFG)
    feed = IncrementalProblemFeed(CFG)
    feed.attach(jobdb)
    b = feed.builder_for("default")
    b.set_queues([Queue("qa")])
    b.set_nodes([_node("n0")])

    with jobdb.write_txn() as txn:
        txn.upsert(Job(spec=_job("j1", "qa", 2), validated=True))
        txn.upsert(Job(spec=_job("j2", "qa", 2), validated=True))
    assert len(b.jobs.key_of_id) == 2

    # an aborted overlay: j1 leased + j3 submitted, then the txn dies
    txn = jobdb.write_txn()
    j1 = txn.get("j1")
    txn.upsert(
        dataclasses.replace(
            j1,
            queued=False,
            runs=(JobRun(id="r1", job_id="j1", created_ns=1, node_id="n0",
                         pool="default"),),
        )
    )
    txn.upsert(Job(spec=_job("j3", "qa", 2), validated=True))
    feed.overlay(txn._upserts, txn._deletes)  # what schedule() does
    assert len(b.jobs.key_of_id) == 2  # j1 out, j3 in
    txn.abort()

    # after the abort the builders reflect committed state again
    b = feed.builder_for("default")
    b.set_queues([Queue("qa")])
    b.set_nodes([_node("n0")])
    assert sorted(k.decode() for k in b.jobs.key_of_id) == ["j1", "j2"]
    assert len(b.runs.key_of_id) == 0


# --------------------------------------------------------------- market ----
# Market pools (market_iterator.go:245): candidates order by
# (-bid_price, submit_time, id); prices are a function of (queue, band) and
# move between cycles.  The incremental tables store (queue, band, submit,
# id) order and permute band slices by current price at assemble time
# (models/incremental._market_perm) -- these tests pin exact equivalence
# with the from-scratch market builder.

from armada_tpu.core.config import PoolConfig

MCFG = dataclasses.replace(
    CFG, pools=(PoolConfig("default", market_driven=True, spot_price_cutoff=0.5),)
)

_BANDS = ("", "low", "mid", "high")


def _pricer(prices):
    """bid_price_of keyed strictly on (queue, band) -- the only shape the
    band table can represent (pkg/bidstore prices per band)."""

    def price(job):
        return prices.get((job.queue, job.price_band), 0.0)

    return price


def _market_world(seed, **kw):
    rng = random.Random(seed * 977)
    nodes, queues, jobs, running = _random_world(seed, **kw)
    jobs = [
        dataclasses.replace(j, price_band=rng.choice(_BANDS)) for j in jobs
    ]
    running = [
        dataclasses.replace(
            r, job=dataclasses.replace(r.job, price_band=rng.choice(_BANDS))
        )
        for r in running
    ]
    prices = {
        (q.name, b): float(rng.randrange(1, 8)) for q in queues for b in _BANDS
    }
    return nodes, queues, jobs, running, prices


def _market_fresh(nodes, queues, jobs, running, price_of, banned=None):
    return build_problem(
        MCFG,
        pool="default",
        nodes=nodes,
        queues=queues,
        queued_jobs=jobs,
        running=running,
        banned_nodes=banned,
        bid_price_of=price_of,
    )


def _market_incr(nodes, queues, jobs, running, price_of, banned=None):
    b = IncrementalBuilder(MCFG, "default", queues, bid_price_of=price_of)
    b.set_nodes(nodes)
    b.submit_many(jobs, banned)
    for r in running:
        b.lease(r)
        if r.job.gang_id:
            b.note_running_gang(r.job.queue, r.job.gang_id, r.job.id)
    return b


def test_market_equivalence_single_shot():
    for seed in range(4):
        nodes, queues, jobs, running, prices = _market_world(seed)
        price_of = _pricer(prices)
        fresh = _round(*_market_fresh(nodes, queues, jobs, running, price_of))
        incr = _round(
            *_market_incr(nodes, queues, jobs, running, price_of).assemble()
        )
        _outcomes_equal(fresh, incr)
        assert fresh.spot_price == incr.spot_price


def test_market_equivalence_with_banned_and_gangs():
    nodes, queues, jobs, running, prices = _market_world(21, num_jobs=60, gangs=4)
    banned = {jobs[3].id: (nodes[0].id,), jobs[9].id: (nodes[1].id, nodes[2].id)}
    price_of = _pricer(prices)
    fresh = _round(
        *_market_fresh(nodes, queues, jobs, running, price_of, banned)
    )
    incr = _round(
        *_market_incr(nodes, queues, jobs, running, price_of, banned).assemble()
    )
    _outcomes_equal(fresh, incr)


def test_market_price_moves_between_cycles():
    """Prices move every cycle; the stored order never changes, only the
    per-cycle slice permutation.  Equivalence must hold at every move,
    including exact (sub, id) merges when bands tie on price."""
    rng = random.Random(31)
    nodes, queues, jobs, running, prices = _market_world(5, num_jobs=90, gangs=2)
    jobs_by_id = {j.id: j for j in jobs}
    running = list(running)
    prices = dict(prices)
    price_of = _pricer(prices)  # reads `prices` live
    builder = _market_incr(nodes, queues, jobs, running, price_of)
    next_id = [0]

    for cycle in range(5):
        fresh = _round(
            *_market_fresh(
                nodes, queues, list(jobs_by_id.values()), running, price_of
            )
        )
        incr = _round(*builder.assemble())
        _outcomes_equal(fresh, incr)

        for jid, nid in incr.scheduled.items():
            spec = jobs_by_id.pop(jid, None)
            if spec is None:
                continue
            builder.remove(jid)
            r = RunningJob(job=spec, node_id=nid)
            running.append(r)
            builder.lease(r)
            if spec.gang_id:
                builder.note_running_gang(spec.queue, spec.gang_id, spec.id)
        for jid in incr.preempted:
            running = [r for r in running if r.job.id != jid]
            builder.unlease(jid)
        for _ in range(8):
            i = next_id[0]
            next_id[0] += 1
            spec = _job(
                f"mkt{i:04d}",
                rng.choice(["qa", "qb", "qc"]),
                rng.choice([1, 2, 4]),
                pc=rng.choice(["low", "high"]),
                sub=20.0 + cycle + rng.random(),
                price_band=rng.choice(_BANDS),
            )
            jobs_by_id[spec.id] = spec
            builder.submit(spec)
        # move prices -- every third cycle force a two-band TIE in one queue
        # so the exact (sub, id) merge path is exercised
        for key in prices:
            prices[key] = float(rng.randrange(1, 8))
        if cycle % 3 == 1:
            prices[("qa", "low")] = prices[("qa", "high")] = 5.0


def test_market_tie_merge_is_exact():
    """Two bands at the same price interleave by (submit_time, id) exactly
    as the reference comparator orders them."""
    nodes = [_node("n0", cpu="4")]
    queues = [Queue("qa", 1.0)]
    jobs = []
    for i, (band, sub) in enumerate(
        [("low", 1.0), ("high", 2.0), ("low", 3.0), ("high", 4.0)]
    ):
        jobs.append(_job(f"t{i}", "qa", 1, sub=sub, price_band=band))
    prices = {("qa", "low"): 5.0, ("qa", "high"): 5.0, ("qa", ""): 0.0}
    price_of = _pricer(prices)
    fresh = _round(*_market_fresh(nodes, queues, jobs, [], price_of))
    incr = _round(*_market_incr(nodes, queues, jobs, [], price_of).assemble())
    _outcomes_equal(fresh, incr)
    assert len(incr.scheduled) == 4


def test_market_non_f32_exact_price_ranks_units_correctly():
    """Regression (round-3 review): the unit rank probe must round to f32
    before comparing with the f32 price table, else a price like 4.7 never
    equals its own band's entry and the unit jumps the whole band."""
    nodes = [_node("n0", cpu="4")]
    queues = [Queue("qa", 1.0)]
    jobs = [_job(f"j{i}", "qa", 1, sub=float(i), price_band="low") for i in range(4)]
    late_banned = _job("zz-late", "qa", 1, sub=10.0, price_band="low")
    prices = {("qa", "low"): 4.7, ("qa", ""): 0.0}  # 4.7 is not f32-exact
    price_of = _pricer(prices)
    banned = {"zz-late": ("n-nonexistent",)}
    fresh = _round(
        *_market_fresh(nodes, queues, jobs + [late_banned], [], price_of, banned)
    )
    incr = _round(
        *_market_incr(
            nodes, queues, jobs + [late_banned], [], price_of, banned
        ).assemble()
    )
    _outcomes_equal(fresh, incr)
    assert sorted(incr.scheduled) == ["j0", "j1", "j2", "j3"]


def test_market_f32_colliding_prices_order_identically():
    """Two bands whose prices differ in f64 but collide in f32 must order
    the same on both paths: prices are f32-canonical everywhere they order
    candidates (the kernel's g_price is f32; build_problem and the
    incremental table both round before comparing)."""
    nodes = [_node("n0", cpu="3")]
    queues = [Queue("qa", 1.0)]
    jobs = []
    for i, (band, sub) in enumerate(
        [("low", 1.0), ("high", 2.0), ("low", 3.0), ("high", 4.0), ("low", 5.0)]
    ):
        jobs.append(_job(f"c{i}", "qa", 1, sub=sub, price_band=band))
    # f64-distinct, f32-equal: both round to np.float32(1.0000000001) == 1.0
    prices = {("qa", "low"): 1.0000000001, ("qa", "high"): 1.0, ("qa", ""): 0.0}
    price_of = _pricer(prices)
    fresh = _round(*_market_fresh(nodes, queues, jobs, [], price_of))
    incr = _round(*_market_incr(nodes, queues, jobs, [], price_of).assemble())
    _outcomes_equal(fresh, incr)
    # the f32 tie means (sub, id) interleave: earliest submits win the node
    assert sorted(fresh.scheduled) == ["c0", "c1", "c2"]


def test_running_gang_spec_refreshes_on_reprioritise():
    """ADVICE r3: running_gang_specs stores the spec captured at lease time;
    a reprioritisation of a RUNNING market gang member must not leave the
    columnar mega round reading a stale priority.  The feed's delta flow
    already guarantees this (apply_job re-leases the run with the job's
    CURRENT priority, and lease_many refreshes the stored spec) -- this test
    pins that path so a future lease_many/apply_job refactor cannot lose it."""
    from armada_tpu.jobdb.job import Job, JobRun
    from armada_tpu.jobdb.jobdb import JobDb
    from armada_tpu.scheduler.incremental_algo import IncrementalProblemFeed

    jobdb = JobDb(MCFG)
    feed = IncrementalProblemFeed(MCFG)
    feed.attach(jobdb)
    b = feed.builder_for("default")
    b.set_queues([Queue("qa")])
    b.set_nodes([_node("n0")])

    spec = _job("jg", "qa", 2, prio=1, gang_id="gang0", gang_cardinality=1,
                price_band="low")
    with jobdb.write_txn() as txn:
        txn.upsert(
            Job(
                spec=spec,
                validated=True,
                queued=False,
                runs=(JobRun(id="r1", job_id="jg", created_ns=1, node_id="n0",
                             pool="default"),),
            )
        )
    assert b.running_gang_specs["jg"].priority == 1

    with jobdb.write_txn() as txn:
        txn.upsert(dataclasses.replace(txn.get("jg"), priority=7))
    assert b.running_gang_specs["jg"].priority == 7


def test_running_gang_partial_preemption_cascades_both_modes():
    """Running-gang fate-sharing (preempting_queue_scheduler.go:345-399 +
    setEvictedGangCardinality; golden trace 'Preempted Gang Job'): a round
    that preempts SOME members of a running gang preempts them ALL -- on the
    from-scratch path and the incremental path alike."""
    nodes = [_node("n0", cpu="4"), _node("n1", cpu="4")]
    queues = [Queue("qa", 1.0), Queue("qb", 1.0)]
    gang_running = [
        RunningJob(
            job=_job(f"gm{i}", "qa", 4, pc="low", sub=-1.0,
                     gang_id="g1", gang_cardinality=2),
            node_id=f"n{i}",
        )
        for i in range(2)
    ]
    # one high-priority job urgency-preempts ONE node's worth
    intruder = [_job("hi1", "qb", 4, pc="high", sub=0.0)]

    fresh = _round(*_fresh(nodes, queues, intruder, gang_running))
    incr = _round(
        *_incremental(nodes, queues, intruder, gang_running).assemble()
    )
    _outcomes_equal(fresh, incr)
    assert sorted(fresh.preempted) == ["gm0", "gm1"], (
        f"partial preemption must cascade to the whole running gang; "
        f"got {sorted(fresh.preempted)}"
    )
    assert "hi1" in fresh.scheduled

    # control: WITHOUT gang identity only one run is preempted
    solo_running = [
        RunningJob(job=_job(f"s{i}", "qa", 4, pc="low", sub=-1.0),
                   node_id=f"n{i}")
        for i in range(2)
    ]
    fresh2 = _round(*_fresh(nodes, queues, intruder, solo_running))
    incr2 = _round(
        *_incremental(nodes, queues, intruder, solo_running).assemble()
    )
    _outcomes_equal(fresh2, incr2)
    assert len(fresh2.preempted) == 1


def test_leases_before_nodes_or_queues_are_buffered():
    """State can arrive runs-first (restart replay; a sidecar session
    syncing before its first round): leases naming nodes/queues the builder
    has not seen must be BUFFERED, not dropped -- a silent drop makes every
    running job invisible to fairness and preemption (round-5 sidecar
    equality failure)."""
    nodes, queues, jobs, running = _random_world(4)
    reference = _round(*_incremental(nodes, queues, jobs, running).assemble())

    # runs first, into a builder that knows neither queues nor nodes yet
    b = IncrementalBuilder(CFG, "default")
    for r in running:
        b.lease(r)
        if r.job.gang_id:
            b.note_running_gang(r.job.queue, r.job.gang_id, r.job.id)
    b.submit_many(jobs)
    b.set_queues(queues)
    b.set_nodes(nodes)
    late = _round(*b.assemble())
    _outcomes_equal(reference, late)
    assert len(b.runs.key_of_id) == len(running)
    assert not b._pending_runs

    # an unlease while still pending must discard the buffered entry
    b2 = IncrementalBuilder(CFG, "default")
    b2.lease(running[0])
    b2.unlease(running[0].job.id)
    b2.set_queues(queues)
    b2.set_nodes(nodes)
    assert len(b2.runs.key_of_id) == 0 and not b2._pending_runs


def test_remove_many_equals_sequential_removes():
    """remove_many is the cycle's decision-feedback hot path (bench + the
    feed's flush): it must be exactly remove() per id -- table rows, demand
    accounting, slab validity, gang side-tables and subsequent outcomes."""
    nodes, queues, jobs, running = _random_world(12, num_jobs=300)
    a = _incremental(nodes, queues, jobs, running)
    b = _incremental(nodes, queues, jobs, running)
    victims = [j.id for j in jobs[::3]] + ["absent-id"]
    for jid in victims:
        a.remove(jid)
    b.remove_many(victims)
    assert a.jobs.key_of_id.keys() == b.jobs.key_of_id.keys()
    assert np.array_equal(a._demand_sg, b._demand_sg)
    assert np.array_equal(a._sg.valid, b._sg.valid)
    assert set(a.gang_jobs) == set(b.gang_jobs)
    pa, _ = a.assemble()
    pb, _ = b.assemble()
    for f in pa._fields:
        assert np.array_equal(np.asarray(getattr(pa, f)),
                              np.asarray(getattr(pb, f))), f
