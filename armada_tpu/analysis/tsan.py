"""Dynamic race harness: lock-order + generation discipline, ARMADA_TSAN=1.

PR 3's watchdog/failover made the scheduler genuinely multi-threaded: an
abandoned (zombie) device worker can unwedge at any time and race the
failover thread over shadow thunks, device caches, and builder prefetch
state.  The hand-fixed races there (the `_ShadowOnce` cursor, the
generation-guarded `prefetch_content`, devcache replacement in reset hooks)
are exactly the class this harness detects mechanically -- the Python
analog of running the reference's Go tests under `-race`.

Two detectors, both recording (never altering behaviour):

* **Lock-order inversions.**  :func:`make_lock` returns an instrumented
  ``threading.Lock`` wrapper.  Every acquisition records edges
  ``held -> acquired`` in a process-global order graph; observing both
  ``A -> B`` and ``B -> A`` is a potential deadlock (two threads
  interleaving those orders wedge forever -- and a wedged scheduler thread
  is indistinguishable from the tunnel hang the watchdog exists for).
  When disarmed the wrapper costs one attribute check per acquire.

* **Generation-stale writes.**  :class:`GenerationGuard` (and the
  free-function :func:`check_generation`) assert that a mutation of
  device-resident state commits under the same watchdog generation it
  began under.  ``DeviceDeltaCache.reset()`` and
  ``IncrementalBuilder.invalidate_prefetch()`` bump generations; a zombie
  worker completing a scatter AFTER the reset is recorded as a violation.
  In correct code the production guards (sig/seq checks, ``_prefetch_gen``)
  make these checks unreachable -- the harness exists so REMOVING one of
  those guards turns the pipeline/faults equality suites red under
  ``ARMADA_TSAN=1`` instead of surfacing as a once-a-month zombie race.

Violations accumulate in a process-global list; the test conftest fails any
test that ends with a non-empty list when the harness is armed.  Arming:
``ARMADA_TSAN=1`` in the environment at process start, or
:func:`enable`/:func:`disable` at runtime (tests, chaos drills).
"""

from __future__ import annotations

import os
import sys
import threading
from typing import Optional

# Armed state: a plain module global read on every acquire.  enable() /
# disable() flip it at runtime; the env var arms it at import (serve,
# pytest-under-ARMADA_TSAN, chaos drills).
_enabled: bool = os.environ.get("ARMADA_TSAN") == "1"

# The harness's own bookkeeping lock is a RAW threading.Lock: it must never
# appear in the order graph it maintains.
_state_lock = threading.Lock()
_held = threading.local()  # per-thread acquisition stack of lock names
_edges: dict = {}  # (first, second) -> "thread/site" where first observed
_violations: list = []


def enabled() -> bool:
    return _enabled


def enable() -> None:
    global _enabled
    _enabled = True


def disable() -> None:
    global _enabled
    _enabled = False


def reset() -> None:
    """Forget recorded edges and violations (per-test isolation).  Held-lock
    stacks are per-thread and self-correct as locks release."""
    with _state_lock:
        _edges.clear()
        _violations.clear()


def violations() -> list:
    with _state_lock:
        return list(_violations)


def take_violations() -> list:
    """Snapshot AND clear -- the conftest teardown consumes them so one
    test's violation never bleeds into the next."""
    with _state_lock:
        out = list(_violations)
        _violations.clear()
        return out


def _record(msg: str) -> None:
    with _state_lock:
        _violations.append(msg)


# --------------------------------------------------------------------------
# lock-order inversion detection
# --------------------------------------------------------------------------

def _stack() -> list:
    st = getattr(_held, "names", None)
    if st is None:
        st = _held.names = []
    return st


def _on_acquire(name: str, oid: int) -> None:
    st = _stack()
    if st:
        tname = threading.current_thread().name
        with _state_lock:
            for h, hid in st:
                if hid == oid:
                    # re-acquiring the very lock we hold: the non-reentrant
                    # wrapped Lock is already deadlocked; nothing to record
                    # that the hang itself won't say louder.
                    continue
                if h == name:
                    # Two DIFFERENT locks sharing a name (instance locks of
                    # one class): without an instance order there is no
                    # consistent global order to check, and nesting them is
                    # the same hazard lockdep flags for same-class locks.
                    _violations.append(
                        f"same-class lock nesting: two locks named {name!r} "
                        f"held together (thread {tname}); give instance "
                        "locks distinct names (make_lock with an instance "
                        "discriminator) or establish an instance order"
                    )
                    continue
                if (name, h) in _edges:
                    msg = (
                        f"lock-order inversion: {h!r} held while acquiring "
                        f"{name!r} (thread {tname}), but the reverse order "
                        f"was observed at {_edges[(name, h)]} -- two threads "
                        "interleaving these orders deadlock"
                    )
                    _violations.append(msg)
                _edges.setdefault((h, name), tname)
    st.append((name, oid))


def _on_release(name: str, oid: int) -> None:
    st = _stack()
    # release order need not be LIFO (lock A, lock B, release A): drop the
    # most recent occurrence.
    for i in range(len(st) - 1, -1, -1):
        if st[i][1] == oid:
            del st[i]
            break


class TsanLock:
    """threading.Lock wrapper feeding the order graph when armed.

    API-compatible with threading.Lock for this repo's usage (acquire/
    release/locked/context manager).  The wrapped lock is real -- the
    harness observes, it does not serialize."""

    __slots__ = ("_lock", "name")

    def __init__(self, name: str):
        self._lock = threading.Lock()
        self.name = name

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        ok = self._lock.acquire(blocking, timeout)
        if ok and _enabled:
            _on_acquire(self.name, id(self))
        return ok

    def release(self) -> None:
        if _enabled:
            _on_release(self.name, id(self))
        self._lock.release()

    def locked(self) -> bool:
        return self._lock.locked()

    def __enter__(self) -> bool:
        return self.acquire()

    def __exit__(self, *exc) -> None:
        self.release()

    def __repr__(self) -> str:
        return f"<TsanLock {self.name!r} {'locked' if self.locked() else 'unlocked'}>"


def make_lock(name: Optional[str] = None) -> TsanLock:
    """An instrumented lock.  `name` identifies it in the order graph;
    default is the creation site (file:line), which is stable enough for
    module-level locks but give instance locks an explicit name."""
    if name is None:
        f = sys._getframe(1)
        name = f"{os.path.basename(f.f_code.co_filename)}:{f.f_lineno}"
    return TsanLock(name)


# --------------------------------------------------------------------------
# generation-stale write detection
# --------------------------------------------------------------------------

def check_generation(what: str, began: int, current: int) -> bool:
    """Record a violation if a mutation that began at generation `began` is
    committing while the state sits at `current` (a reset/invalidation ran
    in between -- the zombie-worker write PR 3 fixed by hand).  Returns
    True when clean; never raises, never blocks the mutation (production
    guards own behaviour, the harness owns visibility)."""
    if _enabled and began != current:
        _record(
            f"generation-stale write: {what} began at generation {began} "
            f"but the state was reset to generation {current} mid-flight "
            "(zombie worker scribbling on reset state)"
        )
        return False
    return True


class GenerationGuard:
    """Ownership epoch for one device-resident cache object.

    `begin()` captures the epoch before a mutation; `commit(token, action)`
    verifies it right before the mutation lands; `bump()` marks a reset /
    invalidation boundary (watchdog reset hooks, devcache.reset)."""

    __slots__ = ("what", "_gen")

    def __init__(self, what: str):
        self.what = what
        self._gen = 0

    @property
    def generation(self) -> int:
        return self._gen

    def bump(self) -> None:
        self._gen += 1

    def begin(self) -> int:
        return self._gen

    def commit(self, token: int, action: str = "write") -> bool:
        return check_generation(f"{self.what}.{action}", token, self._gen)
