import numpy as np
import pytest

from armada_tpu.core.resources import (
    ResourceListFactory,
    parse_quantity,
    format_quantity,
)


@pytest.fixture
def factory():
    return ResourceListFactory.from_config(
        [("memory", "1"), ("cpu", "1m"), ("nvidia.com/gpu", "1")]
    )


def test_parse_quantity():
    assert parse_quantity("1") == 1000
    assert parse_quantity("100m") == 100
    assert parse_quantity("1Ki") == 1024 * 1000
    assert parse_quantity("2Gi") == 2 * 2**30 * 1000
    assert parse_quantity(4) == 4000
    assert parse_quantity("1.5") == 1500
    with pytest.raises(ValueError):
        parse_quantity("abc")


def test_format_roundtrip():
    assert format_quantity(parse_quantity("16")) == "16"
    assert format_quantity(parse_quantity("100m")) == "0.1"


def test_arithmetic(factory):
    a = factory.from_mapping({"cpu": "2", "memory": "4Gi"})
    b = factory.from_mapping({"cpu": "500m", "memory": "1Gi"})
    c = a.subtract(b)
    assert c.get("cpu") == parse_quantity("1500m")
    assert a.add(b).get("memory") == parse_quantity("5Gi")
    assert not b.exceeds(a)
    assert a.exceeds(b)
    assert b.fits_within(a)


def test_unknown_resources_dropped(factory):
    rl = factory.from_mapping({"cpu": "1", "fancy-fpga": "3"})
    assert rl.get("cpu") == 1000
    assert "fancy-fpga" not in rl.to_dict()


def test_quantization_floor_ceil(factory):
    # cpu resolution 1m -> atoms per unit 1; memory resolution "1" -> 1000 atoms.
    rl = factory.from_mapping({"cpu": "1500m", "memory": "1.5"})
    floor = factory.floor_units(rl.atoms)
    ceil = factory.ceil_units(rl.atoms)
    mem_i, cpu_i = factory.index_of("memory"), factory.index_of("cpu")
    assert floor[cpu_i] == 1500 and ceil[cpu_i] == 1500
    assert floor[mem_i] == 1 and ceil[mem_i] == 2


def test_multipliers(factory):
    m = factory.multipliers_for({"cpu": 1.0, "nvidia.com/gpu": 2.0})
    assert m[factory.index_of("cpu")] == 1.0
    assert m[factory.index_of("nvidia.com/gpu")] == 2.0
    assert m[factory.index_of("memory")] == 0.0
