"""Host-side domain objects: nodes, jobs, queues, taints/tolerations.

Equivalent surface to the reference's `internaltypes.Node` (internaltypes/node.go),
`jobdb.Job` (jobdb/job.go) scheduling-relevant fields, and `api.Queue`.  These are
plain frozen dataclasses; the scheduler never mutates them -- mirroring the
reference's immutability discipline (jobdb/jobdb.go:67, resource_list.go:23-24).
"""

from __future__ import annotations

import dataclasses
from typing import Mapping, Optional, Sequence

from armada_tpu.core.resources import ResourceList

# Node label the executor reports hardware type under (mirrors the
# armada-tpu.io/pool label idiom) and the submit-side annotation carrying a
# job's per-type throughput map ("v5e=2.0,v4=1"; parse_node_type_scores).
NODE_TYPE_LABEL = "armada-tpu.io/node-type"
NODE_TYPE_SCORES_ANNOTATION = "armada-tpu.io/node-type-scores"


def parse_node_type_scores(text: str) -> tuple[tuple[str, float], ...]:
    """Parse the node-type-scores annotation into the canonical sorted
    ((type, throughput), ...) tuple JobSpec carries.

    Sorted so that equal maps written in different orders produce the SAME
    scheduling key (core/keys.class_signature folds the tuple verbatim).
    Raises ValueError on malformed entries -- submit validation turns that
    into a client-facing rejection.
    """
    text = (text or "").strip()
    if not text:
        return ()
    out: dict[str, float] = {}
    for part in text.split(","):
        part = part.strip()
        if not part:
            continue
        name, sep, val = part.partition("=")
        name = name.strip()
        if not sep or not name:
            raise ValueError(
                f"node-type-scores entry {part!r}: expected <type>=<throughput>"
            )
        try:
            thr = float(val.strip())
        except ValueError:
            raise ValueError(
                f"node-type-scores entry {part!r}: throughput is not a number"
            ) from None
        if thr <= 0:
            raise ValueError(
                f"node-type-scores entry {part!r}: throughput must be > 0"
            )
        if name in out:
            raise ValueError(f"node-type-scores: duplicate type {name!r}")
        out[name] = thr
    return tuple(sorted(out.items()))


@dataclasses.dataclass(frozen=True)
class Taint:
    """Kubernetes node taint (only NoSchedule/NoExecute block scheduling)."""

    key: str
    value: str = ""
    effect: str = "NoSchedule"  # NoSchedule | NoExecute | PreferNoSchedule


@dataclasses.dataclass(frozen=True)
class Toleration:
    key: str = ""
    operator: str = "Equal"  # Equal | Exists
    value: str = ""
    effect: str = ""  # empty tolerates all effects

    def tolerates(self, taint: Taint) -> bool:
        if self.effect and self.effect != taint.effect:
            return False
        if self.operator == "Exists":
            return self.key == "" or self.key == taint.key
        return self.key == taint.key and self.value == taint.value


def taints_tolerated(taints: Sequence[Taint], tolerations: Sequence[Toleration]) -> bool:
    """True if every blocking taint is tolerated (nodematching.go:127-145)."""
    for taint in taints:
        if taint.effect not in ("NoSchedule", "NoExecute"):
            continue
        if not any(t.tolerates(taint) for t in tolerations):
            return False
    return True


def selector_matches(selector: Mapping[str, str], labels: Mapping[str, str]) -> bool:
    """Node-selector match: every selector entry must equal the node label
    (nodematching.go StaticJobRequirementsMet:161-194)."""
    for k, v in selector.items():
        if labels.get(k) != v:
            return False
    return True


@dataclasses.dataclass(frozen=True)
class NodeSpec:
    """A schedulable node (internaltypes/node.go).

    `running` / allocation state lives in the scheduler's state tensors, not here.
    """

    id: str
    pool: str = "default"
    executor: str = ""
    total_resources: Optional[ResourceList] = None
    taints: tuple[Taint, ...] = ()
    labels: Mapping[str, str] = dataclasses.field(default_factory=dict)
    unschedulable: bool = False
    # Hardware type reported by the executor (the NODE_TYPE_LABEL node label,
    # e.g. "v5e" / "v4" / "cpu"); "" = the untyped default, so existing
    # single-type worlds are unchanged.  Folds into core/keys.NodeType so the
    # static fit matrix and the kernel's per-type score tables see it.
    node_type: str = ""


@dataclasses.dataclass(frozen=True)
class ServiceSpec:
    """A per-job Service the executor materialises next to the pod
    (pkg/api/submit.proto ServiceConfig: NodePort | Headless).  `name` ""
    derives one from the job id."""

    type: str = "NodePort"
    ports: tuple[int, ...] = ()
    name: str = ""


@dataclasses.dataclass(frozen=True)
class IngressSpec:
    """A per-job Ingress exposing service ports over the network
    (pkg/api/submit.proto IngressConfig; materialised like
    executor/util/kubernetes_object.go ExtractIngresses)."""

    ports: tuple[int, ...] = ()
    annotations: Mapping[str, str] = dataclasses.field(default_factory=dict)
    tls_enabled: bool = False
    cert_name: str = ""
    use_cluster_ip: bool = False


@dataclasses.dataclass(frozen=True)
class JobSpec:
    """A job as the scheduler sees it (jobdb/job.go scheduling-relevant subset).

    `priority` is the user-settable queue priority (smaller schedules first, like the
    reference's job priority); `priority_class` determines the node-contention
    priority and preemptibility.  Gang semantics via gang_id/gang_cardinality
    annotations (docs/scheduling_and_preempting_jobs.md:101-107).
    """

    id: str
    queue: str
    jobset: str = ""
    priority_class: str = ""
    priority: int = 0
    submit_time: float = 0.0
    resources: Optional[ResourceList] = None
    node_selector: Mapping[str, str] = dataclasses.field(default_factory=dict)
    tolerations: tuple[Toleration, ...] = ()
    gang_id: str = ""
    gang_cardinality: int = 1
    gang_node_uniformity_label: str = ""
    pools: tuple[str, ...] = ()  # pools the job may schedule in; empty = all
    # Price band for market-driven pools (reference: bidstore price bands).
    price_band: str = ""
    # Pod payload passthrough (submit item -> events.proto JobSpec -> the
    # cluster adapter): the scheduler itself never reads these.
    namespace: str = "default"
    annotations: Mapping[str, str] = dataclasses.field(default_factory=dict)
    labels: Mapping[str, str] = dataclasses.field(default_factory=dict)
    # Network objects materialised with the pod (submit.proto ingress:9 /
    # services:10); the scheduler never reads these.
    services: tuple[ServiceSpec, ...] = ()
    ingress: tuple[IngressSpec, ...] = ()
    # Per-node-type effective-throughput map, sorted ((type, throughput), ...)
    # (Gavel, arXiv:2008.09213): a NONEMPTY map restricts the job to the named
    # types (absent/<=0 = infeasible there) and biases placement toward
    # higher-throughput types.  () = type-insensitive (every existing world).
    # Folds into the scheduling key -- see core/keys.SchedulingKey.type_scores.
    node_type_scores: tuple[tuple[str, float], ...] = ()


@dataclasses.dataclass(frozen=True)
class Queue:
    """A queue with a fair-share weight (pkg/api Queue; fairness.go Queue iface)."""

    name: str
    weight: float = 1.0

    def __post_init__(self):
        if self.weight <= 0:
            raise ValueError(f"queue {self.name}: weight must be > 0")


@dataclasses.dataclass(frozen=True)
class RunningJob:
    """A job currently bound to a node, as input to a scheduling round
    (the reference reconstructs this from jobdb runs, scheduling_algo.go:331-465)."""

    job: JobSpec
    node_id: str
    # Priority at which its resources are held (normally its PC priority).
    priority: int = 0
    # Scheduled away from its home pool: held at the lowest priority level and
    # always evictable by home jobs (scheduling_algo.go:216-283).
    away: bool = False
