// Demo CLI for the C++ client -- the smoke-test driver
// (tests/test_cpp_client.py) and a minimal native armadactl:
//
//   armadactl-cpp HOST PORT create-queue NAME WEIGHT
//   armadactl-cpp HOST PORT list-queues
//   armadactl-cpp HOST PORT submit QUEUE JOBSET CPU MEMORY [N]
//   armadactl-cpp HOST PORT cancel QUEUE JOBSET JOB_ID
//   armadactl-cpp HOST PORT events QUEUE JOBSET        (prints one kind/line)
//   armadactl-cpp HOST PORT jobs QUEUE                 (lookout rows JSON)
//   armadactl-cpp HOST PORT describe-job JOB_ID        (details JSON)
//   armadactl-cpp HOST PORT queue-report QUEUE         (scheduling report)
//   armadactl-cpp HOST PORT job-report JOB_ID

#include <cstdio>
#include <cstdlib>
#include <iostream>
#include <string>

#include "armada/client.h"

int main(int argc, char** argv) {
  if (argc < 4) {
    std::fprintf(stderr, "usage: %s HOST PORT VERB ...\n", argv[0]);
    return 2;
  }
  armada::Client client(argv[1], std::atoi(argv[2]));
  client.SetPrincipal("cpp-client");
  const std::string verb = argv[3];
  try {
    if (verb == "create-queue" && argc >= 6) {
      armada_tpu::api::Queue q;
      q.set_name(argv[4]);
      q.set_weight(std::atof(argv[5]));
      client.CreateQueue(q);
      std::printf("created %s\n", q.name().c_str());
    } else if (verb == "list-queues") {
      // bind the response first: ranging over `.queues()` of a temporary is
      // a use-after-scope (the temporary is not lifetime-extended)
      const auto queues = client.ListQueues();
      for (const auto& q : queues.queues()) {
        std::printf("%s weight=%g\n", q.name().c_str(), q.weight());
      }
    } else if (verb == "submit" && argc >= 8) {
      armada_tpu::api::SubmitJobsRequest req;
      req.set_queue(argv[4]);
      req.set_jobset(argv[5]);
      int n = argc >= 9 ? std::atoi(argv[8]) : 1;
      for (int i = 0; i < n; ++i) {
        auto* item = req.add_items();
        (*item->mutable_resources())["cpu"] = argv[6];
        (*item->mutable_resources())["memory"] = argv[7];
      }
      auto resp = client.SubmitJobs(req);
      for (const auto& id : resp.job_ids()) std::printf("%s\n", id.c_str());
    } else if (verb == "cancel" && argc >= 7) {
      armada_tpu::api::CancelJobsRequest req;
      req.set_queue(argv[4]);
      req.set_jobset(argv[5]);
      req.add_job_ids(argv[6]);
      req.set_reason("cancelled via cpp client");
      client.CancelJobs(req);
      std::printf("cancelled %s\n", argv[6]);
    } else if (verb == "events" && argc >= 6) {
      for (const auto& msg : client.GetJobSetEvents(argv[4], argv[5])) {
        for (const auto& ev : msg.sequence().events()) {
          // the oneof case name doubles as the event kind
          const auto* desc = ev.GetDescriptor()->FindOneofByName("event");
          const auto* field =
              ev.GetReflection()->GetOneofFieldDescriptor(ev, desc);
          std::printf("%lld %s\n", static_cast<long long>(msg.idx()),
                      field ? field->name().c_str() : "?");
        }
      }
    } else if (verb == "jobs" && argc >= 5) {
      // lookout query surface: filter by queue, results as raw JSON
      // (escape the argument -- raw interpolation would let quotes in a
      // queue name malform or alter the query)
      std::string escaped;
      for (char c : std::string(argv[4])) {
        if (c == '"' || c == '\\') escaped += '\\';
        escaped += c;
      }
      std::string q = std::string("{\"filters\":[{\"field\":\"queue\",") +
                      "\"value\":\"" + escaped + "\"}]}";
      std::printf("%s\n", client.GetJobs(q).c_str());
    } else if (verb == "describe-job" && argc >= 5) {
      std::printf("%s\n", client.GetJobDetails(argv[4]).c_str());
    } else if (verb == "queue-report" && argc >= 5) {
      std::printf("%s\n", client.GetQueueReport(argv[4]).c_str());
    } else if (verb == "job-report" && argc >= 5) {
      std::printf("%s\n", client.GetJobReport(argv[4]).c_str());
    } else {
      std::fprintf(stderr, "unknown verb %s\n", verb.c_str());
      return 2;
    }
  } catch (const armada::ClientError& e) {
    std::fprintf(stderr, "error (%d): %s\n", e.status, e.message.c_str());
    return 1;
  }
  return 0;
}
