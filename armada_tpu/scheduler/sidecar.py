"""The scheduling sidecar: the TPU round kernel as a gRPC-callable backend.

Mirrors the reference's SchedulingAlgo boundary (internal/scheduler/
scheduling/scheduling_algo.go:36-41 -- Schedule(ctx, txn) -> SchedulerResult)
so an EXTERNAL control plane (the build plan's "colocate with the reference's
Go scheduler" deployment, SURVEY.md north star) can use this repo's kernel
without adopting its Python control plane:

  caller owns job truth  --SyncState deltas-->  session's JobDb mirror
  caller's cycle         --ScheduleRound----->  FairSchedulingAlgo.schedule
  response               <--leases/preemptions  (caller applies to ITS jobDb)

The session keeps the full incremental machinery server-side (JobDb mirror,
per-pool IncrementalBuilders, device-resident slabs), so a steady-state call
carries O(cycle delta) bytes: the state-transfer economics the reference gets
from Schedule() being an in-process call are preserved across the boundary.

The sidecar's decisions are applied to its own mirror when the round commits,
exactly like the in-process scheduler -- the caller's subsequent SyncState
deltas are idempotent re-assertions (latest state wins), so an accepted lease
round-trips as a no-op and a rejected one (caller failed to publish) is
corrected by the next sync.
"""

from __future__ import annotations

import json
import time
import uuid
from typing import Optional, Sequence

from armada_tpu.core.config import SchedulingConfig
from armada_tpu.core.logging import get_logger
from armada_tpu.core.pipeline import pipeline_enabled, prefetch_worthwhile
from armada_tpu.core.types import Queue
from armada_tpu.events.convert import job_spec_from_proto
from armada_tpu.jobdb.job import Job, JobRun
from armada_tpu.jobdb.jobdb import JobDb
from armada_tpu.scheduler.algo import FairSchedulingAlgo, SchedulerResult
from armada_tpu.scheduler.providers import most_specific_bid
from armada_tpu.scheduler.executors import ExecutorSnapshot

from armada_tpu.analysis.tsan import make_lock

FAILED_SAMPLE_CAP = 1000

_log = get_logger(__name__)


class UnknownSession(KeyError):
    """No session with this id -- maps to gRPC NOT_FOUND.  A dedicated type
    so an incidental KeyError inside a round can never masquerade as a
    missing session (the caller would wrongly rebuild its mirror)."""


class SessionExists(ValueError):
    """Caller-chosen session id already live -- maps to ALREADY_EXISTS.
    Silently replacing the session would discard a mirror another caller
    (or a retried CreateSession) is still feeding."""


class SessionBids:
    """Latest synced market bid prices, (queue, band, pool)-keyed.

    Stands in for the polling BidPriceProvider (scheduler/
    external_providers.py): the CALLER refreshes prices by syncing a new
    table; lookups between syncs serve the cached one, matching the
    reference's bid-price cache semantics (pricing/bid_price.go).
    """

    def __init__(self):
        self._prices: dict[tuple[str, str, str], float] = {}

    def update(self, prices: dict[tuple[str, str, str], float]) -> None:
        self._prices = dict(prices)

    def price(self, queue: str, band: str = "", pool: str = "") -> float:
        return most_specific_bid(self._prices, queue, band, pool)


def _job_from_state(msg, factory) -> Job:
    """JobState wire message -> jobdb Job (the mirror's view of the caller's
    job).  Ban nodes ride as synthetic terminal attempted runs so
    Job.anti_affinity_nodes derives them exactly like a native retry."""
    spec = job_spec_from_proto(
        msg.job_id,
        msg.queue,
        msg.jobset,
        msg.spec,
        factory,
        submit_time=msg.submit_time,
    )
    runs = []
    for node_id in msg.banned_nodes:
        runs.append(
            JobRun(
                id=f"ban/{msg.job_id}/{node_id}",
                job_id=msg.job_id,
                node_id=node_id,
                node_name=node_id,
                failed=True,
                run_attempted=True,
            )
        )
    has_run = bool(msg.run.run_id or msg.run.node_id)
    if has_run:
        r = msg.run
        runs.append(
            JobRun(
                id=r.run_id or uuid.uuid4().hex,
                job_id=msg.job_id,
                executor=r.executor,
                node_id=r.node_id,
                node_name=r.node_name or r.node_id,
                pool=r.pool or "default",
                scheduled_at_priority=(
                    int(r.scheduled_at_priority)
                    if r.has_scheduled_at_priority
                    else None
                ),
                pool_scheduled_away=r.away,
                running=r.running,
                running_ns=int(r.running_ns),
                run_attempted=r.running or bool(r.running_ns),
                # A terminal job's run is over (resources free); the job row
                # is retained only for the short-job penalty window, which
                # exempts preempted runs.
                failed=bool(msg.terminal) and not r.preempted,
                preempted=bool(msg.terminal) and r.preempted,
            )
        )
    return Job(
        spec=spec,
        priority=int(msg.priority),
        queued=bool(msg.queued) and not msg.terminal,
        validated=bool(msg.validated),
        pools=tuple(msg.pools),
        failed=bool(msg.terminal),
        runs=tuple(runs),
    )


class ScheduleSession:
    """One caller's mirrored world + algo; serialized rounds."""

    def __init__(
        self,
        session_id: str,
        config: SchedulingConfig,
        clock_ns=lambda: int(time.time() * 1e9),
    ):
        self.id = session_id
        self.config = config
        self._clock_ns = clock_ns
        # Terminal jobs the caller synced (retained only for the short-job
        # penalty window): job id -> running_ns (or sync time when the run
        # never ran).  Swept at round end so a long-lived session's mirror
        # cannot grow without bound on a caller that deletes lazily -- the
        # in-process scheduler's _retained_terminal sweep equivalent.
        self._terminal_synced: dict[str, int] = {}
        self.factory = config.resource_list_factory()
        self.jobdb = JobDb(config)
        self.queues: list[Queue] = []
        self.executors: list[ExecutorSnapshot] = []
        self.bids = SessionBids()
        self.feed = None
        if config.incremental_problem_build:
            from armada_tpu.scheduler.incremental_algo import (
                IncrementalProblemFeed,
            )

            self.feed = IncrementalProblemFeed(config)
            self.feed.attach(self.jobdb)
        market = any(p.market_driven for p in config.pools)
        self.algo = FairSchedulingAlgo(
            config,
            queues=lambda: self.queues,
            clock_ns=clock_ns,
            collect_stats=False,
            bid_prices=self.bids if market else None,
            feed=self.feed,
        )
        self._lock = make_lock("sidecar.session")

    # ----------------------------------------------------------- syncing ----
    # One SyncState request applies ATOMICALLY with respect to rounds: the
    # session lock is held across all its parts, so a concurrent
    # ScheduleRound can never see (say) this request's jobs against the
    # executor set the same request replaces.

    def apply_sync(
        self,
        jobs: Sequence = (),
        deletes: Sequence[str] = (),
        executors: Optional[Sequence[ExecutorSnapshot]] = None,
        queues: Optional[Sequence[Queue]] = None,
        bids: Optional[dict] = None,
        trace_id: str = "",
    ) -> None:
        from armada_tpu.ops.trace import recorder as trace_recorder

        # The caller's cycle is sync + round: the sync half gets its own
        # ring entry (kind "sync") under the caller's trace id so the two
        # stitch by id in a dump (tools/sidecar_profile.py reads the split
        # from exactly these entries).
        with trace_recorder().cycle(
            "sidecar_sync",
            trace_id=trace_id,
            kind="sync",
            jobs=len(jobs),
            deletes=len(deletes),
        ):
            self._apply_sync_locked(jobs, deletes, executors, queues, bids)

    def _apply_sync_locked(
        self, jobs, deletes, executors, queues, bids
    ) -> None:
        with self._lock:
            if jobs or deletes:
                for m in jobs:
                    if m.terminal:
                        # 0 = never ran: the penalty can't apply
                        # (ShortJobPenalty.applies needs running_ns > 0), so
                        # the sweep drops it at the next round -- and never
                        # mixes the sidecar wall clock with the caller's
                        # logical now_ns.
                        self._terminal_synced[m.job_id] = int(
                            m.run.running_ns
                        )
                    else:
                        self._terminal_synced.pop(m.job_id, None)
                for jid in deletes:
                    self._terminal_synced.pop(jid, None)
                txn = self.jobdb.write_txn()
                if deletes:
                    txn.delete(list(deletes))
                if jobs:
                    txn.upsert(
                        [_job_from_state(m, self.factory) for m in jobs]
                    )
                txn.commit()
                if (
                    self.feed is not None
                    and pipeline_enabled()
                    and prefetch_worthwhile()
                ):
                    # Shadow-pipeline stage (b): the commit just landed these
                    # caller-asserted rows in the builders -- start their
                    # slab upload NOW, so the tunnel transfer overlaps the
                    # rest of the sync and the next round's assemble instead
                    # of serializing inside its device apply.  Best-effort:
                    # the mirror COMMITTED, so a device error here must not
                    # fail the sync (the caller would wrongly retry state
                    # that applied); the rows just ride the next bundle.
                    try:
                        self.feed.prefetch_content()
                    except Exception:
                        _log.warning(
                            "sync content prefetch failed", exc_info=True
                        )
            if executors is not None:
                self.executors = list(executors)
            if queues is not None:
                self.queues = list(queues)
            if bids is not None:
                self.bids.update(bids)

    def sync_jobs(self, jobs: Sequence, deletes: Sequence[str] = ()) -> None:
        self.apply_sync(jobs=jobs, deletes=deletes)

    def set_executors(self, executors: Sequence[ExecutorSnapshot]) -> None:
        self.apply_sync(executors=executors)

    def set_queues(self, queues: Sequence[Queue]) -> None:
        self.apply_sync(queues=queues)

    def set_bids(self, prices: dict) -> None:
        self.apply_sync(bids=prices)

    # ------------------------------------------------------------ rounds ----

    def schedule_round(
        self,
        now_ns: Optional[int] = None,
        quarantined=frozenset(),
        trace_id: str = "",
    ) -> SchedulerResult:
        from armada_tpu.core.watchdog import supervisor
        from armada_tpu.ops.metrics import mono_now
        from armada_tpu.ops.trace import recorder as trace_recorder
        from armada_tpu.scheduler.slo import recorder as slo_recorder

        t_start = mono_now()
        sup0 = supervisor()
        fallbacks0 = sup0.snapshot()["fallbacks"]
        degraded0 = sup0.degraded
        # The round's cycle trace carries the CALLER's trace id when one
        # arrived over the gRPC metadata (rpc/server.py): the caller grafts
        # the returned spans under its RPC span, yielding one stitched
        # cross-process tree (tests/test_trace.py pins it).
        with trace_recorder().cycle(
            "sidecar_round", trace_id=trace_id, kind="round", session=self.id
        ), self._lock:
            txn = self.jobdb.write_txn()
            now = now_ns or self._clock_ns()

            def sweep():
                # Sweep synced terminal jobs once they leave the short-job
                # penalty window (immediately when no penalty is
                # configured): only ids from _terminal_synced, O(tracked),
                # never a backlog scan.  Decision-independent (terminal
                # jobs can neither schedule nor preempt, and builders only
                # see txn deletes at commit), so the pipelined round runs
                # it in the kernel shadow; final mirror state is identical
                # either way (tests/test_pipeline.py).
                window = int(
                    max(
                        self.config.short_job_penalty_cutoffs().values(),
                        default=0.0,
                    )
                    * 1e9
                )
                expired = [
                    jid
                    for jid, ns in self._terminal_synced.items()
                    if ns == 0 or now - ns >= window
                ]
                if expired:
                    txn.delete(expired)
                    for jid in expired:
                        self._terminal_synced.pop(jid, None)

            pipelined = pipeline_enabled()
            result = self.algo.schedule(
                txn,
                self.executors,
                now_ns=now_ns or None,
                quarantined_nodes=frozenset(quarantined),
                shadow_work=[sweep] if pipelined else None,
            )
            if not pipelined:
                sweep()
            # Commit the mirror like the in-process scheduler commits its
            # jobDb: later rounds must see this round's leases.  The caller
            # re-asserting job state via SyncState is idempotent on top.
            txn.commit()
            # Sidecar rounds feed the same streaming cycle-latency SLO as
            # the in-process scheduler (TTFL/ingest-lag stay caller-side:
            # the caller owns submit timing across the boundary).  Degraded
            # = before OR fallback-delta OR after: a drill-speed re-probe
            # can promote back before the failed-over round returns, and a
            # promotion can land mid-round (scheduler.cycle's rule).
            sup = supervisor()
            slo_recorder().observe_cycle(
                mono_now() - t_start,
                degraded=degraded0
                or sup.degraded
                or sup.snapshot()["fallbacks"] > fallbacks0,
            )
            # Per-pool round latency rides the same recorder (round 17):
            # the algo stamps each PoolStats with its round seconds + the
            # per-round fallback-delta degraded flag.
            for ps in result.pools:
                if ps.round_s:
                    slo_recorder().observe_pool_round(
                        ps.pool, ps.round_s, degraded=ps.degraded
                    )
            return result


def _stats_of(result: SchedulerResult, trace: Optional[dict] = None) -> str:
    pools = []
    for s in result.pools:
        entry = {
            "pool": s.pool,
            "num_nodes": s.num_nodes,
            "num_queued": s.num_queued,
            "num_running": s.num_running,
            "scheduled": len(s.outcome.scheduled),
            "preempted": len(s.outcome.preempted),
            "termination": s.outcome.termination,
            "iterations": s.outcome.num_iterations,
            # physical while-loop trips under the multi-commit kernel
            # (ARMADA_COMMIT_K); == iterations at K=1
            "kernel_iters": getattr(s.outcome, "kernel_iters", 0),
            "queue_stats": s.outcome.queue_stats,
        }
        if s.market:
            entry["indicative_prices"] = s.indicative_prices
            entry["idealised_values"] = s.idealised_values
            entry["realised_values"] = s.realised_values
        pools.append(entry)
    # Degradation state rides the stats JSON so an EXTERNAL control plane
    # (the sidecar's whole audience) sees a CPU-failover round without
    # scraping this process's /healthz: backend, consecutive failures,
    # last fallback reason (core/watchdog).
    from armada_tpu.core.watchdog import supervisor
    from armada_tpu.scheduler.slo import recorder as slo_recorder

    doc = {
        "pools": pools,
        "device": supervisor().snapshot(),
        # Streaming SLO percentiles (cycle latency split healthy/
        # degraded): the external control plane reads its scheduling
        # tail latency from the same response it already parses.
        "slo": slo_recorder().snapshot(),
    }
    if trace is not None:
        # The round's span tree (offset form, ops/trace.Span.to_dict): the
        # caller grafts it under its RPC span for one stitched timeline.
        doc["trace"] = trace
    return json.dumps(doc, default=float)


class ScheduleSidecar:
    """Session registry behind the armada_tpu.api.Schedule service."""

    def __init__(self, default_config: SchedulingConfig, clock_ns=None):
        self.default_config = default_config
        self._clock_ns = clock_ns or (lambda: int(time.time() * 1e9))
        self._sessions: dict[str, ScheduleSession] = {}
        self._lock = make_lock("sidecar.service")

    def create_session(
        self, session_id: str = "", config_yaml: str = ""
    ) -> str:
        config = self.default_config
        if config_yaml:
            import yaml

            from armada_tpu.core.config import scheduling_config_from_dict

            try:
                doc = yaml.safe_load(config_yaml) or {}
                if "scheduling" in doc:
                    doc = doc["scheduling"]
                config = scheduling_config_from_dict(doc)
            except (yaml.YAMLError, TypeError, KeyError) as e:
                # caller data -> INVALID_ARGUMENT, never a server traceback
                raise ValueError(f"bad session config_yaml: {e}") from e
        sid = session_id or uuid.uuid4().hex
        # Construct outside the registry lock (JobDb + feed + algo setup is
        # not instant; other sessions' lookups must not stall behind it),
        # then publish under it.
        session = ScheduleSession(sid, config, clock_ns=self._clock_ns)
        with self._lock:
            if sid in self._sessions:
                raise SessionExists(sid)
            self._sessions[sid] = session
        return sid

    def session(self, session_id: str) -> ScheduleSession:
        with self._lock:
            s = self._sessions.get(session_id)
        if s is None:
            raise UnknownSession(session_id)
        return s

    def close_session(self, session_id: str) -> None:
        with self._lock:
            self._sessions.pop(session_id, None)

    # ----------------------------------------------------- wire handling ----
    # (proto-level entry points used by the gRPC service; kept here so the
    # service class in rpc/server.py stays a thin auth + status-code shim)

    def handle_sync(self, msg, trace_id: str = "") -> None:
        s = self.session(msg.session_id)
        executors = None
        if msg.set_executors:
            from armada_tpu.rpc.convert import snapshot_from_proto

            executors = [
                snapshot_from_proto(e, s.factory) for e in msg.executors
            ]
        queues = None
        if msg.set_queues:
            queues = [Queue(q.name, q.weight or 1.0) for q in msg.queues]
        bids = None
        if msg.set_bids:
            bids = {}
            for qb in msg.bids.queues:
                for bid in qb.bids:
                    bids[(qb.queue, bid.band, bid.pool)] = bid.price
        s.apply_sync(
            jobs=list(msg.jobs),
            deletes=list(msg.deleted_job_ids),
            executors=executors,
            queues=queues,
            bids=bids,
            trace_id=trace_id,
        )

    def handle_round(self, msg, trace_id: str = ""):
        from armada_tpu.ops.trace import recorder as trace_recorder
        from armada_tpu.rpc import rpc_pb2 as pb

        s = self.session(msg.session_id)
        result = s.schedule_round(
            now_ns=int(msg.now_ns) or None,
            quarantined=frozenset(msg.quarantined_node_ids),
            trace_id=trace_id,
        )
        # The round's finished trace (it just closed): ship its span tree
        # back only when the caller ASKED to stitch (sent a trace id) --
        # an untraced caller pays zero response bytes for it.
        trace_doc = None
        if trace_id:
            rec = trace_recorder()
            for t in reversed(rec.last()):
                if t.trace_id == trace_id and t.kind == "round":
                    d = t.root.to_dict(t.root.t0)
                    d.setdefault("args", {})["pid"] = t.pid
                    trace_doc = d
                    break
        resp = pb.ScheduleRoundResponse(
            pool_stats_json=_stats_of(result, trace=trace_doc)
        )
        for job, run in result.scheduled:
            resp.scheduled.append(
                pb.RoundLease(
                    job_id=job.id,
                    run_id=run.id,
                    queue=job.queue,
                    node_id=run.node_id,
                    executor=run.executor,
                    pool=run.pool,
                    scheduled_at_priority=run.scheduled_at_priority or 0,
                    away=run.pool_scheduled_away,
                )
            )
        for job, run in result.preempted:
            resp.preempted.append(
                pb.RoundPreemption(job_id=job.id, run_id=run.id)
            )
        for jid in result.failed:
            if len(resp.failed_sample) >= FAILED_SAMPLE_CAP:
                break
            resp.failed_sample.append(jid)
        return resp
