"""Third-party integrations (reference: third_party/)."""
