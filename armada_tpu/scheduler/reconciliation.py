"""Reconciling scheduler-DB rows into the JobDb.

Equivalent of the reference's jobdb reconciliation (internal/scheduler/jobdb/
reconciliation.go, driven from scheduler.go syncState:386): job rows update
job-level fields (authoritative for everything they carry, guarded by
queued_version so stale requeue rows can't regress a newer local lease), run
rows update/insert runs on their job; jobs whose DB row is terminal are deleted
from the JobDb -- the decision events that made them terminal have round-tripped
through the ingestion path, so nothing references them again.
"""

from __future__ import annotations

import dataclasses
from typing import Iterable, Optional

from armada_tpu.core.config import SchedulingConfig
from armada_tpu.core.resources import ResourceListFactory
from armada_tpu.events import events_pb2 as pb
from armada_tpu.events.convert import job_spec_from_proto
from armada_tpu.jobdb.job import Job, JobRun
from armada_tpu.jobdb.jobdb import WriteTxn

# Run flags that only ever go false -> true (monotonic lifecycle flags); the
# remaining fields are identity/placement facts where the first non-empty
# value wins.
_RUN_FLAGS = (
    "leased",
    "pending",
    "running",
    "preempt_requested",
    "succeeded",
    "failed",
    "cancelled",
    "preempted",
    "returned",
    "run_attempted",
)


def job_from_row(row, factory: ResourceListFactory) -> Job:
    spec_pb = pb.JobSpec.FromString(row["spec"])
    spec = job_spec_from_proto(
        row["job_id"],
        row["queue"],
        row["jobset"],
        spec_pb,
        factory,
        submit_time=row["submitted_ns"] / 1e9,
    )
    pools = tuple(p for p in row["pools"].split(",") if p)
    return Job(
        spec=spec,
        priority=int(row["priority"]),
        submitted_ns=int(row["submitted_ns"]),
        queued=bool(row["queued"]),
        queued_version=int(row["queued_version"]),
        validated=bool(row["validated"]),
        pools=pools,
        cancel_requested=bool(row["cancel_requested"]),
        cancel_by_jobset_requested=bool(row["cancel_by_jobset_requested"]),
        preempt_requested=bool(row["preempt_requested"]),
        cancelled=bool(row["cancelled"]),
        succeeded=bool(row["succeeded"]),
        failed=bool(row["failed"]),
    )


def run_from_row(row) -> JobRun:
    return JobRun(
        id=row["run_id"],
        job_id=row["job_id"],
        created_ns=int(row["created_ns"]),
        executor=row["executor"],
        node_id=row["node_id"],
        node_name=row["node_name"] or row["node_id"],
        pool=row["pool"],
        scheduled_at_priority=(
            int(row["scheduled_at_priority"])
            if row["scheduled_at_priority"] is not None
            else None
        ),
        pool_scheduled_away=bool(row["pool_scheduled_away"]),
        leased=bool(row["leased"]),
        pending=bool(row["pending"]),
        running=bool(row["running"]),
        preempt_requested=bool(row["preempt_requested"]),
        succeeded=bool(row["succeeded"]),
        failed=bool(row["failed"]),
        cancelled=bool(row["cancelled"]),
        preempted=bool(row["preempted"]),
        returned=bool(row["returned"]),
        run_attempted=bool(row["run_attempted"]),
        running_ns=int(row["running_ns"]) if "running_ns" in row.keys() else 0,
    )


def _merge_job(existing: Optional[Job], row, factory: ResourceListFactory) -> Job:
    """DB job row is authoritative for job-level fields; existing runs are kept.

    queued/queued_version use the version guard: a stale row (e.g. an old
    requeue materialized after the scheduler already leased the job again) must
    not flip the job back to queued (jobdb JobRequeued update_sequence_number).
    """
    fresh = job_from_row(row, factory)
    if existing is None:
        return fresh
    queued, version = fresh.queued, fresh.queued_version
    if existing.queued_version > version:
        queued, version = existing.queued, existing.queued_version
    return Job(
        spec=fresh.spec,
        priority=fresh.priority,
        requested_priority=fresh.priority,
        submitted_ns=fresh.submitted_ns,
        queued=queued,
        queued_version=version,
        validated=fresh.validated or existing.validated,
        pools=fresh.pools or existing.pools,
        cancel_requested=fresh.cancel_requested or existing.cancel_requested,
        cancel_by_jobset_requested=(
            fresh.cancel_by_jobset_requested or existing.cancel_by_jobset_requested
        ),
        preempt_requested=fresh.preempt_requested or existing.preempt_requested,
        cancelled=fresh.cancelled or existing.cancelled,
        succeeded=fresh.succeeded or existing.succeeded,
        failed=fresh.failed or existing.failed,
        runs=existing.runs,
    )


def _merge_run(existing: Optional[JobRun], fresh: JobRun) -> JobRun:
    """Lifecycle flags are monotonic; OR them so replayed rows can't regress."""
    if existing is None:
        return fresh
    kw = {}
    for f in dataclasses.fields(JobRun):
        a, b = getattr(existing, f.name), getattr(fresh, f.name)
        if f.name in _RUN_FLAGS:
            kw[f.name] = a or b
        else:
            kw[f.name] = b if b not in (None, "", 0, False) else a
    return JobRun(**kw)


def apply_rows(
    txn: WriteTxn,
    job_rows: Iterable,
    run_rows: Iterable,
    config: SchedulingConfig,
    retained_terminal: Optional[set] = None,
) -> list[str]:
    """Apply fetched rows to the txn; returns ids of jobs that changed.

    retained_terminal (a set, mutated): when given, DB-terminal jobs are kept
    in the JobDb (queued=False) and their ids recorded, instead of being
    deleted -- the short-job penalty needs to see recently finished jobs
    (scheduler.go:436-447); the Scheduler's sweep deletes exactly the recorded
    ids once the penalty window lapses.  Only DB-terminal jobs are eligible:
    locally-terminal jobs whose events have not round-tripped must never be
    deleted early (or a later row for them would resurrect a zombie)."""
    factory = config.resource_list_factory()
    touched: list[str] = []

    for row in job_rows:
        job_id = row["job_id"]
        if row["cancelled"] or row["succeeded"] or row["failed"]:
            # Terminal in the DB: state round-tripped; drop from the JobDb
            # (the reference deletes persisted-terminal jobs, scheduler.go:414-441)
            # unless the short-job penalty wants it kept around.
            existing = txn.get(job_id)
            if retained_terminal is not None:
                merged = _merge_job(existing, row, factory)
                # Never let a version-guarded stale queued flag resurrect a
                # terminal job into the queued index.
                txn.upsert(dataclasses.replace(merged, queued=False))
                retained_terminal.add(job_id)
            elif existing is not None:
                txn.delete(job_id)
            touched.append(job_id)
            continue
        existing = txn.get(job_id)
        txn.upsert(_merge_job(existing, row, factory))
        touched.append(job_id)

    for row in run_rows:
        job = txn.get(row["job_id"])
        if job is None:
            continue  # job terminal/unknown; late run row is irrelevant
        fresh = run_from_row(row)
        existing = job.run_by_id(fresh.id)
        merged = _merge_run(existing, fresh)
        if existing is None:
            # Insert without with_new_run: reconciliation must not bump
            # queued_version (that bump belongs to the scheduler's own lease
            # path); derived queued state is fixed up below.
            job = dataclasses.replace(job, runs=job.runs + (merged,))
        else:
            job = job.with_updated_run(merged)
        txn.upsert(job)
        touched.append(job.id)

    return sorted(set(touched))
