"""Ingestion: event log -> materialized views.

Equivalent of the reference's internal/common/ingest +
internal/scheduleringester (SURVEY.md section 2.5): a generic pipeline turning
the partitioned event log into per-view databases, with typed bulk operations
and exactly-once positioning.
"""

from armada_tpu.ingest.converter import convert_sequences
from armada_tpu.ingest.pipeline import IngestionPipeline
from armada_tpu.ingest.schedulerdb import SchedulerDb
from armada_tpu.ingest.shards import (
    PartitionedIngestionPipeline,
    resolve_num_shards,
)


def scheduler_ingestion_pipeline(
    log, db: SchedulerDb, consumer_name: str = "scheduler"
) -> IngestionPipeline:
    """The scheduler ingester: events -> DbOperations -> scheduler SQLite."""
    return IngestionPipeline(
        log,
        sink=db,
        converter=convert_sequences,
        consumer_name=consumer_name,
        start_positions=db.positions(consumer_name),
    )


__all__ = [
    "IngestionPipeline",
    "PartitionedIngestionPipeline",
    "SchedulerDb",
    "convert_sequences",
    "resolve_num_shards",
    "scheduler_ingestion_pipeline",
]
