"""Node-fit predicates on device.

The reference decomposes fit into a static part -- taints/labels/selectors, checked
at NodeType granularity (nodedb/nodematching.go NodeTypeJobRequirementsMet:127,
StaticJobRequirementsMet:161) -- and a dynamic part -- allocatable-at-priority >=
request (DynamicJobRequirementsMet:194).  Static fit was precomputed host-side into a
(scheduling-key x node-type) matrix (core/keys.py); on device it is one gather.

Priority semantics (internaltypes/node.go AllocatableByPriority): a job bound at
priority p consumes allocatable at every priority <= p; equivalently allocatable at
priority p = total - sum of usage by jobs with priority >= p.  We keep per-level
usage `used[P, N, R]` (exact priority level) and derive allocatable via a reversed
cumulative sum, so binding/unbinding is a single-level scatter.
"""

from __future__ import annotations

import jax.numpy as jnp


def allocatable_from_used(total, used):
    """allocatable[P, N, R] from total[N, R] and per-level usage used[P, N, R].

    allocatable[p] = total - sum_{p' >= p} used[p'] (suffix sum over the priority
    ladder, lowest priority at index 0).
    """
    suffix = jnp.cumsum(used[::-1], axis=0)[::-1]
    return total[None, :, :] - suffix


def static_fit(compat, key, node_type):
    """bool[N]: static fit of scheduling-key `key` against per-node type ids.

    compat: bool[K, T] from core.keys.static_fit_matrix; one row gather + one
    per-node gather (nodematching.go:127-145 collapsed to memory lookups).
    """
    return compat[key][node_type]


def dynamic_fit(alloc_at_p, req):
    """bool[N]: request fits in allocatable-at-priority (nodematching.go:194-214).

    alloc_at_p: [N, R] allocatable at the job's priority level; req: [R].
    """
    return jnp.all(alloc_at_p >= req[None, :], axis=-1)


def job_fit(
    compat,
    key,
    node_type,
    alloc_at_p,
    req,
    node_ok,
    pinned_node,
):
    """Full per-node fit mask for one job (nodedb.go SelectNodeForJobWithTxn:392).

    node_ok: bool[N] -- node is in the right pool, schedulable, not padding.
    pinned_node: int32 scalar; >= 0 restricts fit to that node (the evicted-job
    node-id selector path, api.go addNodeIdSelector:278 / nodedb.go:426).
    """
    mask = static_fit(compat, key, node_type) & dynamic_fit(alloc_at_p, req) & node_ok
    n = alloc_at_p.shape[0]
    pin_mask = jnp.where(
        pinned_node >= 0,
        jnp.arange(n, dtype=pinned_node.dtype) == pinned_node,
        jnp.ones((n,), bool),
    )
    return mask & pin_mask
