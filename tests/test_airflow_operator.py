"""ArmadaOperator (third_party/airflow equivalent) against a live control
plane over gRPC, without Airflow installed (the gated-import path)."""

import threading

import pytest

from armada_tpu.cli.serve import run_fake_executor, start_control_plane
from armada_tpu.core.config import SchedulingConfig
from armada_tpu.integrations.airflow import AirflowException, ArmadaOperator
from armada_tpu.rpc.client import ArmadaClient
from armada_tpu.server.queues import QueueRecord


@pytest.fixture
def plane(tmp_path):
    p = start_control_plane(
        str(tmp_path / "data"),
        config=SchedulingConfig(shape_bucket=32),
        cycle_interval_s=0.05,
        schedule_interval_s=0.1,
    )
    client = ArmadaClient(f"127.0.0.1:{p.port}")
    client.create_queue(QueueRecord("af"))
    client.close()
    yield p
    p.stop()


def agent(plane, runtime_s=0.2):
    stop = threading.Event()
    t = threading.Thread(
        target=run_fake_executor,
        args=(f"127.0.0.1:{plane.port}",),
        kwargs={
            "interval_s": 0.05,
            "stop": stop,
            "default_runtime_s": runtime_s,
            "config": SchedulingConfig(shape_bucket=32),
        },
        daemon=True,
    )
    t.start()
    return stop, t


def test_operator_runs_job_to_success(plane):
    stop, t = agent(plane)
    try:
        op = ArmadaOperator(
            task_id="sim",
            armada_url=f"127.0.0.1:{plane.port}",
            queue="af",
            job={"resources": {"cpu": "2", "memory": "1"}},
            poll_interval_s=0.2,
            timeout_s=30,
        )
        job_id = op.execute()
        assert job_id and op.jobset == "sim"
    finally:
        stop.set()
        t.join(timeout=5)


def test_operator_raises_on_unschedulable_failure(plane):
    stop, t = agent(plane)
    try:
        op = ArmadaOperator(
            task_id="toolarge",
            armada_url=f"127.0.0.1:{plane.port}",
            queue="af",
            # larger than any fake node: the submit check fails it terminally
            job={"resources": {"cpu": "9999", "memory": "1"}},
            poll_interval_s=0.2,
            timeout_s=30,
        )
        with pytest.raises(AirflowException, match="failed"):
            op.execute()
    finally:
        stop.set()
        t.join(timeout=5)


def test_on_kill_cancels_the_job(plane):
    # No executor: the job stays queued; on_kill cancels it.
    op = ArmadaOperator(
        task_id="killme",
        armada_url=f"127.0.0.1:{plane.port}",
        queue="af",
        job={"resources": {"cpu": "1", "memory": "1"}, "priorityClass": ""},
        poll_interval_s=0.1,
        timeout_s=2,
    )
    with pytest.raises(AirflowException, match="timed out"):
        op.execute()
    assert op.job_id is not None
    op.on_kill()
    # the cancellation lands as a cancelled_job event
    client = ArmadaClient(f"127.0.0.1:{plane.port}")
    try:
        import time

        deadline = time.time() + 10
        cancelled = False
        while time.time() < deadline and not cancelled:
            for _, seq in client.get_jobset_events("af", "killme"):
                for ev in seq.events:
                    if ev.WhichOneof("event") == "cancelled_job":
                        cancelled = True
        assert cancelled
    finally:
        client.close()


def test_camel_case_job_keys_accepted():
    op = ArmadaOperator(
        task_id="x",
        armada_url="localhost:1",
        queue="q",
        job={
            "resources": {"cpu": "1"},
            "priorityClassName": "armada-default",
            "nodeSelector": {"zone": "a"},
            "gangCardinality": 2,
        },
    )
    from armada_tpu.integrations.airflow import _snake_item

    item = _snake_item(op.job)
    assert item["priority_class"] == "armada-default"
    assert item["node_selector"] == {"zone": "a"}
    assert item["gang_cardinality"] == 2
