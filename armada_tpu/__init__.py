"""armada-tpu: a TPU-native batch-scheduling framework.

A from-scratch re-architecture of the capabilities of Armada
(github.com/armadaproject/armada, mounted read-only at /root/reference): queueing of
millions of jobs across many clusters, dominant-resource-fair scheduling, urgency- and
fair-share preemption, all-or-nothing gang scheduling, an event-sourced control plane,
executor reconciliation, a discrete-event simulator, CLI and observability.

The per-round job->node assignment -- the reference's `SchedulingAlgo.Schedule`
(internal/scheduler/scheduling/scheduling_algo.go:36-41) -- is reformulated as dense
(queues x jobs x nodes x resources) tensor computation compiled with jax.jit/pjit and
executed on TPU.  See SURVEY.md section 7 for the blueprint.
"""

__version__ = "0.1.0"

from armada_tpu.core.resources import ResourceListFactory, ResourceList
from armada_tpu.core.config import (
    SchedulingConfig,
    PriorityClass,
    default_scheduling_config,
    scheduling_config_from_yaml,
)

__all__ = [
    "ResourceListFactory",
    "ResourceList",
    "SchedulingConfig",
    "PriorityClass",
    "default_scheduling_config",
    "scheduling_config_from_yaml",
    "__version__",
]
