"""Gang node-uniformity domains + cross-class atomicity
(gang_scheduler.go NodeUniformity + all-or-nothing, :100-247)."""

import pytest

from armada_tpu.core.config import SchedulingConfig
from armada_tpu.core.types import JobSpec, NodeSpec, Queue
from armada_tpu.models import run_scheduling_round
from armada_tpu.scheduler.submitcheck import SubmitChecker
from armada_tpu.scheduler.executors import ExecutorSnapshot

CFG = SchedulingConfig(shape_bucket=32, indexed_node_labels=("rack",))
F = CFG.resource_list_factory()


def rnode(nid, rack, cpu="8"):
    return NodeSpec(
        id=nid,
        pool="default",
        labels={"rack": rack},
        total_resources=F.from_mapping({"cpu": cpu, "memory": "32"}),
    )


def member(jid, cpu="8", gang="g1", card=2, uniformity="rack", **kw):
    return JobSpec(
        id=jid,
        queue="q",
        gang_id=gang,
        gang_cardinality=card,
        gang_node_uniformity_label=uniformity,
        resources=F.from_mapping({"cpu": cpu, "memory": "2"}),
        **kw,
    )


def test_gang_lands_in_one_uniformity_domain():
    # rack a has two nodes, rack b one: both members must land in rack a.
    nodes = [rnode("a1", "a"), rnode("b1", "b"), rnode("a2", "a")]
    out = run_scheduling_round(
        CFG,
        pool="default",
        nodes=nodes,
        queues=[Queue("q")],
        queued_jobs=[member("m1"), member("m2")],
    )
    assert set(out.scheduled) == {"m1", "m2"}
    assert set(out.scheduled.values()) == {"a1", "a2"}


def test_gang_never_straddles_domains():
    # one node per rack: the gang COULD fit split across racks, but
    # uniformity forbids it.
    nodes = [rnode("a1", "a"), rnode("b1", "b")]
    out = run_scheduling_round(
        CFG,
        pool="default",
        nodes=nodes,
        queues=[Queue("q")],
        queued_jobs=[member("m1"), member("m2")],
    )
    assert out.scheduled == {}
    # a non-uniformity gang of the same shape happily straddles
    free = run_scheduling_round(
        CFG,
        pool="default",
        nodes=nodes,
        queues=[Queue("q")],
        queued_jobs=[
            member("m1", uniformity=""),
            member("m2", uniformity=""),
        ],
    )
    assert set(free.scheduled) == {"m1", "m2"}


def test_unlabeled_nodes_cannot_host_uniformity_gangs():
    nodes = [
        NodeSpec(
            id="plain",
            pool="default",
            total_resources=F.from_mapping({"cpu": "32", "memory": "64"}),
        )
    ]
    out = run_scheduling_round(
        CFG,
        pool="default",
        nodes=nodes,
        queues=[Queue("q")],
        queued_jobs=[member("m1", cpu="2"), member("m2", cpu="2")],
    )
    assert out.scheduled == {}


def test_heterogeneous_gang_is_atomic_across_key_classes():
    # m2's selector matches nothing: its sub-gang can never place, so m1's
    # schedulable sub-gang must unwind (no half-gang).
    nodes = [rnode("a1", "a"), rnode("a2", "a")]
    out = run_scheduling_round(
        CFG,
        pool="default",
        nodes=nodes,
        queues=[Queue("q")],
        queued_jobs=[
            member("m1", uniformity=""),
            member("m2", uniformity="", node_selector={"rack": "nowhere"}),
        ],
    )
    assert out.scheduled == {}
    assert "m1" in out.failed and "m2" in out.failed


def test_heterogeneous_gang_schedules_when_all_classes_fit():
    nodes = [rnode("a1", "a"), rnode("b1", "b")]
    out = run_scheduling_round(
        CFG,
        pool="default",
        nodes=nodes,
        queues=[Queue("q")],
        queued_jobs=[
            member("m1", uniformity=""),
            member("m2", uniformity="", node_selector={"rack": "b"}),
        ],
    )
    assert set(out.scheduled) == {"m1", "m2"}
    assert out.scheduled["m2"] == "b1"


def test_submit_checker_respects_uniformity_domains():
    checker = SubmitChecker(CFG)
    checker.update_executors(
        [
            ExecutorSnapshot(
                id="ex1",
                pool="default",
                nodes=(rnode("a1", "a"), rnode("b1", "b")),
                last_update_ns=1,
            )
        ]
    )
    # 2x8cpu with uniformity: no single rack holds both -> unschedulable
    res = checker.check_gang([member("m1"), member("m2")])
    assert not res.ok
    # without uniformity the same shape passes
    res2 = checker.check_gang(
        [member("m1", uniformity=""), member("m2", uniformity="")]
    )
    assert res2.ok


def test_lookback_cap_keeps_split_gangs_atomic():
    """A split gang whose sibling falls past maxQueueLookback is dropped
    whole -- a truncated sibling must not let a half-gang lease."""
    import dataclasses

    cfg = dataclasses.replace(CFG, max_queue_lookback=3)
    nodes = [rnode("a1", "a", cpu="32"), rnode("b1", "b", cpu="32")]
    singles = [
        JobSpec(id=f"a{i}", queue="q", resources=F.from_mapping({"cpu": "2", "memory": "1"}))
        for i in range(2)
    ]
    gang = [
        member("m1", cpu="2", uniformity=""),
        member("m2", cpu="2", uniformity="", node_selector={"rack": "b"}),
    ]
    out = run_scheduling_round(
        cfg,
        pool="default",
        nodes=nodes,
        queues=[Queue("q")],
        queued_jobs=singles + gang,
    )
    # 2 singles + 1 sub-gang fit the lookback; the second sub-gang is cut:
    # neither gang member may schedule.
    assert set(out.scheduled) == {"a0", "a1"}


def test_submit_checker_rejects_gang_with_impossible_class():
    """A heterogeneous gang with one never-schedulable member class is
    rejected up front (the round would keep it perma-dead otherwise)."""
    checker = SubmitChecker(CFG)
    checker.update_executors(
        [
            ExecutorSnapshot(
                id="ex1",
                pool="default",
                nodes=(rnode("a1", "a"), rnode("a2", "a")),
                last_update_ns=1,
            )
        ]
    )
    res = checker.check_gang(
        [
            member("m1", cpu="2", uniformity=""),
            member("m2", cpu="2", uniformity="", node_selector={"rack": "nowhere"}),
        ]
    )
    assert not res.ok
    ok = checker.check_gang(
        [
            member("m1", cpu="2", uniformity=""),
            member("m2", cpu="2", uniformity="", node_selector={"rack": "a"}),
        ]
    )
    assert ok.ok


def test_het_uniformity_gang_domain_works_for_all_classes():
    """The chosen domain must satisfy every key class: m2 only fits rack b,
    so the gang must land wholly in rack b even though rack a has more
    capacity for m1."""
    nodes = [
        rnode("a1", "a", cpu="32"),
        rnode("a2", "a", cpu="32"),
        rnode("b1", "b", cpu="8"),
        rnode("b2", "b", cpu="8"),
    ]
    out = run_scheduling_round(
        CFG,
        pool="default",
        nodes=nodes,
        queues=[Queue("q")],
        queued_jobs=[
            member("m1", cpu="2"),
            member("m2", cpu="2", node_selector={"rack": "b"}),
        ],
    )
    assert set(out.scheduled) == {"m1", "m2"}
    assert all(n in ("b1", "b2") for n in out.scheduled.values())


def test_submit_check_survives_node_id_only_selector_difference():
    """Members differing only in the excluded node-id-label selector share a
    key class; the checker must not crash or mis-split (regression: the
    class split used raw selectors while key_of excludes the pin label)."""
    checker = SubmitChecker(CFG)
    checker.update_executors(
        [
            ExecutorSnapshot(
                id="ex1",
                pool="default",
                nodes=(rnode("a1", "a"), rnode("a2", "a")),
                last_update_ns=1,
            )
        ]
    )
    res = checker.check_gang(
        [
            member("m1", cpu="2", uniformity="",
                   node_selector={"kubernetes.io/hostname": "a1"}),
            member("m2", cpu="2", uniformity="",
                   node_selector={"kubernetes.io/hostname": "a2"}),
        ]
    )
    assert res.ok


def test_requeued_members_rejoin_their_running_siblings_domain():
    """Half a gang runs in rack b; the re-queued half must rejoin rack b even
    though rack a has more free capacity."""
    from armada_tpu.core.types import RunningJob

    nodes = [
        rnode("a1", "a", cpu="32"),
        rnode("a2", "a", cpu="32"),
        rnode("b1", "b", cpu="8"),
        rnode("b2", "b", cpu="8"),
    ]
    running = [
        RunningJob(job=member("m1", cpu="8"), node_id="b1", priority=1000)
    ]
    out = run_scheduling_round(
        CFG,
        pool="default",
        nodes=nodes,
        queues=[Queue("q")],
        queued_jobs=[member("m2", cpu="8", card=2)],
        running=running,
    )
    assert out.scheduled == {"m2": "b2"}
