"""Cross-host event-log replication: followers tail the leader's log.

The reference survives a node loss because its durable state lives in
Pulsar + Postgres, off the scheduler hosts (leader.go:112-190 only elects;
state is remote).  This repo's log is host-local (native/eventlog.cc), so a
replicated deployment WITHOUT shared storage needs the follower to carry
its own copy: `LogReplicator` tails every partition of the leader's log
over the LogReplication gRPC service into the follower's local log.

Records are byte-framed with offset == byte position, so appending the
same records in the same order reproduces IDENTICAL offsets -- after
takeover the follower's ingest pipelines resume from their own committed
consumer positions against a log that is a byte-for-byte prefix-equal
copy of the leader's.

Replication is asynchronous (the tail of Pulsar-style geo-replication,
not synchronous quorum writes): an event the leader committed but had not
yet streamed when it died is lost with the leader's disk.  The window is
one poll interval (~50ms); deployments that cannot tolerate it need
shared/remote storage for the log itself.

Divergence (this replica's log is not a prefix of the current leader's --
the classic cause: we led once, accepted writes, lost the election, and
the new leader never saw our tail) is repaired AUTOMATICALLY when it is
safe: the follower truncates its log back to the last common prefix with
the leader and resumes tailing, PROVIDED no local consumer has acked past
the cut (the dropped suffix was never consumed into a local view, so
truncation erases nothing observable).  If a consumer HAS read into the
divergent suffix, truncation would leave views built from records the new
lineage never had -- replication halts and an operator picks a survivor
(docs/operations.md has the truncate-vs-wipe decision table).
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Callable, Optional

from armada_tpu.eventlog.log import EventLog

log = logging.getLogger("armada.replicator")


class ReplicationDiverged(RuntimeError):
    """The local log is not a prefix of the leader's.  Recovered by
    truncating to the last common prefix when the divergent suffix is
    unacked; otherwise replication halts for operator action (automatic
    repair would silently drop records a local view already consumed)."""


class LogReplicator:
    """Tail the current leader's log into `local` (all partitions).

    `leader_address` returns the address to tail: None/"" = no leader to
    follow right now (we ARE the leader, or an election gap) -- the
    replicator idles and re-resolves.  `client_factory(address)` returns an
    object with `tail_log(partition, from_offset, follow, idle_timeout_s)`
    yielding LogRecord messages and a `close()` (rpc.client.ReplicationClient).

    `min_acked` (optional) returns, per partition, the LOWEST consumer
    position any local materialized view has committed -- the safety bound
    for divergence truncation.  Without it, divergence always halts (the
    pre-truncation behavior).
    """

    def __init__(
        self,
        local: EventLog,
        leader_address: Callable[[], Optional[str]],
        client_factory,
        poll_interval_s: float = 0.2,
        idle_timeout_s: float = 5.0,
        min_acked: Optional[Callable[[], dict[int, int]]] = None,
    ):
        self.local = local
        self._leader_address = leader_address
        self._client_factory = client_factory
        self._poll = poll_interval_s
        self._idle = idle_timeout_s
        self._min_acked = min_acked
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []
        # partition -> replicated end offset (observability/tests)
        self.replicated_to: dict[int, int] = {
            p: local.end_offset(p) for p in range(local.num_partitions)
        }
        # Durability gauges (serve /healthz + prometheus): last known leader
        # end per partition and the monotonic instant each partition was
        # last caught up to it.
        self.leader_ends: dict[int, int] = {}
        self._caught_up_at: dict[int, float] = {}
        self.records_replicated = 0
        self.truncations = 0
        self.diverged = threading.Event()

    def start(self) -> None:
        for p in range(self.local.num_partitions):
            t = threading.Thread(
                target=self._run_partition, args=(p,), daemon=True,
                name=f"log-replicator-p{p}",
            )
            t.start()
            self._threads.append(t)

    def stop(self) -> None:
        self._stop.set()
        for t in self._threads:
            t.join(timeout=5)

    # ------------------------------------------------------------------------

    def _run_partition(self, partition: int) -> None:
        from armada_tpu.core.backoff import Backoff

        # Bounded exponential backoff + jitter on tail failures: a dead
        # leader must not be hammered at poll frequency by every partition
        # thread of every follower in lockstep; cap keeps takeover lag
        # bounded once the peer returns.
        backoff = Backoff(base_s=self._poll, cap_s=30.0)
        while not self._stop.is_set():
            address = None
            try:
                address = self._leader_address()
            except Exception:
                pass
            if not address:
                # we lead (None) or nobody does (""): nothing to tail
                self._stop.wait(self._poll)
                continue
            try:
                self._tail_once(partition, address)
                backoff.reset()
            except ReplicationDiverged as e:
                if self._recover_divergence(partition, address, e):
                    backoff.reset()
                    continue
                self.diverged.set()
                log.error(
                    "partition %d: local log diverged from leader %s and "
                    "the divergent suffix is acked -- replication halted "
                    "(operator action required): %s",
                    partition,
                    address,
                    e,
                )
                return
            except Exception as e:
                delay = backoff.next_delay()
                log.warning(
                    "partition %d: tail of %s failed (%s); attempt %d, "
                    "retrying in %.2fs",
                    partition,
                    address,
                    e,
                    backoff.attempts,
                    delay,
                )
                self._stop.wait(delay)

    def _tail_once(self, partition: int, address: str) -> None:
        client = self._client_factory(address)
        try:
            start = self.local.end_offset(partition)
            info = client.get_log_info()
            leader_end = list(info.end_offsets)[partition]
            self.leader_ends[partition] = leader_end
            if start >= leader_end:
                self._caught_up_at[partition] = time.monotonic()
            elif partition not in self._caught_up_at:
                # First observation of being BEHIND with no catch-up ever
                # recorded (fresh replica against a long leader log): start
                # the lag clock NOW, or lag_s would read 0.0 for the whole
                # hours-long initial catch-up -- exactly when the
                # "takeover would lose this window" alert matters most.
                self._caught_up_at[partition] = time.monotonic()
            if start > leader_end:
                # local log is LONGER than the leader's: we hold committed
                # records the leader never saw (e.g. this replica led once)
                raise ReplicationDiverged(
                    f"partition {partition}: local end {start} beyond "
                    f"leader end {leader_end}"
                )
            for record in client.tail_log(
                partition,
                from_offset=start,
                follow=True,
                idle_timeout_s=self._idle,
            ):
                if self._stop.is_set():
                    return
                local_end = self.local.end_offset(partition)
                if record.offset != local_end:
                    # Gap (leader compacted?) or overlap mismatch: either
                    # way the byte-prefix property is broken.
                    raise ReplicationDiverged(
                        f"partition {partition}: leader streams offset "
                        f"{record.offset}, local end is {local_end}"
                    )
                self.local.append(partition, record.key, record.payload)
                self.records_replicated += 1
                new_end = self.local.end_offset(partition)
                self.replicated_to[partition] = new_end
                if new_end >= leader_end:
                    self._caught_up_at[partition] = time.monotonic()
        except Exception as e:
            # A local end offset that is not a record BOUNDARY in the
            # leader's log makes the leader's read fail with its corrupt-
            # record error: that is divergence (mismatched histories), not
            # a transient stream failure.
            if "corrupt record" in str(e):
                raise ReplicationDiverged(
                    f"partition {partition}: local end is not a record "
                    f"boundary in the leader's log ({e})"
                ) from e
            raise
        finally:
            client.close()

    # --- divergence recovery -------------------------------------------------

    def _common_prefix(self, partition: int, client) -> int:
        """Largest offset up to which local and leader logs hold identical
        records.  Walks both logs record-by-record from 0 -- O(log size),
        paid only on the rare divergence event, and exact (no trust in
        offsets alone: payloads are compared)."""
        common = 0
        local_iter = self.local.iter_from(partition, 0)
        for theirs in client.tail_log(
            partition, from_offset=0, follow=False, idle_timeout_s=0.5
        ):
            ours = next(local_iter, None)
            if ours is None:
                break  # local is a strict prefix: everything local matches
            if (
                ours.offset != theirs.offset
                or ours.key != theirs.key
                or ours.payload != theirs.payload
            ):
                break
            common = ours.next_offset
        return common

    def _recover_divergence(
        self, partition: int, address: str, cause: ReplicationDiverged
    ) -> bool:
        """Truncate the local partition back to the last common prefix with
        the leader IF no local consumer acked past it; returns True when
        replication may resume.  Conservative on any error: halt."""
        if self._min_acked is None:
            return False
        try:
            client = self._client_factory(address)
            try:
                common = self._common_prefix(partition, client)
            finally:
                client.close()
            acked = int(self._min_acked().get(partition, 0))
        except Exception as e:  # noqa: BLE001 - recovery must fail CLOSED
            log.warning(
                "partition %d: divergence recovery probe failed (%s); halting",
                partition,
                e,
            )
            return False
        if acked > common:
            log.error(
                "partition %d: local views consumed to %d but the common "
                "prefix with the leader ends at %d -- truncation would "
                "orphan consumed state",
                partition,
                acked,
                common,
            )
            return False
        dropped = self.local.end_offset(partition) - common
        self.local.truncate(partition, common)
        self.replicated_to[partition] = common
        self.truncations += 1
        log.warning(
            "partition %d: diverged from leader %s (%s); truncated %d "
            "unacked bytes back to common prefix %d and resuming",
            partition,
            address,
            cause,
            dropped,
            common,
        )
        return True

    # --- observability -------------------------------------------------------

    def status(self) -> dict:
        """Replication-lag block for /healthz + the prometheus gauges:
        bytes behind the last known leader end, seconds since each
        partition was last caught up, totals."""
        now = time.monotonic()
        lag_bytes = 0
        lag_s = 0.0
        for p in range(self.local.num_partitions):
            leader_end = self.leader_ends.get(p)
            if leader_end is not None:
                lag_bytes += max(0, leader_end - self.local.end_offset(p))
            seen = self._caught_up_at.get(p)
            if seen is not None:
                lag_s = max(lag_s, now - seen)
        return {
            "lag_bytes": lag_bytes,
            "lag_s": round(lag_s, 3),
            "records_replicated": self.records_replicated,
            "truncations": self.truncations,
            "diverged": self.diverged.is_set(),
        }

    def caught_up_to(self, end_offsets: dict[int, int]) -> bool:
        """True when every partition has replicated at least to the given
        end offsets (test/drain helper)."""
        return all(
            self.local.end_offset(p) >= off for p, off in end_offsets.items()
        )
