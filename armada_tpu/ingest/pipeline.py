"""Generic ingestion pipeline: consume -> convert -> store -> ack.

Equivalent of the reference's ingest.IngestionPipeline generics
(internal/common/ingest/ingestion_pipeline.go:40-79), reused by all three
ingesters there (scheduler PG / lookout PG / Redis events).  Here the sink
stores data AND the consumer position in one transaction (see SchedulerDb),
so a crash between store and ack cannot double-apply.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Protocol

from armada_tpu.eventlog import Consumer, EventLog
from armada_tpu.events import events_pb2 as pb


class Sink(Protocol):
    def store(self, batch_ops, consumer: str, next_positions: dict[int, int]) -> None:
        ...


class IngestionPipeline:
    """Polls the event log, converts batches, stores them transactionally.

    `converter(sequences) -> batch` produces whatever the sink stores (DbOps
    for the scheduler DB, rows for lookout, stream entries for the event API).
    """

    def __init__(
        self,
        log: EventLog,
        sink: Sink,
        converter: Callable[[list[pb.EventSequence]], object],
        consumer_name: str,
        start_positions: dict[int, int] | None = None,
        poll_interval: float = 0.05,
    ):
        self.consumer_name = consumer_name
        self._consumer = Consumer(log, positions=start_positions)
        self._sink = sink
        self._converter = converter
        self._poll_interval = poll_interval
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def run_once(self) -> int:
        """One consume->convert->store->ack round; returns #sequences applied."""
        from armada_tpu.core import faults

        batch = self._consumer.poll()
        if not batch.sequences:
            return 0
        converted = self._converter(batch.sequences)
        self._sink.store(
            converted,
            consumer=self.consumer_name,
            next_positions=batch.next_positions,
        )
        # Crash drill: die between the batch's transactional commit (data +
        # cursor advance together) and the in-memory ack.  Exactly-once must
        # hold EITHER WAY: a restarted pipeline resumes from the store's
        # committed positions, and a surviving in-process consumer that
        # re-polls the same batch re-stores it idempotently (INSERT OR
        # IGNORE / monotonic marks) with the same cursor values.
        faults.check("ingest_ack")
        self._consumer.ack(batch.next_positions)
        return len(batch.sequences)

    def run_until_caught_up(self, max_rounds: int = 1_000_000) -> int:
        total = 0
        for _ in range(max_rounds):
            n = self.run_once()
            total += n
            if n == 0 and self._consumer.caught_up():
                return total
        return total

    # --- background service mode -------------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            raise RuntimeError("pipeline already started")
        self._stop.clear()
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join()
            self._thread = None

    def alive(self) -> bool:
        """True while the background loop is running (feeds health checks)."""
        return self._thread is not None and self._thread.is_alive()

    def _loop(self) -> None:
        from armada_tpu.core.logging import get_logger, log_context

        with log_context(consumer=self.consumer_name):
            self._loop_inner(get_logger(__name__))

    def _loop_inner(self, log) -> None:
        from armada_tpu.core.backoff import Backoff

        # Jittered exponential backoff on batch failures (a restarting
        # external DB would otherwise see every pipeline retry in lockstep
        # at the same instant); positions were not acked, so the batch
        # replays exactly-once when the store recovers.
        backoff = Backoff(base_s=self._poll_interval, cap_s=5.0)
        while not self._stop.is_set():
            try:
                n = self.run_once()
                backoff.reset()
            except Exception:  # noqa: BLE001 - service thread must survive
                delay = backoff.next_delay()
                log.exception(
                    "ingestion pipeline %s: batch failed (attempt %d); "
                    "retrying in %.2fs",
                    self.consumer_name,
                    backoff.attempts,
                    delay,
                )
                self._stop.wait(delay)
                continue
            if n == 0:
                self._stop.wait(self._poll_interval)
