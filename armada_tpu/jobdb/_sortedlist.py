"""Chunked sorted-key list: the SortedKeyList subset JobDb uses.

Drop-in for ``sortedcontainers.SortedKeyList`` (add / discard / len / ordered
iteration) when that package is absent from the toolchain.  Same design:
values live in bounded chunks kept in key order, with a per-chunk max-key
index, so ``add``/``discard`` cost one bisect over the chunk index plus one
O(load) list insert -- not an O(n) memmove of a million-entry flat list
(the JobDb's per-queue queued index reaches backlog scale).
"""

from __future__ import annotations

from bisect import bisect_left, bisect_right
from typing import Callable, Iterable, Optional

_LOAD = 1024


class SortedKeyList:
    __slots__ = ("_key", "_chunks", "_keys", "_maxes", "_len")

    def __init__(self, iterable: Optional[Iterable] = None, key: Callable = None):
        if key is None:
            raise TypeError("SortedKeyList requires a key function")
        self._key = key
        self._chunks: list[list] = []
        self._keys: list[list] = []
        self._maxes: list = []
        self._len = 0
        if iterable is not None:
            values = sorted(iterable, key=key)
            for lo in range(0, len(values), _LOAD):
                chunk = values[lo : lo + _LOAD]
                self._chunks.append(chunk)
                self._keys.append([key(v) for v in chunk])
                self._maxes.append(self._keys[-1][-1])
            self._len = len(values)

    @property
    def key(self) -> Callable:
        return self._key

    def __len__(self) -> int:
        return self._len

    def __iter__(self):
        for chunk in self._chunks:
            yield from chunk

    def __repr__(self) -> str:
        return f"SortedKeyList({list(self)!r})"

    def add(self, value) -> None:
        k = self._key(value)
        if not self._maxes:
            self._chunks.append([value])
            self._keys.append([k])
            self._maxes.append(k)
            self._len = 1
            return
        ci = bisect_left(self._maxes, k)
        if ci == len(self._maxes):
            ci -= 1
        keys = self._keys[ci]
        pos = bisect_right(keys, k)
        keys.insert(pos, k)
        self._chunks[ci].insert(pos, value)
        self._maxes[ci] = keys[-1]
        self._len += 1
        if len(keys) > 2 * _LOAD:
            self._split(ci)

    def _split(self, ci: int) -> None:
        keys = self._keys[ci]
        chunk = self._chunks[ci]
        half = len(keys) // 2
        self._keys[ci : ci + 1] = [keys[:half], keys[half:]]
        self._chunks[ci : ci + 1] = [chunk[:half], chunk[half:]]
        self._maxes[ci : ci + 1] = [self._keys[ci][-1], self._keys[ci + 1][-1]]

    def discard(self, value) -> None:
        k = self._key(value)
        if not self._maxes:
            return
        ci = bisect_left(self._maxes, k)
        # equal keys may straddle a chunk boundary: scan forward while the
        # chunk can still hold this key
        while ci < len(self._maxes):
            keys = self._keys[ci]
            pos = bisect_left(keys, k)
            while pos < len(keys) and keys[pos] == k:
                if self._chunks[ci][pos] == value:
                    del keys[pos]
                    del self._chunks[ci][pos]
                    self._len -= 1
                    if not keys:
                        del self._keys[ci]
                        del self._chunks[ci]
                        del self._maxes[ci]
                    else:
                        self._maxes[ci] = keys[-1]
                    return
                pos += 1
            if pos < len(keys):
                return  # key range exhausted within this chunk
            ci += 1

    def update(self, iterable) -> None:
        for v in iterable:
            self.add(v)
