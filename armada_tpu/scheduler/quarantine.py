"""Quarantine: stop trusting infrastructure with high failure rates.

Two trackers live here, same philosophy, different layers:

* ``NodeQuarantine`` -- the reference's "automatically removing nodes
  exhibiting high failure rates from consideration for scheduling"
  (README.md:28): every attempted run that dies reports its node; a node
  accumulating `failure_threshold` failures within `window_s` is treated
  unschedulable for `cooldown_s`, then re-admitted.  Complementary to
  retry anti-affinity (scheduler.go:522-568), which keeps one job off its
  own bad nodes; quarantine protects EVERY job from a node that keeps
  killing other people's pods.

* ``DeviceQuarantine`` -- the ACCELERATOR-side analogue, fed by round-
  output verification (models/verify.py): a device whose rounds keep
  failing the conservation-invariant / fingerprint certification is
  producing silently-wrong answers, the one failure mode a re-probe's
  healthy matmul cannot see.  ``strikes`` verification failures within
  ``window_s`` quarantine the device: the watchdog re-probe loop and the
  mesh restore loop (core/watchdog.promote / parallel/serving.restore,
  gated through watchdog.set_promotion_gate) stop re-promoting it, and
  rounds stay on the CPU rung until an OPERATOR clears it
  (``armadactl quarantine --clear``) -- unlike nodes there is no cooldown,
  because a chip that corrupts results does not heal by waiting.
  Knobs: ``ARMADA_QUARANTINE_STRIKES`` (default 3; 0 disables),
  ``ARMADA_QUARANTINE_WINDOW_S`` (default 600).
"""

from __future__ import annotations

import os
import time
from collections import deque
from typing import Deque, Dict, Optional

from armada_tpu.analysis.tsan import make_lock


class NodeQuarantine:
    def __init__(
        self,
        failure_threshold: int = 0,
        window_s: float = 600.0,
        cooldown_s: float = 1200.0,
    ):
        """failure_threshold 0 disables the tracker entirely."""
        self.failure_threshold = failure_threshold
        self.window_ns = int(window_s * 1e9)
        self.cooldown_ns = int(cooldown_s * 1e9)
        self._failures: Dict[str, Deque[int]] = {}
        self._quarantined_until: Dict[str, int] = {}

    @property
    def enabled(self) -> bool:
        return self.failure_threshold > 0

    def record_failure(self, node_id: str, now_ns: int) -> bool:
        """Record one run death on `node_id`; True if this trips quarantine."""
        if not self.enabled or not node_id:
            return False
        q = self._failures.setdefault(node_id, deque())
        q.append(now_ns)
        cutoff = now_ns - self.window_ns
        while q and q[0] < cutoff:
            q.popleft()
        if len(q) >= self.failure_threshold:
            self._quarantined_until[node_id] = now_ns + self.cooldown_ns
            q.clear()
            return True
        return False

    def quarantined(self, now_ns: int) -> frozenset:
        """Node ids currently quarantined (cooldown not yet lapsed)."""
        if not self._quarantined_until:
            return frozenset()
        expired = [
            nid for nid, until in self._quarantined_until.items() if until <= now_ns
        ]
        for nid in expired:
            del self._quarantined_until[nid]
            self._failures.pop(nid, None)
        return frozenset(self._quarantined_until)


class DeviceQuarantine:
    """Per-device verification-strike scoreboard (module docstring).

    Thread-safe: strikes arrive from whichever thread ran the failed round
    (the watchdog worker, the scheduler loop, a sidecar round) while the
    re-probe loops read the promotion gate concurrently."""

    def __init__(
        self,
        strikes: Optional[int] = None,
        window_s: Optional[float] = None,
    ):
        if strikes is None:
            try:
                strikes = int(os.environ.get("ARMADA_QUARANTINE_STRIKES", "3"))
            except ValueError:
                strikes = 3
        if window_s is None:
            try:
                window_s = float(
                    os.environ.get("ARMADA_QUARANTINE_WINDOW_S", "600")
                )
            except ValueError:
                window_s = 600.0
        self.strikes = max(0, strikes)  # 0 disables (strikes still counted)
        self.window_s = max(0.0, window_s)
        self._lock = make_lock("quarantine.device")
        self._strikes: Dict[str, Deque[float]] = {}
        self._strike_totals: Dict[str, int] = {}
        self._quarantined: Dict[str, dict] = {}  # device -> {ts, reason}

    def record_strikes(self, device_ids, reason: str = "") -> list:
        """One verification strike against each device of the failed
        attempt; returns the devices this call NEWLY quarantined."""
        now = time.monotonic()
        newly = []
        with self._lock:
            for dev in device_ids:
                if not dev:
                    continue
                self._strike_totals[dev] = self._strike_totals.get(dev, 0) + 1
                q = self._strikes.setdefault(dev, deque())
                q.append(now)
                cutoff = now - self.window_s
                while q and q[0] < cutoff:
                    q.popleft()
                if (
                    self.strikes > 0
                    and len(q) >= self.strikes
                    and dev not in self._quarantined
                ):
                    self._quarantined[dev] = {
                        "ts": time.time(),
                        "reason": str(reason)[:300],
                        "strikes": len(q),
                    }
                    newly.append(dev)
        return newly

    def quarantined(self) -> dict:
        """device id -> {ts, reason, strikes}; no expiry -- operator clear
        only (a chip that corrupts results does not heal by waiting)."""
        with self._lock:
            return {d: dict(v) for d, v in self._quarantined.items()}

    def clear(self, device: str = "") -> list:
        """Operator clear (armadactl quarantine --clear): forget the
        quarantine AND the strike window for `device`, or every device
        when empty.  Returns the cleared ids; the next healthy re-probe
        may then promote."""
        with self._lock:
            targets = (
                [device]
                if device
                # BOTH maps: a device mid-window (struck but not yet
                # quarantined) must also reset, or the "fresh slate" clear
                # leaves it one strike from re-quarantine.
                else list({*self._quarantined, *self._strikes})
            )
            cleared = []
            for dev in targets:
                if dev in self._quarantined or dev in self._strikes:
                    cleared.append(dev)
                self._quarantined.pop(dev, None)
                self._strikes.pop(dev, None)
            return cleared

    def promotion_blocked(self) -> Optional[str]:
        """The watchdog/mesh promotion gate (core/watchdog
        set_promotion_gate): a non-None reason while ANY device is
        quarantined -- re-promotion targets the same backend whose answers
        the verification pass rejected, so it stays down until an operator
        clears it.  Conservative by design: a healthy-matmul probe cannot
        distinguish the corrupting chip from its neighbours."""
        with self._lock:
            if not self._quarantined:
                return None
            devs = ", ".join(sorted(self._quarantined))
        return f"device(s) quarantined by round verification: {devs}"

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "strike_threshold": self.strikes,
                "window_s": self.window_s,
                "strike_totals": dict(self._strike_totals),
                "quarantined": {
                    d: dict(v) for d, v in self._quarantined.items()
                },
            }


_DEVICE_QUARANTINE: Optional[DeviceQuarantine] = None


def device_quarantine() -> DeviceQuarantine:
    """The process-global device quarantine; first use registers its
    promotion gate with the watchdog (core/watchdog.set_promotion_gate) so
    the re-probe/restore loops consult it before promoting."""
    global _DEVICE_QUARANTINE
    if _DEVICE_QUARANTINE is None:
        _DEVICE_QUARANTINE = DeviceQuarantine()
        from armada_tpu.core.watchdog import set_promotion_gate

        set_promotion_gate(_DEVICE_QUARANTINE.promotion_blocked)
    return _DEVICE_QUARANTINE


def reset_device_quarantine(**kw) -> DeviceQuarantine:
    """Fresh scoreboard (tests); re-registers the promotion gate."""
    global _DEVICE_QUARANTINE
    _DEVICE_QUARANTINE = DeviceQuarantine(**kw)
    from armada_tpu.core.watchdog import set_promotion_gate

    set_promotion_gate(_DEVICE_QUARANTINE.promotion_blocked)
    return _DEVICE_QUARANTINE
