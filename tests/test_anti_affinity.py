"""Retry anti-affinity: retried jobs avoid nodes where attempts died
(scheduler.go:522-568 -- the reference injects node anti-affinity terms into
retried jobs so they don't bounce off the same bad node forever)."""

import pytest

from armada_tpu.core.config import SchedulingConfig
from armada_tpu.core.types import JobSpec, NodeSpec, Queue
from armada_tpu.models import run_scheduling_round
from tests.control_plane import ControlPlane
from armada_tpu.server import JobSubmitItem, QueueRecord

CFG = SchedulingConfig(shape_bucket=32)
F = CFG.resource_list_factory()


def test_kernel_honors_banned_nodes():
    # n0 is emptier (best-fit would pick it); the ban forces n1.
    nodes = [
        NodeSpec(id="n0", pool="default", total_resources=F.from_mapping({"cpu": "16", "memory": "64"})),
        NodeSpec(id="n1", pool="default", total_resources=F.from_mapping({"cpu": "8", "memory": "32"})),
    ]
    job = JobSpec(id="retry-1", queue="q", resources=F.from_mapping({"cpu": "2", "memory": "2"}))
    free = run_scheduling_round(
        CFG, pool="default", nodes=nodes, queues=[Queue("q")], queued_jobs=[job]
    )
    banned = run_scheduling_round(
        CFG,
        pool="default",
        nodes=nodes,
        queues=[Queue("q")],
        queued_jobs=[job],
        banned_nodes={"retry-1": ["n1"]},
    )
    # without bans, best-fit picks the fuller node n1; the ban flips it
    assert free.scheduled["retry-1"] == "n1"
    assert banned.scheduled["retry-1"] == "n0"

    both = run_scheduling_round(
        CFG,
        pool="default",
        nodes=nodes,
        queues=[Queue("q")],
        queued_jobs=[job],
        banned_nodes={"retry-1": ["n0", "n1"]},
    )
    assert both.scheduled == {} and "retry-1" in both.failed


def test_bans_only_affect_their_job():
    nodes = [
        NodeSpec(id="n0", pool="default", total_resources=F.from_mapping({"cpu": "8", "memory": "32"})),
    ]
    jobs = [
        JobSpec(id="banned", queue="q", resources=F.from_mapping({"cpu": "2", "memory": "2"})),
        JobSpec(id="fine", queue="q", resources=F.from_mapping({"cpu": "2", "memory": "2"})),
    ]
    out = run_scheduling_round(
        CFG,
        pool="default",
        nodes=nodes,
        queues=[Queue("q")],
        queued_jobs=jobs,
        banned_nodes={"banned": ["n0"]},
    )
    assert "fine" in out.scheduled and "banned" not in out.scheduled


@pytest.fixture(params=[False, True], ids=["legacy", "incremental"])
def _inc_cfg(request):
    import dataclasses

    return dataclasses.replace(CFG, incremental_problem_build=request.param)


def test_retry_avoids_bad_node_end_to_end(tmp_path, _inc_cfg):
    """A job whose pod sticks on one node retries on a DIFFERENT node --
    in incremental mode the retry ban routes the job through the feed's
    slow path (banned jobs join gang_jobs)."""
    cp = ControlPlane.build(
        tmp_path, config=_inc_cfg, executor_specs={"ex1": (2, "8", "32")},
        runtime_s=5.0,
    )
    cp.server.create_queue(QueueRecord("q"))
    ex = cp.executors[0]
    ex._pending_timeout = 10.0
    (jid,) = cp.server.submit_jobs(
        "q", "retry", [JobSubmitItem(resources={"cpu": "2", "memory": "2"})]
    )
    ex.run_once()
    cp.ingest()
    cp.scheduler.cycle()
    cp.ingest()
    ex.run_once()
    (pod,) = ex.cluster.pod_states()
    first_node = pod.node_id

    # wedge it: never starts; stuck-check returns the run with run_attempted
    # semantics preserved by the executor report (pending pods attempted=False
    # in the reference; force attempted here by letting it run first)
    ex.cluster.tick(0.5)
    ex.report_cycle()  # running reported -> run_attempted materializes
    cp.ingest()
    # then the executor dies with the pod running: lease expiry path
    ex.cluster.delete_pod(pod.run_id)
    cp.clock.advance(cp.config.executor_timeout_s + 10)
    res = cp.scheduler.cycle()
    assert res.events_by_kind().get("job_requeued") == 1

    # the executor returns; retry must land on the OTHER node
    import dataclasses

    snap = ex.snapshot()
    cp.db.upsert_executor(ex.id, snap.to_json(), snap.last_update_ns)
    # advance the fleet heartbeat stamp past the expiry window
    snap = dataclasses.replace(snap, last_update_ns=cp.scheduler.now_ns())
    cp.db.upsert_executor(ex.id, snap.to_json(), snap.last_update_ns)
    res2 = cp.scheduler.cycle()
    leases = [
        ev.job_run_leased
        for s in res2.published
        for ev in s.events
        if ev.WhichOneof("event") == "job_run_leased"
    ]
    assert len(leases) == 1
    assert leases[0].node_id != first_node
    cp.close()


def test_requeue_gate_fails_job_with_nowhere_left_to_run(tmp_path, _inc_cfg):
    """When anti-affinity bans cover every node the job could use, the requeue
    is converted into a terminal failure (scheduler.go:826-840
    addNodeAntiAffinitiesForAttemptedRunsIfSchedulable)."""
    import dataclasses

    cp = ControlPlane.build(
        tmp_path,
        config=_inc_cfg,
        # ex1 hosts the only node the job fits; ex2's node is too small.
        executor_specs={"ex1": (1, "8", "32"), "ex2": (1, "1", "1")},
        runtime_s=50.0,
    )
    cp.server.create_queue(QueueRecord("q"))
    ex1, ex2 = cp.executors
    (jid,) = cp.server.submit_jobs(
        "q", "gate", [JobSubmitItem(resources={"cpu": "2", "memory": "2"})]
    )
    ex1.run_once()
    ex2.run_once()
    cp.ingest()
    cp.scheduler.cycle()
    cp.ingest()
    ex1.run_once()
    (pod,) = ex1.cluster.pod_states()

    # run long enough to be reported RUNNING -> run_attempted materializes
    ex1.cluster.tick(0.5)
    ex1.report_cycle()
    cp.ingest()

    # ex1 dies with the pod running; ex2 stays live (fresh heartbeat)
    ex1.cluster.delete_pod(pod.run_id)
    cp.clock.advance(cp.config.executor_timeout_s + 10)
    snap2 = dataclasses.replace(ex2.snapshot(), last_update_ns=cp.scheduler.now_ns())
    cp.db.upsert_executor(ex2.id, snap2.to_json(), snap2.last_update_ns)

    res = cp.scheduler.cycle()
    kinds = res.events_by_kind()
    # the only node the retry could use is banned -> terminal failure, no requeue
    assert kinds.get("job_requeued") is None
    assert kinds.get("job_errors") == 1
    job = cp.jobdb.read_txn().get(jid)
    assert job.failed and not job.queued
    cp.close()


def test_gang_bans_apply_as_union_keeping_atomicity():
    """A retried gang shares the UNION of member ban sets: per-member keys
    would shatter the gang into independent singletons and allow a half-gang
    to schedule (all-or-nothing, gang_scheduler.go)."""
    nodes = [
        NodeSpec(id="n0", pool="default", total_resources=F.from_mapping({"cpu": "8", "memory": "32"})),
        NodeSpec(id="n1", pool="default", total_resources=F.from_mapping({"cpu": "4", "memory": "16"})),
    ]
    members = [
        JobSpec(id="m1", queue="q", gang_id="g1", gang_cardinality=2,
                resources=F.from_mapping({"cpu": "8", "memory": "2"})),
        JobSpec(id="m2", queue="q", gang_id="g1", gang_cardinality=2,
                resources=F.from_mapping({"cpu": "8", "memory": "2"})),
    ]
    # m1's attempt died on n0.  Without the union, m2's singleton sub-gang
    # would land on n0 while m1 stays queued -- a half-gang.
    out = run_scheduling_round(
        CFG,
        pool="default",
        nodes=nodes,
        queues=[Queue("q")],
        queued_jobs=members,
        banned_nodes={"m1": ["n0"]},
    )
    # Neither member may schedule alone (without the union, m2's singleton
    # sub-gang would be placed on n0).  The gang is blocked before a fit
    # attempt here (queue cap), so it is unscheduled rather than failed.
    assert out.scheduled == {}
