"""ctypes binding for the native partitioned event log (native/eventlog.cc).

The log is the framework's Pulsar equivalent (internal/common/pulsarutils in
the reference): ordered partitions, byte-offset message ids, replay from any
consumer position.  The shared library is built lazily from source with g++ the
first time it is needed, then cached next to this module.
"""

from __future__ import annotations

import ctypes
import fcntl
import os
import struct
import subprocess
from typing import Iterator, NamedTuple, Optional

from armada_tpu.analysis.tsan import make_lock

_HERE = os.path.dirname(os.path.abspath(__file__))
_SO_PATH = os.path.join(_HERE, "_eventlog.so")
_SRC = os.path.join(_HERE, os.pardir, "native", "eventlog.cc")

_build_lock = make_lock("eventlog.native_build")
_lib = None


def _load_lib() -> ctypes.CDLL:
    global _lib
    if _lib is not None:
        return _lib
    with _build_lock:
        if _lib is not None:
            return _lib
        if not os.path.exists(_SO_PATH) or (
            os.path.exists(_SRC)
            and os.path.getmtime(_SRC) > os.path.getmtime(_SO_PATH)
        ):
            # Single source of truth for compile flags: the native Makefile.
            # A cross-process flock keeps concurrent first-importers (e.g.
            # pytest-xdist workers) from racing the build output.
            with open(_SO_PATH + ".lock", "w") as lockf:
                fcntl.flock(lockf, fcntl.LOCK_EX)
                subprocess.run(
                    ["make", "-C", os.path.dirname(_SRC)], check=True,
                    stdout=subprocess.DEVNULL,
                )
        lib = ctypes.CDLL(_SO_PATH)
        lib.el_open.restype = ctypes.c_void_p
        lib.el_open.argtypes = [ctypes.c_char_p, ctypes.c_int]
        lib.el_close.argtypes = [ctypes.c_void_p]
        lib.el_num_partitions.restype = ctypes.c_int
        lib.el_num_partitions.argtypes = [ctypes.c_void_p]
        lib.el_append.restype = ctypes.c_int64
        lib.el_append.argtypes = [
            ctypes.c_void_p,
            ctypes.c_int,
            ctypes.c_char_p,
            ctypes.c_int,
            ctypes.c_char_p,
            ctypes.c_int,
        ]
        lib.el_end_offset.restype = ctypes.c_int64
        lib.el_end_offset.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.el_read.restype = ctypes.c_int64
        lib.el_read.argtypes = [
            ctypes.c_void_p,
            ctypes.c_int,
            ctypes.c_int64,
            ctypes.c_char_p,
            ctypes.c_int64,
            ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int64),
        ]
        lib.el_flush.restype = ctypes.c_int
        lib.el_flush.argtypes = [ctypes.c_void_p]
        lib.el_reset.restype = ctypes.c_int
        lib.el_reset.argtypes = [ctypes.c_void_p]
        lib.el_truncate.restype = ctypes.c_int
        lib.el_truncate.argtypes = [
            ctypes.c_void_p,
            ctypes.c_int,
            ctypes.c_int64,
        ]
        _lib = lib
    return _lib


class Message(NamedTuple):
    """One log record: `offset` is its id; `next_offset` the resume position."""

    partition: int
    offset: int
    next_offset: int
    key: bytes
    payload: bytes


class EventLog:
    """A durable partitioned append-only log (thread-safe appends)."""

    DEFAULT_PARTITIONS = 4

    def __init__(self, directory: str, num_partitions: Optional[int] = None):
        self._lib = _load_lib()
        os.makedirs(directory, exist_ok=True)
        # The partition count is a permanent property of a log (it keys the
        # jobset -> partition routing); persist it and reject mismatched opens
        # rather than silently hiding partitions or re-routing keys.
        # num_partitions=None ADOPTS an existing log's persisted count (the
        # restart path: `serve` without --log-partitions must reopen a log
        # created at any width), falling back to DEFAULT_PARTITIONS only for
        # a fresh directory.
        meta_path = os.path.join(directory, "META")
        if os.path.exists(meta_path):
            with open(meta_path) as f:
                existing = int(f.read().strip())
            if num_partitions is None:
                num_partitions = existing
            elif existing != num_partitions:
                raise ValueError(
                    f"event log at {directory} has {existing} partitions; "
                    f"requested {num_partitions}"
                )
        else:
            if num_partitions is None:
                num_partitions = self.DEFAULT_PARTITIONS
            with open(meta_path, "w") as f:
                f.write(str(num_partitions))
        self._handle = self._lib.el_open(directory.encode(), num_partitions)
        if not self._handle:
            raise OSError(f"failed to open event log at {directory}")
        self.directory = directory
        self.num_partitions = num_partitions
        self._closed = False

    def close(self) -> None:
        if not self._closed:
            self._lib.el_close(self._handle)
            self._closed = True

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def _check_open(self) -> None:
        # Guard every native call: the handle is freed memory after close().
        if self._closed:
            raise ValueError(f"event log at {self.directory} is closed")

    def append(self, partition: int, key: bytes, payload: bytes) -> int:
        """Append one record; returns its offset (the message id)."""
        self._check_open()
        off = self._lib.el_append(
            self._handle, partition, key, len(key), payload, len(payload)
        )
        if off < 0:
            raise OSError(f"append to partition {partition} failed")
        return off

    def end_offset(self, partition: int) -> int:
        self._check_open()
        return self._lib.el_end_offset(self._handle, partition)

    def flush(self) -> None:
        self._check_open()
        if self._lib.el_flush(self._handle) != 0:
            raise OSError("event log fsync failed")

    def reset(self) -> None:
        self._check_open()
        if self._lib.el_reset(self._handle) != 0:
            raise OSError("event log reset failed")

    def truncate(self, partition: int, offset: int) -> None:
        """Drop everything at/after `offset` in one partition (divergence
        recovery -- eventlog/replicator.py).  `offset` must be a record
        boundary at or before the current end."""
        self._check_open()
        if self._lib.el_truncate(self._handle, partition, offset) != 0:
            raise OSError(
                f"truncate of partition {partition} to {offset} failed"
            )

    def read_raw(
        self,
        partition: int,
        offset: int,
        max_bytes: int = 1 << 20,
        max_msgs: int = 1 << 30,
    ) -> tuple[bytes, int]:
        """Whole records from `offset` with their framing intact, plus the
        next read offset.  The zero-framing read for shard workers
        (ingest/shards.py): the Python record walk moves to whoever consumes
        the buffer (a converter subprocess), off this thread's GIL.  Empty
        bytes means caught up."""
        self._check_open()
        end = self.end_offset(partition)
        if offset >= end:
            return b"", offset  # caught up: skip the buffer allocation
        max_bytes = min(max_bytes, end - offset)
        while True:
            buf = ctypes.create_string_buffer(max_bytes)
            next_off = ctypes.c_int64(0)
            n = self._lib.el_read(
                self._handle,
                partition,
                offset,
                buf,
                max_bytes,
                max_msgs,
                ctypes.byref(next_off),
            )
            if n == -3:
                # One record larger than the buffer: grow and retry rather
                # than mis-reporting "caught up".
                max_bytes *= 4
                continue
            if n == -2:
                raise OSError(
                    f"corrupt record in partition {partition} at/after offset {offset}"
                )
            if n < 0:
                raise OSError(f"read from partition {partition} failed")
            return buf.raw[:n], next_off.value

    def read(
        self,
        partition: int,
        offset: int,
        max_bytes: int = 1 << 20,
        max_msgs: int = 1 << 30,
    ) -> list[Message]:
        """Read whole records from `offset`; empty list means caught up."""
        data, next_off = self.read_raw(partition, offset, max_bytes, max_msgs)
        n = len(data)
        out: list[Message] = []
        pos = 0
        rec_off = offset
        while pos < n:
            paylen, keylen = struct.unpack_from("<II", data, pos)
            key = bytes(data[pos + 8 : pos + 8 + keylen])
            payload = bytes(data[pos + 8 + keylen : pos + 8 + keylen + paylen])
            total = 8 + keylen + paylen + 4
            out.append(Message(partition, rec_off, rec_off + total, key, payload))
            pos += total
            rec_off += total
        assert n == 0 or rec_off == next_off
        return out

    def iter_from(self, partition: int, offset: int) -> Iterator[Message]:
        """Iterate all records currently in the partition from `offset`."""
        while True:
            batch = self.read(partition, offset)
            if not batch:
                return
            yield from batch
            offset = batch[-1].next_offset
