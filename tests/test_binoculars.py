"""Binoculars-lite tests: pod logs + node cordon next to the cluster.

Modeled on the reference's binoculars service (internal/binoculars/service/
logs.go, cordon.go): logs come straight from the cluster; cordoning a node
stops new placements there while running pods finish.
"""

import grpc
import pytest

from armada_tpu.executor.binoculars import Binoculars
from armada_tpu.rpc.client import BinocularsClient
from armada_tpu.rpc.server import make_server
from armada_tpu.server import JobSubmitItem, QueueRecord
from tests.control_plane import ControlPlane


@pytest.fixture
def stack(tmp_path):
    cp = ControlPlane.build(tmp_path, runtime_s=5.0)
    cp.server.create_queue(QueueRecord("q"))
    cluster = cp.executors[0].cluster
    server, port = make_server(binoculars=Binoculars(cluster))
    client = BinocularsClient(f"127.0.0.1:{port}")
    yield cp, cluster, client
    client.close()
    server.stop(None)
    cp.close()


def item(cpu="2"):
    return JobSubmitItem(resources={"cpu": cpu, "memory": "2"})


def test_logs_over_wire(stack):
    cp, cluster, client = stack
    (jid,) = cp.server.submit_jobs("q", "js", [item()])
    for ex in cp.executors:
        ex.run_once()
    cp.step()
    cluster.tick(1.0)

    log = client.logs(job_id=jid)
    assert "pod created for job" in log
    assert "container started" in log

    (pod,) = cluster.pod_states()
    assert client.logs(run_id=pod.run_id) == log

    with pytest.raises(grpc.RpcError) as e:
        client.logs(job_id="ghost")
    assert e.value.code() == grpc.StatusCode.NOT_FOUND


def test_failed_pod_log_carries_reason(stack):
    cp, cluster, client = stack
    (jid,) = cp.server.submit_jobs("q", "js", [item()])
    for ex in cp.executors:
        ex.run_once()
    cp.step()
    (pod,) = cluster.pod_states()
    cluster.fail_pod(pod.run_id, "disk exploded")
    assert "FAILED: disk exploded" in client.logs(job_id=jid)


def test_cordon_stops_new_placements(stack):
    cp, cluster, client = stack
    nodes = [n.id for n in cluster.node_specs()]
    client.cordon(nodes[0])
    assert next(
        n for n in cluster.node_specs() if n.id == nodes[0]
    ).unschedulable

    # snapshot propagates on the next heartbeat; everything lands on node 1
    ids = cp.server.submit_jobs("q", "js", [item() for _ in range(3)])
    for ex in cp.executors:
        ex.run_once()
    cp.step()
    placed = {p.node_id for p in cluster.pod_states()}
    assert placed == {nodes[1]}

    # uncordon restores the node
    client.uncordon(nodes[0])
    cp.server.submit_jobs("q", "js2", [item() for _ in range(3)])
    cp.step()
    cp.step()
    placed = {p.node_id for p in cluster.pod_states()}
    assert nodes[0] in placed

    with pytest.raises(grpc.RpcError):
        client.cordon("no-such-node")


def test_cordon_audit_labels_template_user(tmp_path):
    """Configured cordon labels land on the node with `<user>` templated to
    the authenticated principal (cordon.go AdditionalLabels +
    templateLabels:63-71); uncordon does not re-apply them."""
    cp = ControlPlane.build(tmp_path)
    cluster = cp.executors[0].cluster
    server, port = make_server(
        binoculars=Binoculars(
            cluster,
            cordon_labels={"armadaproject.io/cordoned-by": "<user>"},
        )
    )
    client = BinocularsClient(f"127.0.0.1:{port}", principal="ops-alice")
    try:
        node_id = cluster.node_specs()[0].id
        client.cordon(node_id)
        node = next(n for n in cluster.node_specs() if n.id == node_id)
        assert node.unschedulable
        assert node.labels["armadaproject.io/cordoned-by"] == "ops-alice"
        client.uncordon(node_id)
        node = next(n for n in cluster.node_specs() if n.id == node_id)
        assert not node.unschedulable
    finally:
        client.close()
        server.stop(None)
        cp.close()


def test_cordon_requires_permission(tmp_path):
    """A closed authorizer rejects cordon for principals lacking
    CORDON_NODES (cordon.go:48-51 -> PermissionDenied) and admits one that
    has it."""
    from armada_tpu.server.auth import ActionAuthorizer, Permission, Principal
    from armada_tpu.server.authn import MultiAuthenticator

    class _Static:
        def __init__(self, principal):
            self._p = principal

        def authenticate(self, meta):
            return self._p

    cp = ControlPlane.build(tmp_path)
    cluster = cp.executors[0].cluster
    node_id = cluster.node_specs()[0].id

    def serve_as(principal):
        return make_server(
            binoculars=Binoculars(cluster),
            binoculars_authorizer=ActionAuthorizer(open_by_default=False),
            authenticator=MultiAuthenticator([_Static(principal)]),
        )

    server, port = serve_as(Principal(name="nobody"))
    client = BinocularsClient(f"127.0.0.1:{port}")
    try:
        with pytest.raises(grpc.RpcError) as e:
            client.cordon(node_id)
        assert e.value.code() == grpc.StatusCode.PERMISSION_DENIED
    finally:
        client.close()
        server.stop(None)
    server, port = serve_as(
        Principal(
            name="ops", permissions=frozenset({Permission.CORDON_NODES})
        )
    )
    client = BinocularsClient(f"127.0.0.1:{port}")
    try:
        client.cordon(node_id)
        assert next(
            n for n in cluster.node_specs() if n.id == node_id
        ).unschedulable
    finally:
        client.close()
        server.stop(None)
        cp.close()
