"""Lookout web UI: a single-page jobs dashboard over the lookout query stack.

Plays the role of the reference's lookout UI (internal/lookoutui, React/TS
~18k LoC): a jobs table with filtering, grouping with per-state counts, job
details with runs and errors, drilldown navigation, a live log viewer,
server-side saved views, URL-state routing, and an OIDC login flow -- a
hand-rolled module SPA (armada_tpu/lookout/ui/*.js) + JSON endpoints on a
stdlib HTTP server, backed by LookoutQueries (repository/getjobs.go,
groupjobs.go semantics).

Endpoints:
  GET /                  the app shell (ui/index.html + boot config)
  GET /static/*          the SPA's modules and stylesheet
  GET /api/jobs?...      filtered page of jobs + total count
  GET /api/groups?by=X   grouped counts with per-state breakdown
  GET /api/job/{id}      job details incl. runs
  GET /api/overview      global state counts
  GET /api/me            the authenticated principal (identity chip)
  GET /api/logs?job=&run=   pod logs via binoculars (logs.go:39-43); 501
                            when the UI has no binoculars wired
  GET/POST /api/views    server-side saved views (lookout DB saved_view
                            table; the reference UI's server-backed views)
  DELETE /api/views/{name}
  GET /login /oauth/callback /logout   the OIDC authorization-code flow
      (lookout/oidc.py; the browser analog of
      internal/lookoutui/src/oidcAuth/OidcAuthProvider.tsx)

Drilldown: grouping by queue and clicking a row descends to jobsets within
that queue; clicking a jobset lands on its job list; a job opens details
with runs and a live log viewer -- queue -> group -> job -> runs -> logs
without the CLI (App.tsx navigation parity).

State colors are the validated categorical theme (dataviz skill reference
palette; adjacency validated in both modes: CVD dE 9.1 light / 8.4 dark);
identity is never color-alone -- every segment and chip carries the state name
and count as text, and the table is the primary view.
"""

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from pathlib import Path
from typing import Callable, Optional
from urllib.parse import parse_qs, quote, unquote, urlparse

from armada_tpu.lookout.db import JOB_STATES
from armada_tpu.lookout.oidc import (
    OidcFlowError,
    OidcSessionManager,
    OidcWebConfig,
)
from armada_tpu.lookout.queries import JobFilter, JobOrder, LookoutQueries

# Fixed state -> hue assignment in the theme's validated adjacency order
# (the meter renders segments in exactly this order).
STATE_ORDER = (
    "RUNNING", "PREEMPTED", "LEASED", "QUEUED",
    "PENDING", "SUCCEEDED", "CANCELLED", "FAILED",
)
STATE_COLORS_LIGHT = {
    "RUNNING": "#2a78d6", "PREEMPTED": "#eb6834", "LEASED": "#1baf7a",
    "QUEUED": "#eda100", "PENDING": "#e87ba4", "SUCCEEDED": "#008300",
    "CANCELLED": "#4a3aa7", "FAILED": "#e34948",
}
STATE_COLORS_DARK = {
    "RUNNING": "#3987e5", "PREEMPTED": "#d95926", "LEASED": "#199e70",
    "QUEUED": "#c98500", "PENDING": "#d55181", "SUCCEEDED": "#008300",
    "CANCELLED": "#9085e9", "FAILED": "#e66767",
}

_UI_DIR = Path(__file__).parent / "ui"
_CONTENT_TYPES = {
    ".js": "text/javascript; charset=utf-8",
    ".css": "text/css; charset=utf-8",
    ".html": "text/html; charset=utf-8",
}


def _render_page() -> str:
    options = "".join(f'<option value="{s}">{s.lower()}</option>' for s in JOB_STATES)
    boot = json.dumps(
        {
            "colors": {"light": STATE_COLORS_LIGHT, "dark": STATE_COLORS_DARK},
            "order": list(STATE_ORDER),
        }
    )
    template = (_UI_DIR / "index.html").read_text()
    return template.replace("__STATE_OPTIONS__", options).replace(
        "__BOOT_JSON__", boot
    )


def _load_static() -> dict[str, tuple[bytes, str]]:
    """The SPA's modules, read once at startup (they are package data; a
    dev editing them restarts the process like any Python change)."""
    out = {}
    for path in _UI_DIR.iterdir():
        if path.name == "index.html" or path.suffix not in _CONTENT_TYPES:
            continue
        out["/static/" + path.name] = (
            path.read_bytes(),
            _CONTENT_TYPES[path.suffix],
        )
    return out


def _filters_from_query(qs: dict) -> list[JobFilter]:
    filters = []
    if qs.get("queue"):
        filters.append(JobFilter("queue", qs["queue"][0], "contains"))
    if qs.get("jobset"):
        filters.append(JobFilter("jobset", qs["jobset"][0], "contains"))
    if qs.get("state"):
        filters.append(JobFilter("state", qs["state"][0]))
    # annotation filters: ann.<key>=<value> (exact), ann.<key>=* (exists),
    # annmatch=<mode> applies one of the querybuilder match modes to all
    # annotation terms (querybuilder.go:320-346 parity).
    mode = qs.get("annmatch", ["exact"])[0]
    for param, values in qs.items():
        if param.startswith("ann.") and values:
            key = param[4:]
            if values[0] == "*":
                filters.append(
                    JobFilter("annotation", None, "exists", annotation_key=key)
                )
            else:
                filters.append(
                    JobFilter("annotation", values[0], mode, annotation_key=key)
                )
    return filters


class _Handler(BaseHTTPRequestHandler):
    server_version = "armada-tpu-lookout/1"

    def log_message(self, fmt, *args):
        pass

    def _json(self, obj, status=200, extra_headers=()):
        body = json.dumps(obj).encode()
        self.send_response(status)
        for k, v in extra_headers:
            self.send_header(k, v)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _redirect(self, location: str, set_cookie: Optional[str] = None):
        self.send_response(302)
        self.send_header("Location", location)
        if set_cookie:
            self.send_header("Set-Cookie", set_cookie)
        self.send_header("Content-Length", "0")
        self.end_headers()

    def _redirect_uri(self) -> str:
        """The callback URL as the browser sees this server.  X-Forwarded-*
        are honoured only behind a declared reverse proxy (trust_proxy) --
        on a directly exposed server they are client-controlled and could
        steer the IdP redirect_uri (ADVICE r4)."""
        srv: "LookoutWebUI" = self.server.owner  # type: ignore[attr-defined]
        host = self.headers.get("Host", "127.0.0.1")
        proto = "http"
        if getattr(srv, "trust_proxy", False):
            host = self.headers.get("X-Forwarded-Host") or host
            proto = self.headers.get("X-Forwarded-Proto", proto)
        return f"{proto}://{host}/oauth/callback"

    def _authed(self) -> Optional["object"]:
        """Gate every request on the server's authenticator chain (the same
        server/authn.py chain the gRPC/REST transports use; None = open dev
        default).  Precedence: OIDC session cookie (validated through the
        chain after transparent refresh), then plain header credentials
        (bearer / basic).  Returns the principal (or an anonymous truthy
        marker when no chain is configured); writes the 401/302 and returns
        None on failure."""
        srv: "LookoutWebUI" = self.server.owner  # type: ignore[attr-defined]
        if srv.authenticator is None:
            return object()  # open dev default
        if srv.oidc is not None:
            principal = srv.oidc.authenticate(self.headers)
            if principal is not None:
                self.session_principal = principal
                return principal
        from armada_tpu.server.authn import authenticate_http_headers

        principal, reason = authenticate_http_headers(
            srv.authenticator, self.headers
        )
        if principal is not None:
            return principal
        path = urlparse(self.path).path
        if (
            srv.oidc is not None
            and self.command == "GET"
            and not path.startswith("/api/")
        ):
            # page navigation: bounce through the login flow and come back
            self._redirect("/login?next=" + quote(self.path, safe=""))
            return None
        extra = []
        body = {"error": f"unauthenticated: {reason}"}
        if srv.oidc is not None:
            body["login"] = "/login"  # the SPA's api.js follows this
        else:
            extra.append(
                ("WWW-Authenticate", 'Basic realm="armada-tpu lookout"')
            )
        self._json(body, 401, extra_headers=extra)
        return None

    def _handle_oidc_routes(self, path: str, qs: dict) -> bool:
        """Login-flow routes run BEFORE authentication (they exist to
        establish it).  Returns True when the request was handled."""
        srv: "LookoutWebUI" = self.server.owner  # type: ignore[attr-defined]
        if path == "/login" and self.command == "GET":
            if srv.oidc is None:
                self._json({"error": "no OIDC login flow configured"}, 404)
                return True
            nxt = qs.get("next", ["/"])[0]
            self._redirect(srv.oidc.login_redirect(nxt, self._redirect_uri()))
            return True
        if path == "/oauth/callback" and self.command == "GET":
            if srv.oidc is None:
                self._json({"error": "no OIDC login flow configured"}, 404)
                return True
            params = {k: v[0] for k, v in qs.items()}
            try:
                nxt, cookie, _principal = srv.oidc.handle_callback(
                    params, self._redirect_uri()
                )
            except OidcFlowError as e:
                self._json({"error": str(e), "login": "/login"}, 401)
                return True
            self._redirect(nxt, set_cookie=cookie)
            return True
        if path == "/logout":
            # POST-only: the session cookie is SameSite=Lax, which rides
            # top-level cross-site GET navigations -- a GET logout would let
            # any page force-kill the victim's session (CSRF).  auth.js
            # POSTs and follows the returned redirect.
            if srv.oidc is None:
                self._json({"error": "no OIDC login flow configured"}, 404)
                return True
            if self.command != "POST":
                self._json(
                    {"error": "logout requires POST (CSRF protection)"}, 405
                )
                return True
            target, clearing = srv.oidc.logout(self.headers)
            self._json(
                {"redirect": target},
                extra_headers=[("Set-Cookie", clearing)],
            )
            return True
        return False

    def do_GET(self):  # noqa: N802
        srv: "LookoutWebUI" = self.server.owner  # type: ignore[attr-defined]
        parsed = urlparse(self.path)
        path = parsed.path
        qs = parse_qs(parsed.query)
        self.session_principal = None
        if self._handle_oidc_routes(path, qs):
            return
        principal = self._authed()
        if principal is None:
            return
        q = srv.queries
        try:
            if path == "/":
                body = srv.page.encode()
                self.send_response(200)
                self.send_header("Content-Type", "text/html; charset=utf-8")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
            elif path in srv.static:
                body, ctype = srv.static[path]
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
            elif path == "/api/me":
                name = getattr(principal, "name", None)
                groups = list(getattr(principal, "groups", ()) or ())
                self._json(
                    {
                        "name": name,
                        "groups": groups,
                        # logout link only makes sense for cookie sessions
                        "session": self.session_principal is not None,
                    }
                )
            elif path == "/api/jobs":
                filters = _filters_from_query(qs)
                order = JobOrder(
                    field=qs.get("order", ["submitted"])[0],
                    direction=qs.get("dir", ["DESC"])[0],
                )
                skip = max(0, int(qs.get("skip", ["0"])[0]))
                take = max(1, min(int(qs.get("take", ["50"])[0]), 500))
                self._json(
                    {
                        "jobs": q.get_jobs(filters, order, skip=skip, take=take),
                        "total": q.count_jobs(filters),
                    }
                )
            elif path == "/api/groups":
                by = qs.get("by", ["queue"])[0]
                take = max(1, min(int(qs.get("take", ["100"])[0]), 500))
                aggs = tuple(
                    qs.get("aggs", ["state"])[0].split(",")
                ) if qs.get("aggs", ["state"])[0] else ()
                # one extra row detects truncation
                groups = q.group_jobs(
                    by,
                    _filters_from_query(qs),
                    aggregates=aggs,
                    take=take + 1,
                    annotation_key=qs.get("key", [""])[0],
                )
                truncated = len(groups) > take
                self._json({"groups": groups[:take], "truncated": truncated})
            elif path == "/api/overview":
                groups = q.group_jobs("state", ())
                states = {g["group"]: g["count"] for g in groups}
                self._json({"states": states})
            elif path.startswith("/api/job/"):
                job_id = path[len("/api/job/") :]
                details = q.get_job_details(job_id)
                if details is None:
                    self._json({"error": f"no job {job_id}"}, 404)
                else:
                    # why-(not)-scheduled forensics (scheduler/reports +
                    # the explain pass's reason codes).  Best-effort: a
                    # follower that cannot reach the leader still serves
                    # the lookout rows.
                    from armada_tpu.scheduler.reports import try_job_report

                    report = try_job_report(srv.reports, job_id)
                    if report is not None:
                        details["scheduling_report"] = report
                    self._json(details)
            elif path == "/api/logs":
                if srv.logs_of is None:
                    self._json(
                        {"error": "no binoculars wired (serve --binoculars-url)"},
                        501,
                    )
                    return
                job_id = qs.get("job", [""])[0]
                run_id = qs.get("run", [""])[0]
                try:
                    self._json(
                        {"log": srv.logs_of(job_id=job_id, run_id=run_id)}
                    )
                except KeyError as exc:
                    self._json({"error": str(exc)}, 404)
                except Exception as exc:  # cluster-side failure, not a 500
                    self._json({"error": f"binoculars: {exc}"}, 502)
            elif path == "/api/views":
                self._json({"views": q.list_views()})
            else:
                self._json({"error": "not found"}, 404)
        except (ValueError, KeyError) as exc:
            self._json({"error": str(exc)}, 400)

    def _operator_action(self, srv, principal, path: str) -> None:
        """Shared prologue + error mapping for the SPA's operator actions:
        submit-server presence, body parse, principal coercion, and the
        AuthorizationError->403 / SubmitError->400 mapping live ONCE here."""
        if srv.submit is None:
            self._json(
                {"error": "no submit server wired (read-only UI)"}, 501
            )
            return
        length = int(self.headers.get("Content-Length", "0"))
        body = json.loads(self.rfile.read(length) or b"{}")
        from armada_tpu.server.auth import AuthorizationError, Principal
        from armada_tpu.server.submit import SubmitError

        p = principal if isinstance(principal, Principal) else Principal()
        job_ids = [str(j) for j in body.get("job_ids", [])]
        if path.startswith("/api/jobs/") and not job_ids:
            # SubmitServer treats empty ids as a JOBSET-wide action
            # (reprioritise semantics, submit.py); the per-job surface must
            # never widen a click into a mass action.  The /api/jobsets/*
            # endpoints are the deliberate mass-action surface.
            self._json({"error": "job_ids must be non-empty"}, 400)
            return
        try:
            if path == "/api/jobsets/cancel":
                srv.submit.cancel_jobset(
                    str(body["queue"]),
                    str(body["jobset"]),
                    states=[str(s) for s in body.get("states", [])],
                    reason=str(body.get("reason", "jobset cancelled via UI")),
                    principal=p,
                )
            elif path == "/api/jobsets/reprioritize":
                srv.submit.reprioritize_jobs(
                    str(body["queue"]),
                    str(body["jobset"]),
                    int(body["priority"]),
                    [],  # empty = the whole jobset (submit.py:277)
                    principal=p,
                )
            elif path == "/api/jobs/cancel":
                srv.submit.cancel_jobs(
                    str(body["queue"]),
                    str(body["jobset"]),
                    job_ids,
                    reason=str(body.get("reason", "cancelled via UI")),
                    principal=p,
                )
            else:
                srv.submit.reprioritize_jobs(
                    str(body["queue"]),
                    str(body["jobset"]),
                    int(body["priority"]),
                    job_ids,
                    principal=p,
                )
        except AuthorizationError as exc:
            self._json({"error": str(exc)}, 403)
            return
        except SubmitError as exc:
            self._json({"error": str(exc)}, 400)
            return
        self._json({"ok": True})

    def do_POST(self):  # noqa: N802
        self.session_principal = None
        parsed = urlparse(self.path)
        if self._handle_oidc_routes(parsed.path, parse_qs(parsed.query)):
            return
        principal = self._authed()
        if principal is None:
            return
        srv: "LookoutWebUI" = self.server.owner  # type: ignore[attr-defined]
        path = parsed.path
        try:
            if path == "/api/views":
                length = int(self.headers.get("Content-Length", "0"))
                body = json.loads(self.rfile.read(length) or b"{}")
                name = str(body.get("name", ""))
                payload = json.dumps(body.get("payload", {}))
                srv.queries.save_view(name, payload, now_ns=time.time_ns())
                self._json({"ok": True})
            elif path in (
                "/api/jobsets/cancel",
                "/api/jobsets/reprioritize",
                "/api/jobs/cancel",
                "/api/jobs/reprioritize",
            ):
                # Operator actions from the SPA (the reference UI's
                # Cancel/Reprioritise dialogs, per-job and jobset-wide) --
                # routed through the SAME SubmitServer the gRPC verbs use,
                # so queue ACLs / permissions hold identically.
                self._operator_action(srv, principal, path)
            else:
                self._json({"error": "not found"}, 404)
        except (ValueError, KeyError) as exc:
            self._json({"error": str(exc)}, 400)

    def do_DELETE(self):  # noqa: N802
        self.session_principal = None
        if self._authed() is None:
            return
        srv: "LookoutWebUI" = self.server.owner  # type: ignore[attr-defined]
        path = urlparse(self.path).path
        if path.startswith("/api/views/"):
            name = unquote(path[len("/api/views/") :])
            if srv.queries.delete_view(name):
                self._json({"ok": True})
            else:
                self._json({"error": f"no view {name}"}, 404)
        else:
            self._json({"error": "not found"}, 404)


class LookoutWebUI:
    """Serves the dashboard + JSON API on `port` (0 = pick a free one).

    `logs_of(job_id=..., run_id=...) -> str` supplies pod logs -- wire a
    BinocularsClient.logs (rpc/client.py) or an in-process
    executor.binoculars.Binoculars.logs; None disables the log viewer.

    `authenticator`: a server/authn.py chain gating the page AND the JSON
    API; None keeps the dev default (the page trusts its bind address).

    `oidc`: an OidcWebConfig (or a pre-built OidcSessionManager, for tests
    that inject a clock) enabling the browser login flow -- /login bounces
    to the IdP, /oauth/callback exchanges the code and mints an HttpOnly
    session, and every session token re-validates through `authenticator`.
    Without it, browsers fall back to a Basic challenge."""

    def __init__(
        self,
        queries: LookoutQueries,
        port: int = 0,
        host: str = "127.0.0.1",
        logs_of: Optional[Callable] = None,
        authenticator=None,
        oidc=None,
        submit=None,
        trust_proxy: bool = False,
        reports=None,
    ):
        # `submit`: a server.submit.SubmitServer enabling the UI's operator
        # actions (cancel / reprioritise, the reference UI's dialogs); None
        # keeps the UI read-only (501 on the action endpoints).
        # `trust_proxy`: honour X-Forwarded-Host/Proto when building the
        # OIDC redirect_uri + cookie Secure flag.  Off by default -- on a
        # directly exposed server those headers are client-controlled.
        self.queries = queries
        self.logs_of = logs_of
        self.submit = submit
        # Optional SchedulingReportsRepository (or its leader-proxying
        # wrapper): job details gain the scheduler's why-(not)-scheduled
        # report, incl. the explain pass's reason codes (models/explain.py).
        self.reports = reports
        self.authenticator = authenticator
        self.trust_proxy = trust_proxy
        if oidc is not None and authenticator is None:
            # applies to the pre-built OidcSessionManager form too: a wired
            # session manager with no chain would leave _authed()'s open dev
            # default in front of it (ADVICE r4).
            raise ValueError(
                "OIDC login needs an authenticator chain to validate "
                "tokens against (auth.oidc in the server config)"
            )
        if isinstance(oidc, OidcWebConfig):
            oidc = OidcSessionManager(oidc, authenticator)
        self.oidc: Optional[OidcSessionManager] = oidc
        self.page = _render_page()
        self.static = _load_static()
        self._httpd = ThreadingHTTPServer((host, port), _Handler)
        self._httpd.owner = self  # type: ignore[attr-defined]
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._httpd.shutdown()
        self._thread.join(timeout=5)
        self._httpd.server_close()
