"""An in-process fake kube-apiserver covering the endpoints the
KubernetesClusterContext uses (create/delete/list pods, list nodes, pod
logs) plus coordination.k8s.io/v1 Leases with resourceVersion optimistic
concurrency (for KubernetesLeaseLeaderController).  Test code mutates
`pods`/`nodes` directly to simulate kubelet behavior (phase transitions,
node drains)."""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse


class FakeKubeApi:
    def __init__(self):
        self.lock = threading.Lock()
        # (namespace, name) -> pod manifest dict (with status injected)
        self.pods: dict = {}
        self.services: dict = {}
        self.ingresses: dict = {}
        self.nodes: list = []
        self.logs: dict = {}  # (namespace, name) -> str
        self.requests: list = []  # (method, path) log for assertions
        # (namespace, name) -> lease dict with metadata.resourceVersion
        self.leases: dict = {}
        self._rv = 0
        handler = self._make_handler()
        self._httpd = ThreadingHTTPServer(("127.0.0.1", 0), handler)
        self.port = self._httpd.server_address[1]
        self.url = f"http://127.0.0.1:{self.port}"
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True
        )
        self._thread.start()

    def stop(self):
        self._httpd.shutdown()
        self._thread.join(timeout=5)
        self._httpd.server_close()

    def add_node(self, name, cpu="8", memory="32", labels=None, taints=None,
                 unschedulable=False):
        self.nodes.append(
            {
                "metadata": {"name": name, "labels": {**(labels or {})}},
                "spec": {
                    "taints": list(taints or ()),
                    "unschedulable": unschedulable,
                },
                "status": {"allocatable": {"cpu": cpu, "memory": memory}},
            }
        )

    def set_phase(self, namespace, name, phase, message=""):
        with self.lock:
            pod = self.pods[(namespace, name)]
            pod["status"] = {"phase": phase, "message": message}

    def _make_handler(api):  # noqa: N805 (closure over the fake)
        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def _json(self, status, obj):
                body = json.dumps(obj).encode()
                self.send_response(status)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _text(self, status, text):
                body = text.encode()
                self.send_response(status)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _selected(self, pods, query):
                """Apply k8s labelSelector semantics (bare key = exists,
                k=v = equality)."""
                sel = parse_qs(query).get("labelSelector", [""])[0]
                terms = [t for t in sel.split(",") if t]
                out = []
                for p in pods:
                    labels = p["metadata"].get("labels", {})
                    ok = True
                    for term in terms:
                        if "=" in term:
                            k, v = term.split("=", 1)
                            ok = ok and labels.get(k) == v
                        else:
                            ok = ok and term in labels
                    if ok:
                        out.append(p)
                return out

            def _lease_key(self, parts):
                # apis/coordination.k8s.io/v1/namespaces/{ns}/leases[/{name}]
                if (
                    len(parts) >= 6
                    and parts[0] == "apis"
                    and parts[1] == "coordination.k8s.io"
                    and parts[3] == "namespaces"
                    and parts[5] == "leases"
                ):
                    ns = parts[4]
                    name = parts[6] if len(parts) > 6 else None
                    return ns, name
                return None

            def do_GET(self):  # noqa: N802
                parsed = urlparse(self.path)
                api.requests.append(("GET", parsed.path))
                parts = parsed.path.strip("/").split("/")
                lk = self._lease_key(parts)
                if lk is not None and lk[1] is not None:
                    with api.lock:
                        lease = api.leases.get(lk)
                    if lease is None:
                        self._json(404, {"message": "not found"})
                    else:
                        self._json(200, lease)
                    return
                if parsed.path == "/api/v1/nodes":
                    items = list(api.nodes)
                    qs = parse_qs(parsed.query)
                    for sel in qs.get("labelSelector", []):
                        for term in sel.split(","):
                            k, _, v = term.partition("=")
                            items = [
                                n
                                for n in items
                                if n["metadata"].get("labels", {}).get(k) == v
                            ]
                    self._json(200, {"items": items})
                elif parsed.path == "/api/v1/pods":
                    with api.lock:
                        pods = list(api.pods.values())
                    self._json(200, {"items": self._selected(pods, parsed.query)})
                elif len(parts) == 5 and parts[-1] == "pods":
                    ns = parts[3]
                    with api.lock:
                        pods = [
                            p for (pns, _), p in api.pods.items() if pns == ns
                        ]
                    self._json(200, {"items": self._selected(pods, parsed.query)})
                elif len(parts) == 6 and parts[-2] == "pods":
                    ns, name = parts[3], parts[5]
                    with api.lock:
                        pod = api.pods.get((ns, name))
                    if pod is None:
                        self._json(404, {"message": "not found"})
                    else:
                        self._json(200, pod)
                elif len(parts) == 7 and parts[-1] == "log":
                    ns, name = parts[3], parts[5]
                    log = api.logs.get((ns, name))
                    if log is None:
                        self._json(404, {"message": "not found"})
                    else:
                        self._text(200, log)
                else:
                    self._json(404, {"message": "not found"})

            def do_POST(self):  # noqa: N802
                parsed = urlparse(self.path)
                api.requests.append(("POST", parsed.path))
                parts = parsed.path.strip("/").split("/")
                length = int(self.headers.get("Content-Length", 0))
                body = json.loads(self.rfile.read(length)) if length else {}
                lk = self._lease_key(parts)
                if lk is not None and lk[1] is None:
                    ns = lk[0]
                    name = body["metadata"]["name"]
                    with api.lock:
                        if (ns, name) in api.leases:
                            self._json(409, {"message": "already exists"})
                            return
                        api._rv += 1
                        body["metadata"]["namespace"] = ns
                        body["metadata"]["resourceVersion"] = str(api._rv)
                        api.leases[(ns, name)] = body
                    self._json(201, body)
                    return
                if len(parts) == 5 and parts[-1] == "pods":
                    ns = parts[3]
                    name = body["metadata"]["name"]
                    with api.lock:
                        if (ns, name) in api.pods:
                            self._json(409, {"message": "already exists"})
                            return
                        body["metadata"]["namespace"] = ns
                        body["metadata"].setdefault("uid", f"uid-{name}")
                        body.setdefault("status", {"phase": "Pending"})
                        api.pods[(ns, name)] = body
                    self._json(201, body)
                elif len(parts) == 5 and parts[-1] == "services":
                    ns = parts[3]
                    name = body["metadata"]["name"]
                    with api.lock:
                        if (ns, name) in api.services:
                            self._json(409, {"message": "already exists"})
                            return
                        # NodePort allocation like a real apiserver
                        port_no = 30000 + len(api.services)
                        for entry in body.get("spec", {}).get("ports", ()):
                            if body["spec"].get("type") == "NodePort":
                                entry.setdefault("nodePort", port_no)
                                port_no += 1
                        api.services[(ns, name)] = body
                    self._json(201, body)
                elif (
                    len(parts) == 6
                    and parts[:2] == ["apis", "networking.k8s.io"]
                    and parts[-1] == "ingresses"
                ):
                    ns = parts[4]
                    name = body["metadata"]["name"]
                    with api.lock:
                        if (ns, name) in api.ingresses:
                            self._json(409, {"message": "already exists"})
                            return
                        api.ingresses[(ns, name)] = body
                    self._json(201, body)
                else:
                    self._json(404, {"message": "not found"})

            def do_PUT(self):  # noqa: N802
                parsed = urlparse(self.path)
                api.requests.append(("PUT", parsed.path))
                parts = parsed.path.strip("/").split("/")
                length = int(self.headers.get("Content-Length", 0))
                body = json.loads(self.rfile.read(length)) if length else {}
                lk = self._lease_key(parts)
                if lk is not None and lk[1] is not None:
                    with api.lock:
                        cur = api.leases.get(lk)
                        if cur is None:
                            self._json(404, {"message": "not found"})
                            return
                        # optimistic concurrency: stale resourceVersion -> 409
                        sent_rv = body.get("metadata", {}).get("resourceVersion")
                        if sent_rv != cur["metadata"]["resourceVersion"]:
                            self._json(409, {"message": "conflict"})
                            return
                        api._rv += 1
                        body["metadata"]["resourceVersion"] = str(api._rv)
                        api.leases[lk] = body
                    self._json(200, body)
                    return
                self._json(404, {"message": "not found"})

            def do_PATCH(self):  # noqa: N802
                parsed = urlparse(self.path)
                api.requests.append(("PATCH", parsed.path))
                parts = parsed.path.strip("/").split("/")
                length = int(self.headers.get("Content-Length", 0))
                body = json.loads(self.rfile.read(length)) if length else {}
                # strategic-merge node patch (cordon: spec.unschedulable +
                # metadata.labels)
                if len(parts) == 4 and parts[2] == "nodes":
                    name = parts[3]
                    with api.lock:
                        node = next(
                            (
                                n
                                for n in api.nodes
                                if n["metadata"]["name"] == name
                            ),
                            None,
                        )
                        if node is None:
                            self._json(404, {"message": "not found"})
                            return
                        if "unschedulable" in body.get("spec", {}):
                            node.setdefault("spec", {})["unschedulable"] = (
                                body["spec"]["unschedulable"]
                            )
                        for k, v in (
                            body.get("metadata", {}).get("labels", {}).items()
                        ):
                            node["metadata"].setdefault("labels", {})[k] = v
                    self._json(200, node)
                    return
                self._json(404, {"message": "not found"})

            def do_DELETE(self):  # noqa: N802
                parsed = urlparse(self.path)
                api.requests.append(("DELETE", parsed.path))
                parts = parsed.path.strip("/").split("/")
                if len(parts) == 6 and parts[-2] == "pods":
                    ns, name = parts[3], parts[5]
                    with api.lock:
                        if (ns, name) not in api.pods:
                            self._json(404, {"message": "not found"})
                            return
                        del api.pods[(ns, name)]
                    self._json(200, {})
                elif len(parts) == 6 and parts[-2] == "services":
                    ns, name = parts[3], parts[5]
                    with api.lock:
                        if (ns, name) not in api.services:
                            self._json(404, {"message": "not found"})
                            return
                        del api.services[(ns, name)]
                    self._json(200, {})
                elif len(parts) == 7 and parts[-2] == "ingresses":
                    ns, name = parts[4], parts[6]
                    with api.lock:
                        if (ns, name) not in api.ingresses:
                            self._json(404, {"message": "not found"})
                            return
                        del api.ingresses[(ns, name)]
                    self._json(200, {})
                else:
                    self._json(404, {"message": "not found"})

        return Handler
